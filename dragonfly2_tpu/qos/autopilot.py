"""SLO autopilot: burn-rate verdicts feed back into admission
(DESIGN.md §26; closes the §23 telemetry loop the ROADMAP asked for).

The loop: a declared latency SLO (``telemetry.slos``) burns on BOTH
multi-window burn rates → the autopilot **tightens** — one level per
breached evaluation, each level raising the admission controller's shed
bias (the shard sheds low bands earlier) and scaling over-quota
tenants' announce-rate caps down (``TenantAccounting.set_cap_factor``).
Recovery **relaxes** with hysteresis: only after ``relax_after``
consecutive healthy evaluations does the level step back down, so a
flapping SLO cannot oscillate the shed floor.

Replay-equals-live (the §23 discipline, taken one step further): the
live decision path is *journal-driven* — every evaluation ingests a
metric-journal snapshot (``MetricJournal.last_snapshot``) through the
same ``SLOEngine.ingest_snapshot``/``evaluate`` pair replay uses, and
the level transition is a pure function of the resulting breach-verdict
sequence.  ``SLOAutopilot.replay`` therefore reproduces the live
decision sequence EXACTLY from the journal alone (drift 0), which is
the drill's acceptance bar — and what makes a post-incident "why did
the autopilot shed?" answerable from artifacts.

Every level change closes one ``scheduler/qos.autopilot`` span
(DF016-inventoried) carrying from/to levels and the triggering verdict.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.metrics import Registry
from ..utils.slo import SLOEngine
from ..utils.tracing import default_tracer
from . import metrics


class SLOAutopilot:
    """See module doc.  ``admission`` is a sharding.AdmissionController
    (duck-typed on ``set_shed_bias``), ``accounting`` a
    ``TenantAccounting``; either may be None (decide-only mode — the
    replay path runs this way)."""

    def __init__(
        self,
        slos: Sequence[Any],
        *,
        admission=None,
        accounting=None,
        max_level: int = 4,
        shed_bias_step: float = 0.2,
        cap_backoff: float = 0.5,
        relax_after: int = 3,
    ) -> None:
        # Snapshot-fed engine: the registry is never sampled live, so
        # live and replay run byte-identical arithmetic.
        self.engine = SLOEngine(slos, registry=Registry())
        self.admission = admission
        self.accounting = accounting
        self.max_level = max_level
        self.shed_bias_step = shed_bias_step
        self.cap_backoff = cap_backoff
        self.relax_after = relax_after
        self._level = 0
        self._ok_streak = 0
        # (ts, breached, level) per evaluation — the drill's live
        # decision sequence.
        self.decisions: List[Tuple[float, bool, int]] = []

    @property
    def level(self) -> int:
        return self._level

    # -- the journal-driven evaluation ---------------------------------------

    def ingest(self, snapshot: Dict[str, Any]) -> int:
        """Feed one metric-journal snapshot (live: the frame the journal
        just wrote; replay: a frame read back off disk) and re-decide.
        Returns the level in force after this evaluation."""
        self.engine.ingest_snapshot(snapshot)
        t = float(snapshot.get("ts", 0.0))
        state = self.engine.evaluate(t)
        breached = any(
            state[s.name]["breached"] for s in self.engine.slos
        )
        return self._step(breached, t)

    def _step(self, breached: bool, t: float) -> int:
        prev = self._level
        if breached:
            self._ok_streak = 0
            level = min(prev + 1, self.max_level)
        else:
            self._ok_streak += 1
            if prev > 0 and self._ok_streak >= self.relax_after:
                level = prev - 1
                self._ok_streak = 0
            else:
                level = prev
        self._level = level
        self.decisions.append((t, breached, level))
        if level != prev:
            # The adjustment span: the flight recorder's answer to "why
            # did the shed floor move at 12:03".  Never opened on the
            # steady state — a healthy fleet records zero of these.
            with default_tracer.span(
                "scheduler/qos.autopilot",
                from_level=prev, to_level=level, breached=breached,
            ):
                self._apply(level)
            metrics.AUTOPILOT_ADJUSTMENTS_TOTAL.inc(
                direction="tighten" if level > prev else "relax"
            )
        metrics.AUTOPILOT_LEVEL.set(float(level))
        return level

    def _apply(self, level: int) -> None:
        if self.admission is not None:
            self.admission.set_shed_bias(level * self.shed_bias_step)
        if self.accounting is not None:
            self.accounting.set_cap_factor(self.cap_backoff ** level)

    # -- journal replay (the drill's parity bar) -----------------------------

    @classmethod
    def replay(
        cls,
        snapshots: Sequence[Dict[str, Any]],
        slos: Sequence[Any],
        **kwargs: Any,
    ) -> "SLOAutopilot":
        """Re-run the decision sequence from replayed journal snapshots
        (``utils.metric_journal.replay_metric_journal`` output, one
        process stream in seq order).  The returned pilot's
        ``decisions`` must equal the live pilot's exactly — same
        snapshots, same engine arithmetic, same pure transition
        function."""
        pilot = cls(slos, **kwargs)
        ordered = sorted(
            snapshots, key=lambda s: (s.get("seq", 0), s.get("ts", 0.0))
        )
        for snap in ordered:
            pilot.ingest(snap)
        return pilot

    def levels(self) -> List[int]:
        return [level for _t, _b, level in self.decisions]

    def close(self) -> None:
        self.engine.close()
