"""Tenant identity + per-tenant QoS configuration (DESIGN.md §26).

The reference manager keys traffic to users/PATs/clusters; here the
same identities map onto a **tenant id**: authenticated callers derive
``t-<user id>`` (``derive_tenant``), unauthenticated clusters declare
one in their daemon config (``DaemonConfig.tenant``), and everything
else rides as the ``default`` tenant.

A ``TenantQoS`` row declares what a tenant is entitled to:

- ``priority``            — the default priority class stamped on the
                            tenant's tasks/announces when the workload
                            does not say (preheat jobs override DOWN to
                            LEVEL6 regardless);
- ``weight``              — the weighted-fair share (traffic shaper
                            tenant split, scorer-batcher DRR quantum,
                            admission over-quota test);
- ``upload_rate_bytes_s`` — daemon upload-path bandwidth cap (0 = none);
- ``announce_qps``        — announce/register rate cap at the scheduler
                            admission gate (0 = none);
- ``tenant_class``        — the BOUNDED label ("gold".."background")
                            metrics carry instead of raw tenant ids
                            (DF017: a raw tenant id label is a
                            cardinality explosion on a real fleet).

``QoSPolicy`` is the immutable collection the manager publishes as the
``tenant_qos`` blob of the cluster dynconfig; holders swap whole policy
references atomically (the §18 snapshot discipline), never mutate one.
"""

from __future__ import annotations

import re
from dataclasses import asdict, dataclass
from typing import Any, Dict, Mapping, Optional

DEFAULT_TENANT = "default"

# Bounded tenant classes — the ONLY tenant-shaped metric label allowed
# (DF017 FORBIDDEN_LABELS bans raw tenant ids by name).
TENANT_CLASSES = ("gold", "silver", "bronze", "background")

_TENANT_RE = re.compile(r"[^A-Za-z0-9._-]+")


def derive_tenant(subject: str) -> str:
    """Tenant id from an authenticated subject (user id of a session
    token or PAT owner): ``t-<subject>``, sanitized to the same boring
    charset CRUD row ids use.  Deterministic — every service derives the
    SAME tenant for one identity without coordination."""
    clean = _TENANT_RE.sub("-", subject or "").strip("-")
    return f"t-{clean}" if clean else DEFAULT_TENANT


@dataclass(frozen=True)
class TenantQoS:
    """One tenant's declared QoS entitlement (see module doc)."""

    tenant: str
    tenant_class: str = "silver"
    priority: int = 0
    weight: float = 1.0
    upload_rate_bytes_s: float = 0.0
    announce_qps: float = 0.0
    announce_burst: int = 0

    def validate(self) -> None:
        if not self.tenant:
            raise ValueError("tenant_qos entry needs a tenant id")
        if self.tenant_class not in TENANT_CLASSES:
            raise ValueError(
                f"tenant {self.tenant!r}: tenant_class "
                f"{self.tenant_class!r} not in {TENANT_CLASSES}"
            )
        if not (0 <= int(self.priority) <= 6):
            raise ValueError(
                f"tenant {self.tenant!r}: priority must be in [0, 6]"
            )
        if self.weight <= 0:
            raise ValueError(f"tenant {self.tenant!r}: weight must be > 0")
        if self.upload_rate_bytes_s < 0 or self.announce_qps < 0:
            raise ValueError(
                f"tenant {self.tenant!r}: rate caps must be >= 0 (0 = none)"
            )
        if self.announce_burst < 0:
            raise ValueError(
                f"tenant {self.tenant!r}: announce_burst must be >= 0"
            )

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "TenantQoS":
        unknown = set(d) - {f for f in cls.__dataclass_fields__}
        if unknown:
            raise ValueError(f"tenant_qos: unknown keys {sorted(unknown)}")
        row = cls(**dict(d))
        row.validate()
        return row

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def parse_tenant_qos(raw: Any) -> Dict[str, TenantQoS]:
    """``tenant_qos`` blob → validated rows, keyed by tenant id.  The
    blob shape is ``{tenant_id: {weight: .., announce_qps: ..}, ...}``
    (the tenant key wins over any inline ``tenant`` field).  Raises
    ValueError on malformed entries — surfaced by the manager's
    cluster-blob write validation and config validate()."""
    if raw is None:
        return {}
    if not isinstance(raw, Mapping):
        raise ValueError(
            f"tenant_qos must be an object, got {type(raw).__name__}"
        )
    out: Dict[str, TenantQoS] = {}
    for tenant, entry in raw.items():
        if not isinstance(entry, Mapping):
            raise ValueError(f"tenant_qos[{tenant!r}] must be an object")
        d = dict(entry)
        d["tenant"] = str(tenant)
        out[str(tenant)] = TenantQoS.from_dict(d)
    return out


class QoSPolicy:
    """Immutable per-tenant QoS table with a default row for tenants no
    entry names.  Built once per dynconfig payload; every enforcement
    point reads ONE reference atomically."""

    def __init__(
        self,
        tenants: Optional[Mapping[str, TenantQoS]] = None,
        *,
        default: Optional[TenantQoS] = None,
    ) -> None:
        self._tenants: Dict[str, TenantQoS] = dict(tenants or {})
        for row in self._tenants.values():
            row.validate()
        self._default = default or self._tenants.get(DEFAULT_TENANT) or (
            TenantQoS(tenant=DEFAULT_TENANT)
        )
        self._default.validate()

    # -- lookups -------------------------------------------------------------

    def for_tenant(self, tenant: str) -> TenantQoS:
        row = self._tenants.get(tenant or DEFAULT_TENANT)
        if row is not None:
            return row
        d = self._default
        if d.tenant == (tenant or DEFAULT_TENANT):
            return d
        # Unknown tenants inherit the default entitlement under their
        # own id (accounting stays per-tenant even without a row).
        return TenantQoS(
            tenant=tenant or DEFAULT_TENANT,
            tenant_class=d.tenant_class,
            priority=d.priority,
            weight=d.weight,
            upload_rate_bytes_s=d.upload_rate_bytes_s,
            announce_qps=d.announce_qps,
            announce_burst=d.announce_burst,
        )

    def weight_of(self, tenant: str) -> float:
        return float(self.for_tenant(tenant).weight)

    def class_of(self, tenant: str) -> str:
        """The bounded metric label for a tenant (never the raw id)."""
        return self.for_tenant(tenant).tenant_class

    def tenants(self) -> Dict[str, TenantQoS]:
        return dict(self._tenants)

    def __contains__(self, tenant: str) -> bool:
        return tenant in self._tenants

    # -- wire form (cluster dynconfig blob) ----------------------------------

    def to_payload(self) -> Dict[str, Dict[str, Any]]:
        return {t: row.to_dict() for t, row in sorted(self._tenants.items())}

    @classmethod
    def from_payload(cls, payload: Any) -> "QoSPolicy":
        return cls(parse_tenant_qos(payload))
