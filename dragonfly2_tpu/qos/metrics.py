"""QoS-plane metrics (DESIGN.md §26).

Tenant-shaped series carry the BOUNDED ``tenant_class`` label
("gold".."background"), never raw tenant ids — one series per tenant is
a cardinality explosion on a million-user fleet, and DF017 bans the raw
label names outright.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

QOS_SHED_TOTAL = _reg.counter(
    "scheduler_qos_shed_total",
    "Requests shed by tenant-aware admission control, by tenant class "
    "and priority band",
    ["tenant_class", "priority"],
)
QOS_RATE_CAPPED_TOTAL = _reg.counter(
    "scheduler_qos_rate_capped_total",
    "Requests refused by a tenant's announce-rate token bucket",
    ["tenant_class"],
)
AUTOPILOT_LEVEL = _reg.gauge(
    "scheduler_qos_autopilot_level",
    "Current SLO-autopilot tightening level (0 = declared policy; each "
    "level raises the shed bias and tightens over-quota announce caps)",
)
AUTOPILOT_ADJUSTMENTS_TOTAL = _reg.counter(
    "scheduler_qos_autopilot_adjustments_total",
    "Autopilot level transitions, by direction", ["direction"],
)
