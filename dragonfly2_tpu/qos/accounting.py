"""Per-tenant accounting: the ONE object behind the announce path's
per-request QoS costs (DESIGN.md §26).

Before this, per-request costs on the admission path were scattered
(in-flight counters on the controller, latency sketches, ad-hoc shed
counters).  ``TenantAccounting`` consolidates the tenant-scoped half:

- **windowed usage** — two-epoch-rotated per-tenant request counts (the
  §24 admission-sketch discipline): ``usage_share`` answers "what
  fraction of this shard's recent traffic is tenant X" without
  unbounded history;
- **announce-rate caps** — a per-tenant token bucket built from the
  published ``announce_qps``; the SLO autopilot's ``cap_factor``
  tightens the effective rate for OVER-QUOTA tenants only (a tenant
  inside its weighted share keeps its declared cap through an
  overload);
- **the over-quota signal** — ``usage_share / weight_share``; the
  admission controller scales its shed floor by this, so overload sheds
  the *noisy* tenant's lowest priority band first;
- **shed bookkeeping** — per-tenant shed counts for the drill verdicts
  and the bounded ``tenant_class`` metric label.

State is deliberately rebuildable: every field is a deterministic
function of the request stream since boot (plus the published policy),
so a SIGKILLed shard's replacement reconstructs equivalent accounting
by serving the same traffic — the chaos drill's bar.

Locking: ``_mu`` is a leaf lock; token buckets are taken OUTSIDE it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..rpc.ratelimit import TokenBucket
from .policy import DEFAULT_TENANT, QoSPolicy


class _TenantRow:
    __slots__ = (
        "requests", "cur", "prev", "sheds", "capped", "bytes",
        "bucket", "bucket_rate",
    )

    def __init__(self) -> None:
        self.requests = 0        # cumulative since boot
        self.cur = 0             # current epoch window count
        self.prev = 0            # previous epoch window count
        self.sheds = 0
        self.capped = 0
        self.bytes = 0
        self.bucket: Optional[TokenBucket] = None
        self.bucket_rate = 0.0   # the qps the bucket was built for


class TenantAccounting:
    def __init__(
        self,
        policy: Optional[QoSPolicy] = None,
        *,
        window_s: float = 5.0,
        over_quota_slack: float = 1.25,
        now: Optional[float] = None,
    ) -> None:
        self._mu = threading.Lock()
        self._policy = policy or QoSPolicy()
        self.window_s = window_s
        # A tenant is "over quota" past usage_share > slack × weight_share
        # — the slack keeps bursty-but-entitled tenants out of the noisy
        # band (hysteresis against share jitter at low volumes).
        self.over_quota_slack = over_quota_slack
        self._rows: Dict[str, _TenantRow] = {}
        # ``now`` is a declared clock seam (DESIGN.md §27): the SIGKILL
        # rebuild drill re-anchors the window epoch at the scripted
        # replay clock so two rebuilds over the same stream agree.
        self._epoch_started = time.monotonic() if now is None else now
        # Autopilot output (qos/autopilot.py): scales the EFFECTIVE
        # announce rate of over-quota tenants; 1.0 = declared caps.
        self._cap_factor = 1.0

    # -- policy / autopilot inputs -------------------------------------------

    def set_policy(self, policy: QoSPolicy) -> None:
        with self._mu:
            self._policy = policy
            # Declared caps may have changed: rebuild buckets lazily by
            # invalidating the built-rate memo.
            for row in self._rows.values():
                row.bucket_rate = 0.0

    @property
    def policy(self) -> QoSPolicy:
        with self._mu:
            return self._policy

    def set_cap_factor(self, factor: float) -> None:
        """Autopilot tightening: over-quota tenants' announce caps scale
        by ``factor`` in (0, 1]; 1.0 restores declared rates."""
        with self._mu:
            self._cap_factor = max(0.05, min(1.0, float(factor)))
            for row in self._rows.values():
                row.bucket_rate = 0.0

    def cap_factor(self) -> float:
        with self._mu:
            return self._cap_factor

    # -- the per-request account ---------------------------------------------

    def _row_locked(self, tenant: str) -> _TenantRow:
        row = self._rows.get(tenant)
        if row is None:
            row = self._rows[tenant] = _TenantRow()
        return row

    def _rotate_locked(self, now: float) -> None:
        if now - self._epoch_started >= self.window_s:
            for row in self._rows.values():
                row.prev = row.cur
                row.cur = 0
            self._epoch_started = now

    def note(self, tenant: str, *, now: Optional[float] = None) -> bool:
        """Live edge: samples the monotonic clock OUTSIDE the replay
        path and delegates to ``note_at`` (the declared replay root —
        DESIGN.md §27)."""
        t = time.monotonic() if now is None else now
        return self.note_at(tenant, t)

    def note_at(self, tenant: str, now: float) -> bool:
        """Account one request for ``tenant`` at clock reading ``now``;
        False when the tenant's (possibly autopilot-tightened)
        announce-rate cap refuses it.  The request is counted either way
        — a capped flood still shows up as usage, which is what keeps
        the over-quota signal honest.

        A declared replay root: the verdict is a pure function of the
        request stream and its timestamps, so the SIGKILL rebuild drill
        can replay a scripted stream through the same door the live
        plane uses and land on identical state.
        """
        tenant = tenant or DEFAULT_TENANT
        with self._mu:
            self._rotate_locked(now)
            row = self._row_locked(tenant)
            row.requests += 1
            row.cur += 1
            qos = self._policy.for_tenant(tenant)
            declared = float(qos.announce_qps)
            qps = declared
            if qps > 0.0 and self._over_quota_locked(tenant) > self.over_quota_slack:
                qps *= self._cap_factor
            bucket = row.bucket
            if qps <= 0.0:
                row.bucket = None
                row.bucket_rate = 0.0
                return True
            if bucket is None or row.bucket_rate != qps:
                burst = qos.announce_burst or max(int(declared), 1)
                # A tightened rate tightens the burst headroom with it —
                # rebuilding at the declared burst would hand the capped
                # tenant a fresh declared-size token pile.
                burst = max(1, int(burst * (qps / declared)))
                bucket = row.bucket = TokenBucket(qps, burst)
                row.bucket_rate = qps
        if bucket.take_at(now):
            return True
        with self._mu:
            row.capped += 1
        return False

    def record_shed(self, tenant: str) -> None:
        with self._mu:
            self._row_locked(tenant or DEFAULT_TENANT).sheds += 1

    def record_bytes(self, tenant: str, nbytes: int) -> None:
        """Bandwidth accounting (the upload path's serve bytes)."""
        with self._mu:
            self._row_locked(tenant or DEFAULT_TENANT).bytes += int(nbytes)

    # -- the fairness signals ------------------------------------------------

    def _windowed_locked(self, tenant: str) -> int:
        row = self._rows.get(tenant)
        return (row.cur + row.prev) if row is not None else 0

    def _over_quota_locked(self, tenant: str) -> float:
        """usage_share / weight_share over the active window; 1.0 = at
        quota, >1 = noisy.  0 when the window is empty."""
        total = sum(r.cur + r.prev for r in self._rows.values())
        if total <= 0:
            return 0.0
        active = [t for t, r in self._rows.items() if r.cur + r.prev > 0]
        usage = self._windowed_locked(tenant) / total
        weights = {t: self._policy.weight_of(t) for t in active}
        wsum = sum(weights.values())
        if tenant not in weights or wsum <= 0:
            return 0.0
        return usage / (weights[tenant] / wsum)

    def over_quota(self, tenant: str) -> float:
        with self._mu:
            return self._over_quota_locked(tenant or DEFAULT_TENANT)

    def noise_factor(self, tenant: str) -> float:
        """Shed-floor multiplier in [1, 3]: 1 for tenants inside their
        weighted share, growing with how far past quota they run — the
        admission controller sheds a 3×-over-quota tenant's bands three
        times earlier than a within-quota one's."""
        with self._mu:
            ratio = self._over_quota_locked(tenant or DEFAULT_TENANT)
        if ratio <= self.over_quota_slack:
            return 1.0
        return min(3.0, ratio / self.over_quota_slack)

    def class_of(self, tenant: str) -> str:
        with self._mu:
            return self._policy.class_of(tenant or DEFAULT_TENANT)

    # -- observability / rebuild evidence ------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Deterministic per-tenant accounting state (the chaos drill's
        rebuild-equivalence evidence and the diagnostics payload)."""
        with self._mu:
            return {
                t: {
                    "requests": r.requests,
                    "windowed": r.cur + r.prev,
                    "sheds": r.sheds,
                    "capped": r.capped,
                    "bytes": r.bytes,
                    "over_quota": round(self._over_quota_locked(t), 4),
                    "tenant_class": self._policy.class_of(t),
                }
                for t, r in sorted(self._rows.items())
            }
