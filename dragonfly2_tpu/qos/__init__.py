"""Multi-tenant QoS plane (DESIGN.md §26).

"Millions of users" means contending tenants, not one big swarm.  This
package is the policy + enforcement glue the four services share:

- ``policy``     — tenant identity derivation and the per-tenant QoS
                   config record (priority class, weight, upload
                   bandwidth cap, announce-rate cap) the manager
                   publishes with the cluster dynconfig.
- ``accounting`` — ONE accounting object consolidating the announce
                   path's per-request costs: windowed per-tenant usage,
                   announce-rate token buckets, shed bookkeeping, and
                   the over-quota signal overload shedding keys on.
- ``autopilot``  — the §23 feedback loop: declared-SLO burn verdicts
                   tighten the shard's shed floor and over-quota
                   tenants' announce caps, and relax on recovery; every
                   decision is a stateless function of the snapshot
                   history, so journal replay reproduces live decisions
                   exactly.

Enforcement itself lives at the chokepoints that already existed: the
daemon upload gate (``daemon/upload.py``), the hierarchical traffic
shaper (``daemon/traffic_shaper.py``), the scorer micro-batcher's
deficit-round-robin lanes (``scheduler/microbatch.py``), and the
admission controller (``scheduler/sharding.py``).
"""

from .accounting import TenantAccounting  # noqa: F401
from .autopilot import SLOAutopilot  # noqa: F401
from .policy import (  # noqa: F401
    DEFAULT_TENANT,
    TENANT_CLASSES,
    QoSPolicy,
    TenantQoS,
    derive_tenant,
    parse_tenant_qos,
)
