"""Download conductor: the per-task engine turning a schedule into bytes.

Reference: client/daemon/peer/peertask_conductor.go — register with the
scheduler (:255-368), consume parent lists, run piece workers
(:1009-1077), report per-piece results, fall back to source when P2P
fails (:493-531); plus piece_manager.go's digest-verified piece writes.

Transport-neutral: a ``PieceFetcher`` abstracts "read piece N of task T
from parent P" (in-process: the parent daemon's UploadManager; over the
wire: HTTP range GET to the parent's upload port).  The conductor drives
the REAL scheduler service — the same filter/rank/DAG path production
uses — so daemon-level tests exercise the whole control loop.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence

from ..scheduler.resource import Host, Peer
from ..scheduler.service import SchedulerService
from ..scheduler.scheduling import ScheduleResultKind
from ..utils.types import TINY_FILE_SIZE, Priority
from .storage import DaemonStorage
from .traffic_shaper import TrafficShaper


class PieceFetcher(Protocol):
    def fetch(self, parent_host_id: str, task_id: str, number: int) -> bytes:
        """Fetch one piece from a parent; raises on failure."""
        ...

    def piece_bitmap(self, parent_host_id: str, task_id: str):
        """Optional piece-metadata sync: bytes (1 per held piece) or None."""
        ...


class SourceFetcher(Protocol):
    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        """Back-to-source: fetch piece N of the origin content."""
        ...


class _SourceFetchError(Exception):
    """Internal: a back-to-source piece fetch failed (task-fatal)."""


@dataclass
class DownloadResult:
    ok: bool
    task_id: str
    peer_id: str
    pieces: int = 0
    bytes: int = 0
    back_to_source: bool = False
    failed_pieces: int = 0
    cost_s: float = 0.0


class Conductor:
    def __init__(
        self,
        host: Host,
        storage: DaemonStorage,
        scheduler: SchedulerService,
        piece_fetcher: PieceFetcher,
        source_fetcher: Optional[SourceFetcher] = None,
        *,
        traffic_shaper: Optional[TrafficShaper] = None,
        max_piece_retries: int = 2,
        concurrent_source_groups: int = 1,
        concurrent_source_threshold: int = 2,
        pex=None,
    ) -> None:
        self.host = host
        self.storage = storage
        self.scheduler = scheduler
        self.piece_fetcher = piece_fetcher
        self.source_fetcher = source_fetcher
        # Optional PeerExchange (daemon/pex.py): piece-holder discovery
        # that survives scheduler outages — registration failures fall
        # back to gossip-discovered parents (pex peer_pool semantics).
        self.pex = pex
        self.traffic_shaper = traffic_shaper
        self.max_piece_retries = max_piece_retries
        # Concurrent back-to-source (piece_manager.go:793-873 semantics):
        # split the remaining pieces into `groups` contiguous range groups,
        # one worker per group, any worker failure cancels the task.  Only
        # engages when at least `threshold` pieces remain — tiny remainders
        # aren't worth the fan-out.
        self.concurrent_source_groups = max(1, concurrent_source_groups)
        self.concurrent_source_threshold = max(1, concurrent_source_threshold)
        # Storage writes and scheduler reports from concurrent source
        # workers are serialized; only the origin fetch itself overlaps.
        self._report_lock = threading.Lock()

    def probe_content_length(self, url: str) -> Optional[int]:
        """Origin size via the source fetcher, when it can tell (shared by
        the control API, the seeder, and the CLI --download path)."""
        source = self.source_fetcher
        if source is not None and hasattr(source, "content_length"):
            return source.content_length(url)
        return None

    # -- the main flow (peertask_conductor.go:370 start → pullPieces) --------

    def download(
        self,
        url: str,
        *,
        piece_size: int = 4 << 20,
        content_length: Optional[int] = None,
        expected_pieces: Optional[int] = None,
        source_headers: Optional[dict] = None,
        priority: Priority = Priority.LEVEL0,
        task_id: Optional[str] = None,
    ) -> DownloadResult:
        """``source_headers`` ride along to the origin fetcher (preheat of
        authenticated registry blobs carries the pull token this way);
        they travel per-call — the Conductor is shared across concurrent
        downloads and must not bleed one download's credentials into
        another's origin requests."""
        t0 = time.monotonic()
        try:
            reg = self.scheduler.register_peer(
                host=self.host, url=url, priority=priority, task_id=task_id
            )
        except Exception:
            # Scheduler unreachable: gossip keeps the swarm serving
            # (pex reclaim/pool semantics — peers found WITHOUT the
            # control plane).  No pex or no sizing → the failure is real.
            if self.pex is None or not content_length or content_length < 0:
                raise
            return self._pull_via_pex(url, piece_size, content_length, t0)
        peer = reg.peer
        task = peer.task

        if reg.direct_piece:
            # TINY shortcut: the content arrived inline with registration —
            # no piece transfer at all (service_v1 tiny response).
            self.storage.register_task(
                task.id, piece_size=piece_size, content_length=len(reg.direct_piece)
            )
            self.storage.write_piece(task.id, 0, reg.direct_piece)
            self.scheduler.report_piece_finished(
                peer, 0, parent_id="", length=len(reg.direct_piece), cost_ns=1
            )
            self.scheduler.report_peer_finished(peer)
            return DownloadResult(
                ok=True, task_id=task.id, peer_id=peer.id, pieces=1,
                bytes=len(reg.direct_piece), cost_s=time.monotonic() - t0,
            )

        # First peer in the swarm learns content length from the origin and
        # reports it through the scheduler API (so remote schedulers learn).
        if task.content_length < 0:
            if content_length is None or content_length < 0:
                # -1 is the source clients' "origin won't say" sentinel:
                # proceeding would register a 0-piece task and report a
                # hollow success.
                return self._fail(peer, t0, "unknown content length")
            n_pieces = (
                expected_pieces
                if expected_pieces is not None
                else (content_length + piece_size - 1) // piece_size
            )
            self.scheduler.set_task_info(peer, content_length, n_pieces, piece_size)
        piece_size = task.piece_size or piece_size
        n_pieces = task.total_piece_count

        self.storage.register_task(
            task.id, piece_size=piece_size, content_length=task.content_length
        )
        if self.traffic_shaper is not None:
            self.traffic_shaper.add_task(task.id)
        try:
            if reg.schedule is not None and reg.schedule.kind is ScheduleResultKind.PARENTS:
                result = self._pull_from_parents(peer, reg.schedule.parents, n_pieces, t0)
                if result is not None:
                    return result
                # P2P path exhausted → back-to-source (dfget.go:141 fallback).
            return self._pull_from_source(
                peer, n_pieces, piece_size, t0, source_headers
            )
        finally:
            if self.traffic_shaper is not None:
                self.traffic_shaper.remove_task(task.id)

    def _pull_via_pex(
        self, url: str, piece_size: int, content_length: int, t0: float
    ) -> DownloadResult:
        """Scheduler-less download: gossip-discovered holders serve pieces
        directly (the pex pool is the only metadata source)."""
        from ..utils import idgen

        task_id = idgen.task_id(url)
        n_pieces = (content_length + piece_size - 1) // piece_size
        self.storage.register_task(
            task_id, piece_size=piece_size, content_length=content_length
        )
        nbytes = 0
        for number in range(n_pieces):
            if self.storage.has_piece(task_id, number):
                continue
            fetched = False
            for holder in self.pex.find_peers_with_piece(task_id, number):
                if holder == self.host.id:
                    continue
                try:
                    data = self.piece_fetcher.fetch(holder, task_id, number)
                except Exception:  # noqa: BLE001 — try the next holder
                    continue
                self.storage.write_piece(task_id, number, data)
                nbytes += len(data)
                fetched = True
                break
            if not fetched:
                return DownloadResult(
                    ok=False, task_id=task_id, peer_id="", pieces=number,
                    bytes=nbytes, cost_s=time.monotonic() - t0,
                )
        self.pex.advertise(task_id, set(range(n_pieces)))
        return DownloadResult(
            ok=True, task_id=task_id, peer_id="", pieces=n_pieces,
            bytes=nbytes, cost_s=time.monotonic() - t0,
        )

    def _pull_from_parents(
        self, peer: Peer, parents: List[Peer], n_pieces: int, t0: float
    ) -> Optional[DownloadResult]:
        """Piece workers over the assigned parents; None → fall to source."""
        task = peer.task
        failed = 0
        nbytes = 0
        parents = list(parents)
        # Piece-metadata sync (SyncPieceTasks analog): ask each parent which
        # pieces it holds so workers skip guaranteed 404s — partial holders
        # (mid-download parents, tail-only reloads) stop costing a failed
        # fetch per missing piece.
        bitmaps = {}
        if hasattr(self.piece_fetcher, "piece_bitmap"):
            for p in parents:
                bm = self.piece_fetcher.piece_bitmap(p.host.id, task.id)
                if bm is not None:
                    bitmaps[p.id] = bm

        def holds(parent, number):
            bm = bitmaps.get(parent.id)
            return bm is None or (number < len(bm) and bm[number])

        def refresh_bitmaps(plist):
            if hasattr(self.piece_fetcher, "piece_bitmap"):
                for p in plist:
                    if p.id not in bitmaps:
                        bm = self.piece_fetcher.piece_bitmap(p.host.id, task.id)
                        if bm is not None:
                            bitmaps[p.id] = bm

        # Server-pushed reschedules (the v2 bidi wire): between pieces,
        # adopt whatever the scheduler pushed — new parents replace the
        # current set; a pushed back-to-source aborts the P2P path.
        take_pushed = getattr(self.scheduler, "take_pushed_schedule", None)

        def apply_push():
            nonlocal parents
            if take_pushed is None:
                return True
            res = take_pushed(peer)
            if res is None:
                return True
            if res.kind is ScheduleResultKind.PARENTS and res.parents:
                parents = list(res.parents)
                refresh_bitmaps(parents)
            elif res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
                return False
            return True

        for number in range(n_pieces):
            if not apply_push():
                return None
            if not parents:
                return None
            done = False
            for attempt in range(self.max_piece_retries + 1):
                # Recomputed each attempt: a mid-piece reschedule replaces
                # `parents` and the fresh assignment must be tried NOW, not
                # after the retry budget burns on the dead one.
                preferred = [p for p in parents if holds(p, number)] or parents
                parent = preferred[(number + attempt) % len(preferred)]
                try:
                    t_piece = time.monotonic()
                    data = self.piece_fetcher.fetch(parent.host.id, task.id, number)
                    cost_ns = max(int((time.monotonic() - t_piece) * 1e9), 1)
                except Exception:
                    failed += 1
                    res = self.scheduler.report_piece_failed(peer, parent.id)
                    if res.kind is ScheduleResultKind.PARENTS and res.parents:
                        parents = list(res.parents)
                        refresh_bitmaps(parents)
                    elif res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
                        return None
                    continue
                self.storage.write_piece(task.id, number, data)
                nbytes += len(data)
                if self.traffic_shaper is not None:
                    self.traffic_shaper.record(task.id, len(data))
                self.scheduler.report_piece_finished(
                    peer, number, parent_id=parent.id, length=len(data), cost_ns=cost_ns
                )
                done = True
                break
            if not done:
                return None
        self.scheduler.report_peer_finished(peer)
        if self.pex is not None:
            self.pex.advertise(task.id, set(range(n_pieces)))
        return DownloadResult(
            ok=True,
            task_id=task.id,
            peer_id=peer.id,
            pieces=n_pieces,
            bytes=nbytes,
            failed_pieces=failed,
            cost_s=time.monotonic() - t0,
        )

    def _pull_from_source(
        self,
        peer: Peer,
        n_pieces: int,
        piece_size: int,
        t0: float,
        headers: Optional[dict] = None,
    ) -> DownloadResult:
        task = peer.task
        if self.source_fetcher is None:
            return self._fail(peer, t0, "no source fetcher")
        self.scheduler.mark_back_to_source(peer)
        # Resume, don't restart: pieces already fetched from parents stay
        # on disk with their parent attribution intact — the origin only
        # serves what P2P didn't (piece_manager.go resumes from the
        # persisted piece bitmap the same way).
        missing = [
            n for n in range(n_pieces) if not self.storage.has_piece(task.id, n)
        ]
        groups = min(self.concurrent_source_groups, len(missing))
        try:
            if groups > 1 and len(missing) >= self.concurrent_source_threshold:
                nbytes = self._source_piece_groups(
                    peer, missing, piece_size, groups, headers
                )
            else:
                nbytes = 0
                for number in missing:
                    nbytes += self._source_one_piece(
                        peer, number, piece_size, headers
                    )
        except _SourceFetchError as e:
            return self._fail(peer, t0, str(e))
        self.scheduler.report_peer_finished(peer)
        if self.pex is not None:
            self.pex.advertise(task.id, set(range(n_pieces)))
        return DownloadResult(
            ok=True,
            task_id=task.id,
            peer_id=peer.id,
            pieces=n_pieces,
            bytes=nbytes,
            back_to_source=True,
            cost_s=time.monotonic() - t0,
        )

    def _source_one_piece(
        self,
        peer: Peer,
        number: int,
        piece_size: int,
        headers: Optional[dict] = None,
    ) -> int:
        """Fetch piece `number` from the origin, persist + report it."""
        from ..source.client import call_with_optional_headers

        task = peer.task
        t_piece = time.monotonic()
        try:
            data = call_with_optional_headers(
                self.source_fetcher.fetch, task.url, number, piece_size,
                headers=headers,
            )
        except Exception:
            raise _SourceFetchError(f"source fetch piece {number}")
        cost_ns = max(int((time.monotonic() - t_piece) * 1e9), 1)
        with self._report_lock:
            self.storage.write_piece(task.id, number, data)
            self.scheduler.report_piece_finished(
                peer, number, parent_id="", length=len(data), cost_ns=cost_ns
            )
            # First fetcher of a TINY task publishes the bytes inline so
            # later peers skip the transfer entirely.
            if (
                number == 0
                and 0 < task.content_length <= TINY_FILE_SIZE
                and hasattr(self.scheduler, "set_task_direct_piece")
            ):
                self.scheduler.set_task_direct_piece(
                    peer, data[: task.content_length]
                )
        return len(data)

    def _source_piece_groups(
        self,
        peer: Peer,
        missing: Sequence[int],
        piece_size: int,
        groups: int,
        headers: Optional[dict] = None,
    ) -> int:
        """Concurrent back-to-source by contiguous piece groups.

        piece_manager.go:793-873: `con` workers each own a contiguous slice
        of the remaining pieces (the first `remainder` groups take one extra);
        the first worker failure cancels the whole task.
        """
        per, rem = divmod(len(missing), groups)
        slices: List[Sequence[int]] = []
        start = 0
        for i in range(groups):
            size = per + (1 if i < rem else 0)
            slices.append(missing[start : start + size])
            start += size
        cancelled = threading.Event()

        def run_group(numbers: Sequence[int]) -> int:
            nbytes = 0
            for number in numbers:
                if cancelled.is_set():
                    raise _SourceFetchError("cancelled by sibling group")
                try:
                    nbytes += self._source_one_piece(
                        peer, number, piece_size, headers
                    )
                except Exception as e:
                    # Not just fetch failures: a write/report error
                    # (disk full, scheduler unreachable) is equally
                    # task-fatal and must cancel the siblings rather
                    # than escape past download()'s DownloadResult
                    # contract.
                    cancelled.set()
                    if isinstance(e, _SourceFetchError):
                        raise
                    raise _SourceFetchError(
                        f"piece {number}: {type(e).__name__}: {e}"
                    ) from e
            return nbytes

        with ThreadPoolExecutor(max_workers=groups) as pool:
            futures = [pool.submit(run_group, s) for s in slices]
            total = 0
            error: Optional[_SourceFetchError] = None
            for fut in futures:
                try:
                    total += fut.result()
                except _SourceFetchError as e:
                    error = error or e
        if error is not None:
            raise error
        return total

    def _fail(self, peer: Peer, t0: float, reason: str) -> DownloadResult:
        self.scheduler.report_peer_failed(peer)
        return DownloadResult(
            ok=False,
            task_id=peer.task.id,
            peer_id=peer.id,
            cost_s=time.monotonic() - t0,
        )
