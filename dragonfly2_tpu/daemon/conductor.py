"""Download conductor: the per-task engine turning a schedule into bytes.

Reference: client/daemon/peer/peertask_conductor.go — register with the
scheduler (:255-368), consume parent lists, run CONCURRENT piece workers
pulling a shared piece queue (:1009-1077 initDownloadPieceWorkers /
downloadPieceWorker), report per-piece results, fall back to source when
P2P fails (:493-531); peertask_manager.go:328-423 StartFileTask /
StartStreamTask (reuse-first, stream bytes while downloading);
peertask_reuse.go:49-61 (completed-task reuse skips the scheduler
entirely); peertask_piecetask_synchronizer.go (children learn a
mid-download parent's new pieces as they land — here via bitmap
subscription polls against the parent's piece plane).

Transport-neutral: a ``PieceFetcher`` abstracts "read piece N of task T
from parent P" (in-process: the parent daemon's UploadManager; over the
wire: HTTP range GET to the parent's upload port).  The conductor drives
the REAL scheduler service — the same filter/rank/DAG path production
uses — so daemon-level tests exercise the whole control loop.

Concurrency model (downloadPieceWorker semantics, threads not
goroutines): each active task owns up to ``piece_parallelism`` workers
draining one shared queue of missing piece numbers.  A worker picks a
parent that HOLDS its piece (piece-metadata bitmaps, refreshed while the
swarm is mid-download); a piece nobody holds yet is "no valid piece
temporarily" — the worker polls holder bitmaps instead of burning fetch
failures.  Any worker can adopt server-pushed reschedules for the whole
pool; back-to-source verdicts abort the pool and fall through to the
origin path.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import (
    TYPE_CHECKING, Dict, Iterator, List, Optional, Protocol, Sequence, Set,
    Union,
)

from ..scheduler.resource import Host, Peer
from ..scheduler.service import SchedulerService
from ..scheduler.scheduling import ScheduleResultKind
from ..utils.types import TINY_FILE_SIZE, Priority
from .piece_pipeline import (
    CommitPipeline,
    CommitTee,
    PieceLatencyTracker,
    PieceReportBatcher,
    TeeConsumer,
    hedged_fetch,
)
from .storage import DaemonStorage
from .traffic_shaper import TrafficShaper

if TYPE_CHECKING:  # the wiring-time scheduler arms (no runtime import cycle)
    from ..rpc.scheduler_client import RemoteScheduler
    from ..rpc.steering import SteeringSchedulerClient


class PieceFetcher(Protocol):
    def fetch(self, parent_host_id: str, task_id: str, number: int) -> bytes:
        """Fetch one piece from a parent; raises on failure."""
        ...

    def piece_bitmap(self, parent_host_id: str, task_id: str):
        """Optional piece-metadata sync: bytes (1 per held piece) or None."""
        ...


class SourceFetcher(Protocol):
    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        """Back-to-source: fetch piece N of the origin content."""
        ...


class _SourceFetchError(Exception):
    """Internal: a back-to-source piece fetch failed (task-fatal)."""


def _expected_piece_len(content_length: int, piece_size: int, number: int) -> int:
    """Exact byte length piece `number` must have, or -1 when the task's
    sizing is unknown.  Every fetch path checks its body against this —
    a truncated piece (torn connection, misbehaving parent, injected
    truncate fault) must surface as a FETCH FAILURE to retry/reschedule,
    never be committed as silent corruption."""
    if content_length < 0 or piece_size <= 0:
        return -1
    return max(0, min(piece_size, content_length - number * piece_size))


@dataclass
class DownloadResult:
    ok: bool
    task_id: str
    peer_id: str
    pieces: int = 0
    bytes: int = 0
    back_to_source: bool = False
    failed_pieces: int = 0
    cost_s: float = 0.0
    # True when the bytes came from local storage or a concurrent run of
    # the same task — no new swarm traffic (peertask_reuse.go:49,
    # PeerTaskCacheHitCount).
    reused: bool = False


class TaskRun:
    """Live download state for one task: the subscriber seam streams and
    duplicate downloads attach to (peertask_manager's conductor map +
    SubscribeResponse piece channel, peertask_manager.go:428-437).

    Piece commits and completion signal one shared condition; readers
    wait for "piece N ready" or "run finished".
    """

    def __init__(self, task_id: str) -> None:
        self.task_id = task_id
        self.cond = threading.Condition()
        self.ready: Set[int] = set()
        self.n_pieces = -1
        self.piece_size = 0
        self.content_length = -1
        self.done = False
        self.result: Optional[DownloadResult] = None
        # Pass-through read plane (DESIGN.md §25): every commit path
        # publishes the verified body here; stream consumers (proxy,
        # gateway) register before the download starts and serve bytes
        # with zero disk reads on the fast path.
        self.tee = CommitTee()
        # Byte-range hints from ranged open_stream callers: the piece
        # pull orders the overlapping piece window FIRST so a Range
        # client's bytes arrive before the rest of the task.
        self._range_hints: List[Tuple[int, Optional[int]]] = []
        # The download span's context, recorded when the owned download
        # starts — pass-through serves (the `daemon/stream` span) ride
        # it so they land on the download's trace.
        self.traceparent: Optional[str] = None

    def publish(self, number: int, data: bytes) -> None:
        """Offer a verified piece body to the stream consumers (commit
        paths call this alongside the disk write)."""
        self.tee.publish(number, data)

    def add_range_hint(self, start: int, length: Optional[int]) -> None:
        with self.cond:
            self._range_hints.append((start, length))

    def range_hints(self) -> List[Tuple[int, Optional[int]]]:
        with self.cond:
            return list(self._range_hints)

    def priority_pieces(self, piece_size: int, n_pieces: int) -> Set[int]:
        """Piece numbers covered by any registered byte-range hint."""
        window: Set[int] = set()
        if piece_size <= 0 or n_pieces <= 0:
            return window
        for start, length in self.range_hints():
            first = max(start, 0) // piece_size
            if length is None:
                last = n_pieces - 1
            elif length <= 0:
                continue
            else:
                last = (start + length - 1) // piece_size
            window.update(range(min(first, n_pieces), min(last + 1, n_pieces)))
        return window

    def mark_sized(self, n_pieces: int, piece_size: int, content_length: int) -> None:
        with self.cond:
            self.n_pieces = n_pieces
            self.piece_size = piece_size
            self.content_length = content_length
            self.cond.notify_all()

    def mark_piece(self, number: int) -> None:
        with self.cond:
            self.ready.add(number)
            self.cond.notify_all()

    def finish(self, result: DownloadResult) -> None:
        with self.cond:
            self.done = True
            self.result = result
            self.cond.notify_all()

    def wait_sized(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.n_pieces < 0 and not self.done:
                left = deadline - time.monotonic()
                if left <= 0 or not self.cond.wait(min(left, 1.0)):
                    if time.monotonic() >= deadline:
                        return False
            return self.n_pieces >= 0

    def wait_piece(self, number: int, timeout: float) -> str:
        """→ 'ready' | 'eof' (complete, piece out of range) | 'failed' |
        'timeout'."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while True:
                if number in self.ready:
                    return "ready"
                if self.done:
                    r = self.result
                    if r is not None and r.ok and 0 <= self.n_pieces <= number:
                        return "eof"
                    # A finished-ok run has every in-range piece in
                    # `ready`; done without this one means failure.
                    return "failed"
                left = deadline - time.monotonic()
                if left <= 0:
                    return "timeout"
                self.cond.wait(min(left, 1.0))

    def wait_done(self, timeout: Optional[float] = None) -> Optional[DownloadResult]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            while not self.done:
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return None
                self.cond.wait(1.0 if left is None else min(left, 1.0))
            return self.result


@dataclass
class _SwarmState:
    """Worker-pool shared state for one task's P2P phase (the piece
    dispatcher + peer packet state of peertask_conductor.go, folded into
    one lock-guarded record)."""

    parents: List[Peer]
    bitmaps: Dict[str, bytes] = field(default_factory=dict)
    failed: int = 0
    nbytes: int = 0
    hedges: int = 0
    last_refresh: float = 0.0
    lock: threading.Lock = field(default_factory=threading.Lock)
    abort: threading.Event = field(default_factory=threading.Event)
    latency: PieceLatencyTracker = field(default_factory=PieceLatencyTracker)


class Conductor:
    def __init__(
        self,
        host: Host,
        storage: DaemonStorage,
        scheduler: "Union[SchedulerService, RemoteScheduler, SteeringSchedulerClient]",
        piece_fetcher: PieceFetcher,
        source_fetcher: Optional[SourceFetcher] = None,
        *,
        traffic_shaper: Optional[TrafficShaper] = None,
        max_piece_retries: int = 2,
        piece_parallelism: int = 4,
        piece_poll_interval_s: float = 0.05,
        piece_wait_timeout_s: float = 60.0,
        concurrent_source_groups: int = 1,
        concurrent_source_threshold: int = 2,
        pipeline_depth: int = 4,
        batch_reports: bool = True,
        report_linger_s: float = 0.02,
        hedge_enabled: bool = True,
        hedge_min_samples: int = 16,
        hedge_floor_s: float = 0.05,
        hedge_multiplier: float = 1.5,
        stream_tee_depth: int = 8,
        tenant: str = "",
        native_fetch: bool = True,
        pex=None,
    ) -> None:
        self.host = host
        self.storage = storage
        self.scheduler = scheduler
        # Tenant identity (DESIGN.md §26): stamped on every register
        # this conductor makes; "" rides as the default tenant.
        self.tenant = tenant
        self.piece_fetcher = piece_fetcher
        self.source_fetcher = source_fetcher
        # Optional PeerExchange (daemon/pex.py): piece-holder discovery
        # that survives scheduler outages — registration failures fall
        # back to gossip-discovered parents (pex peer_pool semantics).
        self.pex = pex
        self.traffic_shaper = traffic_shaper
        self.max_piece_retries = max_piece_retries
        # Piece workers per task (peertask_conductor.go:1010 count=4).
        self.piece_parallelism = max(1, piece_parallelism)
        # "No valid piece temporarily": how often to re-poll holder
        # bitmaps, and how long a wanted piece may stay unclaimed before
        # the P2P phase gives up (→ back-to-source).
        self.piece_poll_interval_s = piece_poll_interval_s
        self.piece_wait_timeout_s = piece_wait_timeout_s
        # Subscription window when a worker is STARVED (no holder for its
        # piece): the long-poll parks on the parent's piece plane for up
        # to this long instead of hammering it every poll interval — over
        # HTTP that's the difference between 1 request/s and 20/s per
        # parent while waiting on a mid-download swarm.
        self.piece_subscribe_window_s = max(piece_poll_interval_s, 1.0)
        # Concurrent back-to-source (piece_manager.go:793-873 semantics):
        # split the remaining pieces into `groups` contiguous range groups,
        # one worker per group, any worker failure cancels the task.  Only
        # engages when at least `threshold` pieces remain — tiny remainders
        # aren't worth the fan-out.
        self.concurrent_source_groups = max(1, concurrent_source_groups)
        self.concurrent_source_threshold = max(1, concurrent_source_threshold)
        # Data-plane pipeline (DESIGN.md §22): commit piece N (digest +
        # storage + report enqueue) on a committer thread while the
        # worker fetches N+1; 0 = the pre-pipeline inline path (the
        # benchmark's reference arm).  batch_reports coalesces per-piece
        # finished reports into bounded-linger report_pieces_finished
        # RPCs; hedging races a second parent for p99 stragglers once
        # `hedge_min_samples` fetches have established a baseline.
        self.pipeline_depth = max(0, pipeline_depth)
        self.batch_reports = batch_reports
        self.report_linger_s = report_linger_s
        self.hedge_enabled = hedge_enabled
        self.hedge_min_samples = hedge_min_samples
        self.hedge_floor_s = hedge_floor_s
        self.hedge_multiplier = hedge_multiplier
        # Pass-through read plane (DESIGN.md §25): per-consumer tee
        # buffer depth in pieces; 0 disables the tee (stream consumers
        # read every piece back off disk — the bench's reference arm).
        self.stream_tee_depth = max(0, stream_tee_depth)
        # Native data plane, client half (DESIGN.md §28): when every gate
        # passes (native store engine, plain-HTTP parents, no tee
        # consumers, no piece-plane chaos scenario), a piece window
        # drains through the in-engine fetch loop; any piece it cannot
        # land falls back into the Python path below, byte-identically.
        self.native_fetch = native_fetch
        # Storage writes + piece-run bookkeeping from concurrent source
        # workers are serialized; the origin fetch AND the scheduler
        # report overlap (the report is an RPC on remote wirings — it
        # must never run under this lock; dflint DF008 enforces that).
        self._report_lock = threading.Lock()
        # task_id → active TaskRun (findPeerTaskConductor semantics: one
        # conductor per task; later requests attach, never double-fetch).
        self._runs: Dict[str, TaskRun] = {}
        self._runs_mu = threading.Lock()

    def probe_content_length(self, url: str) -> Optional[int]:
        """Origin size via the source fetcher, when it can tell (shared by
        the control API, the seeder, and the CLI --download path)."""
        source = self.source_fetcher
        if source is not None and hasattr(source, "content_length"):
            return source.content_length(url)
        return None

    # -- task id / reuse -----------------------------------------------------

    def _task_id(self, url: str, task_id: Optional[str]) -> str:
        if task_id:
            return task_id
        from ..utils import idgen

        return idgen.task_id(url)

    def _complete_locally(self, task_id: str) -> bool:
        """True when every piece of the task is committed on disk."""
        n = self.storage.n_pieces(task_id)
        return n >= 0 and self.storage.held_pieces(task_id) >= n

    def _reuse_result(self, task_id: str, t0: float) -> DownloadResult:
        n = max(self.storage.n_pieces(task_id), 0)
        return DownloadResult(
            ok=True, task_id=task_id, peer_id="", pieces=n,
            bytes=self.storage.task_bytes(task_id), reused=True,
            cost_s=time.monotonic() - t0,
        )

    def _claim(self, task_id: str):
        """→ (run, owner): attach to an active run, or own a fresh one."""
        with self._runs_mu:
            run = self._runs.get(task_id)
            if run is not None and not run.done:
                return run, False
            run = TaskRun(task_id)
            self._runs[task_id] = run
            return run, True

    def active_run(self, task_id: str) -> Optional[TaskRun]:
        with self._runs_mu:
            run = self._runs.get(task_id)
            return run if run is not None and not run.done else None

    def _run_piece_pool(
        self,
        pending: "deque",
        fetch_one,
        *,
        abort: threading.Event,
        name: str,
        traceparent: Optional[str] = None,
    ) -> None:
        """ONE worker-pool harness for both piece planes (scheduled
        parents and the pex fallback): min(piece_parallelism, |pending|)
        workers drain the queue; ``fetch_one(number) -> bool`` returning
        False — or raising — aborts the POOL (a silently-dead worker
        would let siblings drain `pending` and report a "successful"
        download with its popped piece missing).  Joins before returning;
        `abort or pending` afterwards means failure."""
        if not pending:
            return
        lock = threading.Lock()

        def drain() -> None:
            while not abort.is_set():
                with lock:
                    if not pending:
                        return
                    number = pending.popleft()
                if not fetch_one(number):
                    abort.set()
                    return

        def worker() -> None:
            try:
                if traceparent is not None:
                    # One span per worker, linked into the caller's
                    # download trace so the worker thread's own RPCs and
                    # its per-piece ``daemon/piece`` spans propagate the
                    # same trace id (the durable log head-samples by
                    # trace id, so a 10k-piece task only lands in full
                    # on sampled traces).
                    from ..utils.tracing import default_tracer

                    with default_tracer.remote_span(
                        f"daemon/{name}", traceparent
                    ):
                        drain()
                else:
                    drain()
            except Exception:  # noqa: BLE001 — abort, don't die silently
                import logging

                abort.set()
                logging.getLogger(__name__).warning(
                    "piece worker aborted (%s)", name, exc_info=True
                )

        threads = [
            threading.Thread(target=worker, name=f"{name}-{i}", daemon=True)
            for i in range(min(self.piece_parallelism, len(pending)))
        ]
        for t in threads:
            t.start()
        for t in threads:
            # Bounded join loop (DF008 timeout sweep): a wedged worker
            # surfaces in the faulthandler watchdog dump instead of
            # parking this thread invisibly forever.
            while t.is_alive():
                t.join(5.0)

    @staticmethod
    def _order_pending(
        numbers, run: Optional[TaskRun], piece_size: int, n_pieces: int
    ) -> "deque":
        """Range-priority piece ordering (DESIGN.md §25): pieces inside
        any ranged stream's window come FIRST (ascending — the reader is
        in-order), then the rest ascending.  No hints → natural order."""
        nums = list(numbers)
        if run is None:
            return deque(nums)
        window = run.priority_pieces(piece_size, n_pieces)
        if not window or len(window) >= len(nums):
            return deque(nums)
        nums.sort(key=lambda n: (n not in window, n))
        return deque(nums)

    # -- the main flow (peertask_conductor.go:370 start → pullPieces) --------

    def download(
        self,
        url: str,
        *,
        piece_size: int = 4 << 20,
        content_length: Optional[int] = None,
        expected_pieces: Optional[int] = None,
        source_headers: Optional[dict] = None,
        priority: Priority = Priority.LEVEL0,
        task_id: Optional[str] = None,
    ) -> DownloadResult:
        """``source_headers`` ride along to the origin fetcher (preheat of
        authenticated registry blobs carries the pull token this way);
        they travel per-call — the Conductor is shared across concurrent
        downloads and must not bleed one download's credentials into
        another's origin requests."""
        t0 = time.monotonic()
        tid = self._task_id(url, task_id)
        # Reuse-first (peertask_reuse.go:49): a completed local task
        # serves from disk with no scheduler contact at all.
        if self._complete_locally(tid):
            return self._reuse_result(tid, t0)
        run, owner = self._claim(tid)
        if not owner:
            # Another thread is already downloading this task — attach
            # instead of double-fetching (findPeerTaskConductor).
            result = run.wait_done()
            if result is not None and result.ok:
                r = self._reuse_result(tid, t0)
                r.back_to_source = result.back_to_source
                return r
            return DownloadResult(
                ok=False, task_id=tid, peer_id="",
                cost_s=time.monotonic() - t0,
            )
        return self._download_owned(
            run, url, piece_size=piece_size, content_length=content_length,
            expected_pieces=expected_pieces, source_headers=source_headers,
            priority=priority, t0=t0,
        )

    # -- streaming (StartStreamTask, peertask_manager.go:357-423) ------------

    def open_stream(
        self,
        url: str,
        *,
        piece_size: int = 4 << 20,
        content_length: Optional[int] = None,
        source_headers: Optional[dict] = None,
        priority: Priority = Priority.LEVEL0,
        task_id: Optional[str] = None,
        sizing_timeout_s: float = 30.0,
        start: int = 0,
        length: Optional[int] = None,
        tee: bool = True,
    ) -> "StreamHandle":
        """Serve the task's bytes AS PIECES COMMIT: reuse a completed
        task, attach to a running one, or start the download in the
        background — the proxy and the object gateway consume this so a
        response starts before the task finishes.

        ``start``/``length`` open a RANGED stream: only the byte window
        is served, and the overlapping piece window is scheduled FIRST
        (range-priority ordering in the piece pull) so an HTTP Range
        client's bytes arrive ahead of the rest of the task.  With
        ``tee`` (default), the handle registers a commit-tee consumer
        and serves published pieces with zero disk reads; ``tee=False``
        (or ``stream_tee_depth=0``) keeps the disk round-trip path.
        """
        tid = self._task_id(url, task_id)
        if self._complete_locally(tid):
            return StreamHandle(self, tid, None, start=start, length=length)
        run, owner = self._claim(tid)
        # Register the consumer and the range hint BEFORE the download
        # thread starts: the piece pull then sees the hint when it
        # orders its queue, and the tee never publishes past us (pieces
        # committed before registration sit on disk — the spill path).
        if start > 0 or length is not None:
            run.add_range_hint(start, length)
        consumer = (
            run.tee.register(depth=self.stream_tee_depth)
            if tee and self.stream_tee_depth > 0
            else None
        )
        if owner:
            t = threading.Thread(
                target=self._download_quiet,
                args=(run, url),
                kwargs=dict(
                    piece_size=piece_size, content_length=content_length,
                    expected_pieces=None, source_headers=source_headers,
                    priority=priority, t0=time.monotonic(),
                ),
                name=f"stream-dl-{tid[:8]}",
                daemon=True,
            )
            t.start()
        if not run.wait_sized(sizing_timeout_s):
            if consumer is not None:
                consumer.close()
            raise IOError(f"stream {tid}: sizing timed out")
        return StreamHandle(
            self, tid, run, consumer=consumer, start=start, length=length
        )

    def _download_quiet(self, run: TaskRun, url: str, **kw) -> None:
        """Background-thread face of _download_owned: failures land on the
        run (subscribers see 'failed'), not on an orphan thread traceback."""
        import logging

        try:
            self._download_owned(run, url, **kw)
        except Exception:  # noqa: BLE001 — recorded on the run
            logging.getLogger(__name__).warning(
                "stream download of %s failed", run.task_id, exc_info=True
            )

    def _download_owned(
        self,
        run: TaskRun,
        url: str,
        *,
        piece_size: int,
        content_length: Optional[int],
        expected_pieces: Optional[int],
        source_headers: Optional[dict],
        priority: Priority,
        t0: float,
    ) -> DownloadResult:
        try:
            result = self._download_inner(
                run, url, piece_size=piece_size,
                content_length=content_length,
                expected_pieces=expected_pieces,
                source_headers=source_headers, priority=priority, t0=t0,
            )
        except BaseException:
            result = DownloadResult(
                ok=False, task_id=run.task_id, peer_id="",
                cost_s=time.monotonic() - t0,
            )
            raise
        finally:
            run.finish(result)
            with self._runs_mu:
                if self._runs.get(run.task_id) is run:
                    self._runs.pop(run.task_id)
        return result

    def _download_inner(
        self,
        run: TaskRun,
        url: str,
        *,
        piece_size: int,
        content_length: Optional[int],
        expected_pieces: Optional[int],
        source_headers: Optional[dict],
        priority: Priority,
        t0: float,
    ) -> DownloadResult:
        # Download-scope span: every scheduler RPC made on this thread
        # injects this context, so the server's handler spans link into
        # ONE trace per download (otel task-span analog).
        from ..utils.tracing import default_tracer

        with default_tracer.span(
            "daemon/download", task_id=run.task_id, url=url
        ) as span:
            # Pass-through serves link here: the `daemon/stream` span
            # carries this context so a proxy/gateway serve lands on the
            # download's trace, not as an orphan root.
            run.traceparent = span.traceparent
            result = self._download_registered(
                run, url, piece_size=piece_size,
                content_length=content_length,
                expected_pieces=expected_pieces,
                source_headers=source_headers, priority=priority, t0=t0,
            )
            span.set(
                ok=result.ok, pieces=result.pieces,
                back_to_source=result.back_to_source,
            )
            return result

    def _download_registered(
        self,
        run: TaskRun,
        url: str,
        *,
        piece_size: int,
        content_length: Optional[int],
        expected_pieces: Optional[int],
        source_headers: Optional[dict],
        priority: Priority,
        t0: float,
    ) -> DownloadResult:
        try:
            reg = self.scheduler.register_peer(
                host=self.host, url=url, priority=priority,
                task_id=run.task_id, tenant=self.tenant,
            )
        except Exception:
            # Scheduler unreachable: gossip keeps the swarm serving
            # (pex reclaim/pool semantics — peers found WITHOUT the
            # control plane).  No pex or no sizing → the failure is real.
            if self.pex is None or not content_length or content_length < 0:
                raise
            return self._pull_via_pex(run, url, piece_size, content_length, t0)
        peer = reg.peer
        task = peer.task

        if reg.direct_piece:
            # TINY shortcut: the content arrived inline with registration —
            # no piece transfer at all (service_v1 tiny response).
            self.storage.register_task(
                task.id, piece_size=piece_size, content_length=len(reg.direct_piece)
            )
            run.publish(0, reg.direct_piece)
            self.storage.write_piece(task.id, 0, reg.direct_piece)
            run.mark_sized(1, piece_size, len(reg.direct_piece))
            run.mark_piece(0)
            self.scheduler.report_piece_finished(
                peer, 0, parent_id="", length=len(reg.direct_piece), cost_ns=1
            )
            self.scheduler.report_peer_finished(peer)
            if self.pex is not None:
                self.pex.advertise(task.id, {0})
            return DownloadResult(
                ok=True, task_id=task.id, peer_id=peer.id, pieces=1,
                bytes=len(reg.direct_piece), cost_s=time.monotonic() - t0,
            )

        # First peer in the swarm learns content length from the origin and
        # reports it through the scheduler API (so remote schedulers learn).
        if task.content_length < 0:
            if content_length is None or content_length < 0:
                # -1 is the source clients' "origin won't say" sentinel:
                # proceeding would register a 0-piece task and report a
                # hollow success.
                return self._fail(peer, t0, "unknown content length")
            n_pieces = (
                expected_pieces
                if expected_pieces is not None
                else (content_length + piece_size - 1) // piece_size
            )
            self.scheduler.set_task_info(peer, content_length, n_pieces, piece_size)
        piece_size = task.piece_size or piece_size
        n_pieces = task.total_piece_count

        self.storage.register_task(
            task.id, piece_size=piece_size, content_length=task.content_length
        )
        run.mark_sized(n_pieces, piece_size, task.content_length)
        # Partial reuse: pieces already on disk (crashed/abandoned earlier
        # run) are ready for subscribers and skipped by the workers
        # (local_storage_subtask / FindPartialCompletedTask semantics).
        if n_pieces > 0:
            for n in self.storage.piece_bitmap(task.id, n_pieces).nonzero()[0]:
                run.mark_piece(int(n))
        if self.traffic_shaper is not None:
            self.traffic_shaper.add_task(task.id)
        try:
            if reg.schedule is not None and reg.schedule.kind is ScheduleResultKind.PARENTS:
                result = self._pull_from_parents(
                    peer, reg.schedule.parents, n_pieces, t0, run
                )
                if result is not None:
                    return result
                # P2P path exhausted → back-to-source (dfget.go:141 fallback).
            return self._pull_from_source(
                peer, n_pieces, piece_size, t0, source_headers, run
            )
        finally:
            if self.traffic_shaper is not None:
                self.traffic_shaper.remove_task(task.id)

    def _pull_via_pex(
        self, run: TaskRun, url: str, piece_size: int, content_length: int,
        t0: float,
    ) -> DownloadResult:
        """Scheduler-less download: gossip-discovered holders serve pieces
        directly (the pex pool is the only metadata source)."""
        task_id = run.task_id
        n_pieces = (content_length + piece_size - 1) // piece_size
        self.storage.register_task(
            task_id, piece_size=piece_size, content_length=content_length
        )
        run.mark_sized(n_pieces, piece_size, content_length)
        pending_nums = []
        for number in range(n_pieces):
            if self.storage.has_piece(task_id, number):
                run.mark_piece(number)
            else:
                pending_nums.append(number)
        pending = self._order_pending(pending_nums, run, piece_size, n_pieces)
        lock = threading.Lock()
        abort = threading.Event()
        counters = {"nbytes": 0, "done": 0}

        def fetch_one(number: int) -> bool:
            # Gossip holders stand in for the parent list (no scheduler
            # to report to); no holder serving the piece fails the task.
            for holder in self.pex.find_peers_with_piece(task_id, number):
                if holder == self.host.id:
                    continue
                try:
                    data = self.piece_fetcher.fetch(holder, task_id, number)
                except Exception as exc:  # noqa: BLE001 — next holder
                    logging.getLogger(__name__).debug(
                        "pex fetch piece %d from %s: %s", number, holder, exc
                    )
                    continue
                if len(data) != _expected_piece_len(
                    content_length, piece_size, number
                ):
                    continue  # torn body — try the next holder
                run.publish(number, data)
                self.storage.write_piece(task_id, number, data)
                run.mark_piece(number)
                with lock:
                    counters["nbytes"] += len(data)
                    counters["done"] += 1
                return True
            return False

        from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

        self._run_piece_pool(
            pending, fetch_one, abort=abort, name="pex-worker",
            traceparent=default_tracer.inject().get(TRACEPARENT_HEADER),
        )
        if abort.is_set() or pending:
            return DownloadResult(
                ok=False, task_id=task_id, peer_id="",
                pieces=counters["done"], bytes=counters["nbytes"],
                cost_s=time.monotonic() - t0,
            )
        self.pex.advertise(task_id, set(range(n_pieces)))
        return DownloadResult(
            ok=True, task_id=task_id, peer_id="", pieces=n_pieces,
            bytes=counters["nbytes"], cost_s=time.monotonic() - t0,
        )

    # -- the in-engine fetch window (DESIGN.md §28) ---------------------------

    def _native_fetch_window(
        self, task, run: TaskRun, state: "_SwarmState", pending,
        report_finished,
    ) -> None:
        """One native pass over the pending window: pieces whose chosen
        parent has a dialable plain-HTTP endpoint go to the in-engine
        fetch loop (``native.pf_*`` — pooled keep-alive fetch → length
        check → crc+fsync commit, zero Python per-piece overhead); this
        thread drains the bounded completion queue and does the per-piece
        bookkeeping.  Python keeps every SCHEDULING decision — parent
        selection happens here before submit, and any non-zero completion
        status simply leaves the piece in ``pending`` for the ordinary
        retry/hedge/reschedule machinery below.  One attempt per piece:
        hedging needs the latency tracker's clock around a single fetch,
        so stragglers re-enter the Python arm rather than hedge natively.

        Fallback matrix (§28) — the byte-identical Python arm takes over
        whole when: the knob is off, storage is not the native engine,
        the transport cannot be dialed natively (TLS), a stream consumer
        is attached (the tee needs verified bodies in Python), the
        installed fault scenario targets the piece plane (the engine
        cannot fire Python seams per piece), or the library is absent.
        """
        from ..utils import faultinject

        if not self.native_fetch or not pending:
            return
        if not getattr(self.storage, "is_native", False):
            return
        endpoint_of = getattr(self.piece_fetcher, "native_endpoint", None)
        if endpoint_of is None:
            return
        if run.tee.consumer_count() > 0:
            return
        if faultinject.targets(
            "piece.fetch", "piece.fetch.body", "daemon.stream.tee"
        ):
            return
        from .. import native

        if not native.available():
            return
        # Dispatch seam (DF004): a raising fault forces the Python arm;
        # the crash kind SIGKILLs mid-window — the resumability drill's
        # deterministic kill switch for the native path.
        try:
            faultinject.fire("daemon.piece.native_fetch")
        except Exception as exc:  # noqa: BLE001 — injected: Python arm
            logging.getLogger(__name__).debug(
                "native fetch dispatch faulted (%s): Python arm", exc
            )
            return

        with state.lock:
            plist = list(state.parents)
            bitmaps = dict(state.bitmaps)
        endpoints: Dict[str, tuple] = {}
        for p in plist:
            ep = endpoint_of(p.host.id)
            if ep is not None:
                endpoints[p.id] = ep
        if not endpoints:
            return

        def holds_snap(pid: str, number: int) -> bool:
            bm = bitmaps.get(pid)
            return bm is None or (number < len(bm) and bool(bm[number]))

        from ..utils.tracing import default_tracer

        log = logging.getLogger(__name__)
        fetcher = None
        succeeded: Set[int] = set()
        try:
            fetcher = native.NativePieceFetcher(
                self.storage.engine,
                workers=max(self.piece_parallelism, 1),
                tenant=self.tenant,
            )
            slot_of: Dict[str, int] = {}
            id_by_slot: Dict[int, str] = {}
            for pid, (ip, port) in endpoints.items():
                slot = len(slot_of)
                fetcher.set_parent(slot, ip, int(port))
                slot_of[pid] = slot
                id_by_slot[slot] = pid
            n_submitted = 0
            for number in list(pending):
                holders = [
                    p for p in plist
                    if p.id in slot_of and holds_snap(p.id, number)
                ]
                if not holders:
                    continue  # Python path polls bitmaps for this one
                parent = holders[number % len(holders)]
                expected = _expected_piece_len(
                    task.content_length, task.piece_size, number
                )
                # expected 0 → the engine skips the length check (unknown
                # content length); the crc at commit still gates the body.
                if fetcher.submit(
                    task.id, slot_of[parent.id], number, max(expected, 0)
                ):
                    n_submitted += 1
            ndone = 0
            deadline = time.monotonic() + self.piece_wait_timeout_s
            while ndone < n_submitted and time.monotonic() < deadline:
                for number, status, length, slot, cost_ns in fetcher.complete(
                    timeout_ms=1000
                ):
                    ndone += 1
                    # Same seam, per drained record: the chaos drill's
                    # crash kind lands the SIGKILL between a C++ commit
                    # and its Python bookkeeping — the worst spot for
                    # durability; a raise aborts the window and the
                    # Python arm re-fetches whatever went un-booked.
                    faultinject.fire("daemon.piece.native_fetch")
                    if status != 0:
                        continue  # stays pending → Python retry/hedge
                    parent_id = id_by_slot.get(slot, "")
                    # Same per-piece flight-recorder evidence as the
                    # Python arm (DF016's daemon/piece witness), opened
                    # at drain time from the engine's cost clock.
                    with default_tracer.span(
                        "daemon/piece", number=number, task_id=task.id
                    ) as sp:
                        sp.set(parent=parent_id, bytes=length, native=True)
                    run.mark_piece(number)
                    with state.lock:
                        state.nbytes += length
                    if self.traffic_shaper is not None:
                        self.traffic_shaper.record(task.id, length)
                    report_finished(number, parent_id, length,
                                    max(int(cost_ns), 1))
                    succeeded.add(number)
        except Exception as exc:  # noqa: BLE001 — window is best-effort
            # Whatever did not land stays in `pending`; the Python arm
            # owns it from here (a latched reporter error re-raises there
            # with its ordinary abort semantics).
            log.debug("native fetch window stopped: %s", exc)
        finally:
            if fetcher is not None:
                fetcher.close()
            if succeeded:
                # Commits bypassed DaemonStorage.write_piece — restore
                # the LRU-reclaim evidence in one touch.
                touch = getattr(self.storage, "touch_task", None)
                if touch is not None:
                    touch(task.id)
                remaining = [n for n in pending if n not in succeeded]
                pending.clear()
                pending.extend(remaining)

    # -- the concurrent P2P phase -------------------------------------------

    def _pull_from_parents(
        self, peer: Peer, parents: List[Peer], n_pieces: int, t0: float,
        run: TaskRun,
    ) -> Optional[DownloadResult]:
        """Piece workers over the assigned parents; None → fall to source.

        peertask_conductor.go:1009-1077 shape: ``piece_parallelism``
        workers drain one shared queue of missing pieces; each picks a
        parent that holds its piece per the bitmap sync, polls for
        unclaimed pieces (mid-download parents advertise pieces as they
        land — piecetask_synchronizer semantics), and any worker can
        adopt server-pushed reschedules for the whole pool.
        """
        task = peer.task
        state = _SwarmState(
            parents=list(parents),
            latency=PieceLatencyTracker(
                min_samples=self.hedge_min_samples,
                floor_s=self.hedge_floor_s,
                multiplier=self.hedge_multiplier,
            ),
        )
        self._refresh_bitmaps(task.id, state, force=True)

        # Resume: pieces already on disk are NOT refetched and NOT
        # per-piece reported (a large partial task would cost thousands of
        # sequential RPCs before the first fetch); the closing
        # report_peer_finished settles the scheduler's task/peer state,
        # and other children learn held pieces from the piece plane's
        # bitmaps, not from the scheduler.
        held = self.storage.piece_bitmap(task.id, n_pieces) if n_pieces > 0 else []
        pending = self._order_pending(
            (n for n in range(n_pieces) if not held[n]), run,
            task.piece_size, n_pieces,
        )

        # Report path: batched (one report_pieces_finished per linger
        # window) or direct per-piece calls.  Commit path: pipelined
        # (digest piece N while N+1 is on the wire) or inline.  Both
        # default ON; the benchmark's reference arm turns them off.
        from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

        reporter = (
            PieceReportBatcher(
                self.scheduler, peer, linger_s=self.report_linger_s,
                traceparent=default_tracer.inject().get(TRACEPARENT_HEADER),
            )
            if self.batch_reports
            else None
        )

        def report_finished(number: int, parent_id: str, length: int,
                            cost_ns: int) -> None:
            if reporter is not None:
                if not reporter.submit(number, parent_id, length, cost_ns):
                    raise reporter.error or IOError("report batcher closed")
            else:
                self.scheduler.report_piece_finished(
                    peer, number, parent_id=parent_id, length=length,
                    cost_ns=cost_ns,
                )

        def commit_piece(number: int, data: bytes, parent_id: str,
                         cost_ns: int) -> None:
            """Digest (crc at write) + persist + mark + report enqueue:
            runs on the committer thread when pipelined, inline in the
            worker otherwise — identical semantics either way."""
            # Tee first (DESIGN.md §25): stream consumers get the
            # verified body alongside the disk write — the pass-through
            # fast path never reads back what was just written.
            run.publish(number, data)
            self.storage.write_piece(task.id, number, data)
            run.mark_piece(number)
            with state.lock:
                state.nbytes += len(data)
            if self.traffic_shaper is not None:
                self.traffic_shaper.record(task.id, len(data))
            report_finished(number, parent_id, len(data), cost_ns)

        pipeline = (
            CommitPipeline(commit_piece, depth=self.pipeline_depth)
            if self.pipeline_depth > 0
            else None
        )

        take_pushed = getattr(self.scheduler, "take_pushed_schedule", None)

        def apply_push() -> None:
            """Adopt a server-pushed reschedule (v2 bidi wire) for the
            whole worker pool."""
            if take_pushed is None:
                return
            res = take_pushed(peer)
            if res is None:
                return
            if res.kind is ScheduleResultKind.PARENTS and res.parents:
                with state.lock:
                    state.parents = list(res.parents)
                self._refresh_bitmaps(task.id, state, force=True)
            elif res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
                state.abort.set()  # pool stops; caller falls to source

        def holds(parent: Peer, number: int) -> bool:
            with state.lock:
                bm = state.bitmaps.get(parent.id)
            return bm is None or (number < len(bm) and bool(bm[number]))

        def fetch_one(number: int) -> bool:
            """Fetch piece `number`; True on success, False → task-level
            abort is set.  One ``daemon/piece`` span per piece (bytes,
            parent, retry count — the flight recorder's per-piece
            evidence; head-sampling keeps a 10k-piece task from flooding
            the durable log on every trace)."""
            from ..utils.tracing import default_tracer

            with default_tracer.span(
                "daemon/piece", number=number, task_id=task.id
            ) as piece_span:
                return fetch_one_traced(number, piece_span)

        def fetch_one_traced(number: int, piece_span) -> bool:
            deadline = time.monotonic() + self.piece_wait_timeout_s
            attempt = 0
            while not state.abort.is_set():
                apply_push()
                with state.lock:
                    plist = list(state.parents)
                if not plist:
                    state.abort.set()
                    return False
                holders = [p for p in plist if holds(p, number)]
                if not holders:
                    # "No valid piece temporarily": nobody claims it yet —
                    # poll holder bitmaps until a mid-download parent
                    # commits it (synchronizer analog), not a fetch error.
                    if time.monotonic() >= deadline:
                        state.abort.set()
                        return False
                    self._refresh_bitmaps(task.id, state)
                    time.sleep(self.piece_poll_interval_s)
                    continue
                parent = holders[(number + attempt) % len(holders)]
                expected = _expected_piece_len(
                    task.content_length, task.piece_size, number
                )
                # Hedge plan: once enough fetches establish a latency
                # baseline, a straggler races a SECOND holder through the
                # same fetch/breaker machinery — first valid body wins.
                threshold = (
                    state.latency.threshold_s() if self.hedge_enabled else None
                )
                by_id = {p.id: p for p in holders}
                alt_id = None
                if threshold is not None and len(holders) > 1:
                    cand = holders[(number + attempt + 1) % len(holders)]
                    if cand.id != parent.id:
                        alt_id = cand.id
                try:
                    t_piece = time.monotonic()
                    data, winner_id, hedged = hedged_fetch(
                        lambda pid: self.piece_fetcher.fetch(
                            by_id[pid].host.id, task.id, number
                        ),
                        lambda d: expected < 0 or len(d) == expected,
                        parent.id,
                        alt_id,
                        threshold_s=threshold,
                        wait_timeout_s=self.piece_wait_timeout_s,
                    )
                    if expected >= 0 and len(data) != expected:
                        raise IOError(
                            f"piece {number}: truncated body "
                            f"({len(data)} != {expected} bytes)"
                        )
                    cost_ns = max(int((time.monotonic() - t_piece) * 1e9), 1)
                    if hedged:
                        with state.lock:
                            state.hedges += 1
                    else:
                        # Only unhedged walls feed the baseline — a
                        # straggler's wall would drag the p99 toward the
                        # very tail the hedge exists to cut.
                        state.latency.observe(time.monotonic() - t_piece)
                except Exception:
                    with state.lock:
                        state.failed += 1
                    res = self.scheduler.report_piece_failed(peer, parent.id)
                    if res.kind is ScheduleResultKind.PARENTS and res.parents:
                        with state.lock:
                            state.parents = list(res.parents)
                        self._refresh_bitmaps(task.id, state, force=True)
                    elif res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
                        state.abort.set()
                        return False
                    attempt += 1
                    if attempt > self.max_piece_retries:
                        piece_span.set(retries=attempt, failed=True)
                        state.abort.set()
                        return False
                    continue
                piece_span.set(
                    parent=winner_id, bytes=len(data), retries=attempt,
                    hedged=hedged,
                )
                if pipeline is not None:
                    # Hand off to the committer: this worker goes straight
                    # to its next fetch while piece `number` digests.
                    if not pipeline.submit(number, data, winner_id, cost_ns):
                        state.abort.set()
                        return False
                else:
                    commit_piece(number, data, winner_id, cost_ns)
                return True
            return False

        # In-engine fast path first (§28): one native pass drains what it
        # can; whatever it leaves in `pending` flows to the Python workers
        # below, whose per-piece semantics are the reference arm.
        self._native_fetch_window(task, run, state, pending, report_finished)

        # Worker threads have their OWN (empty) span stacks; hand them the
        # download span's context so their piece reports stay in-trace.
        from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

        download_tp = default_tracer.inject().get(TRACEPARENT_HEADER)
        try:
            self._run_piece_pool(
                pending, fetch_one, abort=state.abort, name="piece-worker",
                traceparent=download_tp,
            )
        finally:
            # Drain in order: commits first (they enqueue reports), then
            # the report flush — every piece report lands BEFORE the
            # closing report_peer_finished, preserving the scheduler's
            # observable event order.
            commit_err = pipeline.close() if pipeline is not None else None
            report_err = reporter.close() if reporter is not None else None

        with state.lock:
            failed, nbytes = state.failed, state.nbytes
        if state.abort.is_set() or pending or commit_err or report_err:
            if commit_err or report_err:
                logging.getLogger(__name__).warning(
                    "p2p phase failed post-fetch (%s): falling to source",
                    commit_err or report_err,
                )
            return None  # fall to source (or honor pushed back-to-source)
        self.scheduler.report_peer_finished(peer)
        if self.pex is not None:
            self.pex.advertise(task.id, set(range(n_pieces)))
        return DownloadResult(
            ok=True,
            task_id=task.id,
            peer_id=peer.id,
            pieces=n_pieces,
            bytes=nbytes,
            failed_pieces=failed,
            cost_s=time.monotonic() - t0,
        )

    def _refresh_bitmaps(
        self, task_id: str, state: _SwarmState, *, force: bool = False
    ) -> None:
        """Piece-metadata sync (SyncPieceTasks analog): which pieces does
        each parent hold RIGHT NOW.  Rate-limited so a pool of pollers
        doesn't hammer the piece plane; `force` refreshes immediately
        (new parents adopted)."""
        if not hasattr(self.piece_fetcher, "piece_bitmap"):
            return
        wait = getattr(self.piece_fetcher, "wait_piece_bitmap", None)
        # Gate at the width of the refresh itself: with the subscription
        # available, ONE worker parks for the window while its siblings
        # skip (claiming last_refresh at entry) — not a fresh long-poller
        # every poll interval.
        gate = (
            self.piece_subscribe_window_s
            if (wait is not None and not force)
            else self.piece_poll_interval_s
        )
        now = time.monotonic()
        with state.lock:
            if not force and now - state.last_refresh < gate:
                return
            state.last_refresh = now
            plist = list(state.parents)
        # The WHOLE refresh is bounded by one window, split across
        # parents — serial full-window parks would delay abort/push/
        # deadline checks by len(parents) × window.
        per_parent_wait = (
            self.piece_subscribe_window_s / max(len(plist), 1)
            if plist else 0.0
        )
        for p in plist:
            if state.abort.is_set():
                return
            try:
                if wait is not None and not force:
                    with state.lock:
                        have = int(sum(state.bitmaps.get(p.id, b"")))
                    bm = wait(p.host.id, task_id, have, per_parent_wait)
                else:
                    bm = self.piece_fetcher.piece_bitmap(p.host.id, task_id)
            except Exception as exc:  # noqa: BLE001 — a dead parent just has no bitmap
                logging.getLogger(__name__).debug(
                    "bitmap from %s: %s", p.host.id, exc
                )
                bm = None
            if bm is not None:
                with state.lock:
                    state.bitmaps[p.id] = bm

    # -- back-to-source ------------------------------------------------------

    def _pull_from_source(
        self,
        peer: Peer,
        n_pieces: int,
        piece_size: int,
        t0: float,
        headers: Optional[dict] = None,
        run: Optional[TaskRun] = None,
    ) -> DownloadResult:
        task = peer.task
        if self.source_fetcher is None:
            return self._fail(peer, t0, "no source fetcher")
        self.scheduler.mark_back_to_source(peer)
        # Resume, don't restart: pieces already fetched from parents stay
        # on disk with their parent attribution intact — the origin only
        # serves what P2P didn't (piece_manager.go resumes from the
        # persisted piece bitmap the same way).
        missing = list(self._order_pending(
            (n for n in range(n_pieces) if not self.storage.has_piece(task.id, n)),
            run, task.piece_size or piece_size, n_pieces,
        ))
        groups = min(self.concurrent_source_groups, len(missing))
        try:
            if groups > 1 and len(missing) >= self.concurrent_source_threshold:
                nbytes = self._source_piece_groups(
                    peer, missing, piece_size, groups, headers, run
                )
            else:
                nbytes = 0
                for number in missing:
                    nbytes += self._source_one_piece(
                        peer, number, piece_size, headers, run
                    )
        except _SourceFetchError as e:
            return self._fail(peer, t0, str(e))
        self.scheduler.report_peer_finished(peer)
        if self.pex is not None:
            self.pex.advertise(task.id, set(range(n_pieces)))
        return DownloadResult(
            ok=True,
            task_id=task.id,
            peer_id=peer.id,
            pieces=n_pieces,
            bytes=nbytes,
            back_to_source=True,
            cost_s=time.monotonic() - t0,
        )

    def _source_one_piece(
        self,
        peer: Peer,
        number: int,
        piece_size: int,
        headers: Optional[dict] = None,
        run: Optional[TaskRun] = None,
    ) -> int:
        """Fetch piece `number` from the origin, persist + report it."""
        from ..source.client import call_with_optional_headers
        from ..utils.tracing import default_tracer

        task = peer.task
        t_piece = time.monotonic()
        with default_tracer.span(
            "daemon/source.piece", number=number, task_id=task.id
        ) as piece_span:
            try:
                data = call_with_optional_headers(
                    self.source_fetcher.fetch, task.url, number, piece_size,
                    headers=headers,
                )
            except Exception:
                raise _SourceFetchError(f"source fetch piece {number}")
            piece_span.set(bytes=len(data))
        expected = _expected_piece_len(task.content_length, piece_size, number)
        if expected >= 0 and len(data) != expected:
            # A short origin body persisted as a full piece would be
            # SILENT corruption (digest mismatch at read time, long after
            # the cause) — fail the task loudly instead.
            raise _SourceFetchError(
                f"source piece {number}: truncated body "
                f"({len(data)} != {expected} bytes)"
            )
        cost_ns = max(int((time.monotonic() - t_piece) * 1e9), 1)
        with self._report_lock:
            if run is not None:
                run.publish(number, data)
            self.storage.write_piece(task.id, number, data)
            if run is not None:
                run.mark_piece(number)
        # Scheduler reports run OUTSIDE the lock (DF008): the scheduler —
        # local service or RPC client — is thread-safe and piece reports
        # carry their own numbers, so ordering between workers is free.
        # Holding _report_lock across a report RPC would stall every
        # concurrent source worker on one slow scheduler round-trip (the
        # p2p piece path already reports unlocked).
        self.scheduler.report_piece_finished(
            peer, number, parent_id="", length=len(data), cost_ns=cost_ns
        )
        # First fetcher of a TINY task publishes the bytes inline so
        # later peers skip the transfer entirely.
        if (
            number == 0
            and 0 < task.content_length <= TINY_FILE_SIZE
            and hasattr(self.scheduler, "set_task_direct_piece")
        ):
            self.scheduler.set_task_direct_piece(
                peer, data[: task.content_length]
            )
        return len(data)

    def _source_piece_groups(
        self,
        peer: Peer,
        missing: Sequence[int],
        piece_size: int,
        groups: int,
        headers: Optional[dict] = None,
        run: Optional[TaskRun] = None,
    ) -> int:
        """Concurrent back-to-source by contiguous piece groups.

        piece_manager.go:793-873: `con` workers each own a contiguous slice
        of the remaining pieces (the first `remainder` groups take one extra);
        the first worker failure cancels the whole task.
        """
        per, rem = divmod(len(missing), groups)
        slices: List[Sequence[int]] = []
        start = 0
        for i in range(groups):
            size = per + (1 if i < rem else 0)
            slices.append(missing[start : start + size])
            start += size
        cancelled = threading.Event()

        def run_group(numbers: Sequence[int]) -> int:
            nbytes = 0
            for number in numbers:
                if cancelled.is_set():
                    raise _SourceFetchError("cancelled by sibling group")
                try:
                    nbytes += self._source_one_piece(
                        peer, number, piece_size, headers, run
                    )
                except Exception as e:
                    # Not just fetch failures: a write/report error
                    # (disk full, scheduler unreachable) is equally
                    # task-fatal and must cancel the siblings rather
                    # than escape past download()'s DownloadResult
                    # contract.
                    cancelled.set()
                    if isinstance(e, _SourceFetchError):
                        raise
                    raise _SourceFetchError(
                        f"piece {number}: {type(e).__name__}: {e}"
                    ) from e
            return nbytes

        with ThreadPoolExecutor(max_workers=groups) as pool:
            futures = [pool.submit(run_group, s) for s in slices]
            total = 0
            error: Optional[_SourceFetchError] = None
            for fut in futures:
                try:
                    total += fut.result()
                except _SourceFetchError as e:
                    error = error or e
        if error is not None:
            raise error
        return total

    def _fail(self, peer: Peer, t0: float, reason: str) -> DownloadResult:
        self.scheduler.report_peer_failed(peer)
        return DownloadResult(
            ok=False,
            task_id=peer.task.id,
            peer_id=peer.id,
            cost_s=time.monotonic() - t0,
        )


class StreamHandle:
    """A started (or reused) stream task: sizing metadata now, bytes as
    pieces commit (peertask_manager.go StartStreamTask's ReadCloser +
    attribute map).

    With a registered :class:`TeeConsumer` (the default for live runs),
    ``chunks`` serves each piece from the commit tee — ZERO disk reads
    on the fast path; the disk is only touched for cache-hit replays
    (``run is None``), pieces committed before this handle registered,
    and slow-reader spills.  ``start``/``length`` narrow the handle to a
    byte window (the ranged-stream serving half; the scheduling half is
    the run's range hint).
    """

    def __init__(
        self,
        conductor: Conductor,
        task_id: str,
        run: Optional[TaskRun],
        *,
        consumer: Optional[TeeConsumer] = None,
        start: int = 0,
        length: Optional[int] = None,
    ) -> None:
        self._conductor = conductor
        self.task_id = task_id
        self._run = run  # None → completed on disk (pure reuse)
        self._consumer = consumer
        storage = conductor.storage
        if run is None:
            self.content_length = storage.content_length(task_id)
            self.piece_size = storage.piece_size(task_id)
            self.n_pieces = max(storage.n_pieces(task_id), 0)
            self.reused = True
        else:
            self.content_length = run.content_length
            self.piece_size = run.piece_size
            self.n_pieces = run.n_pieces
            self.reused = False
        # Byte window, clamped to the sized representation.
        self.start = max(0, start)
        if self.content_length >= 0:
            self.start = min(self.start, self.content_length)
            end = (
                self.content_length
                if length is None
                else min(self.start + max(length, 0), self.content_length)
            )
        else:
            end = -1 if length is None else self.start + max(length, 0)
        self.end = end  # exclusive; -1 → to EOF of an unsized stream
        # Serve-plane evidence for the zero-disk-read witness.
        self.tee_hits = 0
        self.disk_reads = 0

    def close(self) -> None:
        """Detach the tee consumer (released buffers, no more offers).
        ``chunks`` closes automatically at exhaustion or generator
        close; callers that never iterate must close explicitly."""
        if self._consumer is not None:
            self._consumer.close()

    def narrow(self, start: int, end: int) -> "StreamHandle":
        """Late-bound byte window (``end`` exclusive) for callers that
        only learned the representation length from this stream's own
        sizing (e.g. a Range request for an origin that won't answer a
        length probe).  Registers the range hint with the live run —
        best-effort priority: pieces already queued keep their order."""
        self.start = max(0, start)
        if self.content_length >= 0:
            self.start = min(self.start, self.content_length)
            self.end = min(end, self.content_length)
        else:
            self.end = end
        if self._run is not None:
            self._run.add_range_hint(self.start, max(self.end - self.start, 0))
        return self

    def __enter__(self) -> "StreamHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _piece_window(self) -> range:
        """Piece numbers overlapping the byte window, in serve order."""
        if self.n_pieces <= 0:
            return range(0)
        ps = self.piece_size
        if ps <= 0:
            return range(self.n_pieces)
        first = self.start // ps
        if self.end < 0:
            return range(min(first, self.n_pieces), self.n_pieces)
        if self.end <= self.start:
            return range(0)
        last = (self.end - 1) // ps
        return range(min(first, self.n_pieces), min(last + 1, self.n_pieces))

    def chunks(self, *, piece_timeout_s: float = 60.0) -> Iterator[bytes]:
        """Yield the handle's byte window piece by piece, IN ORDER,
        waiting for pieces that have not committed yet.  Raises IOError
        when the underlying download fails or a piece times out.  The
        generator owns the tee consumer: it detaches at exhaustion or
        close, so an abandoned response can't pin tee buffers."""
        try:
            for number in self._piece_window():
                data = self._one_piece(number, piece_timeout_s)
                if data is None:
                    return  # eof on a shrunken run
                data = self._clip(number, data)
                if data:
                    yield data
        finally:
            self._finish_stream()

    def _one_piece(self, number: int, piece_timeout_s: float) -> Optional[bytes]:
        if self._run is not None:
            status = self._run.wait_piece(number, piece_timeout_s)
            if status == "failed":
                raise IOError(f"stream {self.task_id}: download failed")
            if status == "timeout":
                raise IOError(
                    f"stream {self.task_id}: piece {number} timed out"
                )
            if status == "eof":
                return None
        if self._consumer is not None:
            data = self._consumer.take(number)
            if data is not None:
                self.tee_hits += 1
                return data
        self.disk_reads += 1
        return self._conductor.storage.read_piece(self.task_id, number)

    def _clip(self, number: int, data: bytes) -> bytes:
        """Trim a piece body to the handle's byte window + EOF."""
        ps = self.piece_size
        total = self.content_length
        base = number * ps if ps > 0 else 0
        lo = max(self.start - base, 0)
        hi = len(data)
        if total >= 0 and ps > 0:
            hi = min(hi, max(total - base, 0))
        if self.end >= 0:
            hi = min(hi, max(self.end - base, 0))
        return data[lo:hi] if (lo > 0 or hi < len(data)) else data

    def _finish_stream(self) -> None:
        """Detach the consumer and record the serve on the download's
        trace: one `daemon/stream` span carrying the traceparent the
        run's download span injected, so a pass-through serve is visible
        on the SAME trace as the swarm transfer that fed it."""
        consumer = self._consumer
        self._consumer = None
        if consumer is not None:
            consumer.close()
        from ..utils.tracing import default_tracer

        traceparent = self._run.traceparent if self._run is not None else None
        with default_tracer.remote_span(
            "daemon/stream",
            traceparent,
            task_id=self.task_id,
            start=self.start,
            tee_hits=self.tee_hits,
            disk_reads=self.disk_reads,
            reused=self.reused,
        ):
            pass

    def read_all(self, *, piece_timeout_s: float = 60.0) -> bytes:
        return b"".join(self.chunks(piece_timeout_s=piece_timeout_s))

    def result(self) -> Optional[DownloadResult]:
        """The underlying run's final result (None while running, or for
        pure-reuse handles that never ran a download)."""
        return self._run.result if self._run is not None else None

    def wait_result(self, *, timeout_s: float = 30.0) -> Optional[DownloadResult]:
        """Block for the run's FINAL result — chunks() drains at the last
        piece commit, moments before the run finishes (reports, advertise),
        so immediate result() reads race None."""
        if self._run is None:
            return None
        return self._run.wait_done(timeout_s)
