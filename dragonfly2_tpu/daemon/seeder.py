"""Seed-peer seeder: the ObtainSeeds stream, TPU-build shape.

Reference: the seed daemon serves an ``ObtainSeeds`` stream — the
scheduler triggers a typed, PRIORITIZED download and receives piece
events as the seed fetches from the origin, so children can be attached
to the seed while it is still downloading
(client/daemon/rpcserver/seeder.go:41-151,
scheduler/resource/seed_peer.go:93-229 TriggerDownloadTask).

Here the stream is a chunked HTTP response of JSON-line events
(daemon_control.py POST /obtain_seeds):

    {"event": "accepted", "priority": p}
    {"event": "started",  "task_id": t}
    {"event": "piece",    "count": n}        # monotone piece progress
    {"event": "done",     "ok": true, "pieces": n, "back_to_source": b}

and the prioritized execution lives in ``SeedQueue``: seed jobs beyond
``max_concurrent`` wait in a priority order (LEVEL0 = most urgent
first, FIFO within a level), so a registry-preheat burst cannot starve
an interactive cold-task trigger.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.types import Priority

logger = logging.getLogger(__name__)


@dataclass(order=True)
class _Job:
    priority: int
    seq: int
    run: Callable[[], None] = field(compare=False)


class SeedQueue:
    """Priority-ordered executor for seed downloads.

    ``submit`` returns immediately; the job runs on one of
    ``max_concurrent`` workers, most-urgent (lowest Priority value)
    first, FIFO within a priority level.
    """

    def __init__(self, max_concurrent: int = 2) -> None:
        self.max_concurrent = max(1, max_concurrent)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._heap: list = []
        self._seq = itertools.count()
        self._active = 0
        self._stopped = False
        self._workers = [
            threading.Thread(target=self._loop, name=f"seed-{i}", daemon=True)
            for i in range(self.max_concurrent)
        ]
        for w in self._workers:
            w.start()

    def submit(
        self, run: Callable[[], None], priority: Priority = Priority.LEVEL0
    ) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("SeedQueue stopped")
            heapq.heappush(self._heap, _Job(int(priority), next(self._seq), run))
            self._cv.notify()

    def pending(self) -> int:
        with self._mu:
            return len(self._heap)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._stopped:
                    # Bounded wait + loop (DF008 timeout sweep): notify
                    # still wakes immediately; the timeout keeps an idle
                    # worker visible to watchdog stack dumps.
                    self._cv.wait(30.0)
                if self._stopped and not self._heap:
                    return
                job = heapq.heappop(self._heap)
                self._active += 1
            try:
                job.run()
            except Exception as exc:  # noqa: BLE001 — job errors surface via its own stream
                logger.warning("seed job failed: %s", exc)
            finally:
                with self._mu:
                    self._active -= 1


class Seeder:
    """Runs prioritized seed downloads and reports piece-level progress.

    ``obtain(...)`` submits the download to the SeedQueue and calls
    ``emit(event_dict)`` as progress happens; it returns when the
    download finishes (the control server streams each emitted event to
    the scheduler as a chunked JSON line).
    """

    def __init__(self, conductor, storage, queue: Optional[SeedQueue] = None):
        self.conductor = conductor
        self.storage = storage
        self.queue = queue or SeedQueue()

    def obtain(
        self,
        url: str,
        *,
        piece_size: int,
        priority: Priority = Priority.LEVEL0,
        content_length: Optional[int] = None,
        task_id: Optional[str] = None,
        emit: Callable[[dict], None] = lambda e: None,
        poll_interval_s: float = 0.05,
    ) -> dict:
        emit({"event": "accepted", "priority": int(priority)})
        done = threading.Event()
        result: dict = {}
        from ..utils import idgen

        # Honor the scheduler's task id: seeding under a different id
        # would warm a task nobody asks for (register_peer accepts
        # explicit ids, so the url-derived default is not authoritative).
        task_id = task_id or idgen.task_id(url)

        def run() -> None:
            try:
                cl = content_length
                if cl is None:
                    cl = self.conductor.probe_content_length(url)
                r = self.conductor.download(
                    url, piece_size=piece_size, content_length=cl,
                    priority=priority, task_id=task_id,
                )
                result.update(
                    ok=r.ok, task_id=r.task_id, pieces=r.pieces,
                    back_to_source=r.back_to_source, bytes=r.bytes,
                )
            except Exception as exc:  # noqa: BLE001 — reported on the stream
                result.update(ok=False, error=str(exc))
            finally:
                done.set()

        self.queue.submit(run, priority)

        # Piece progress: poll pieces HELD ON DISK while the download runs
        # — events fire as soon as the seed can actually serve data, which
        # is when the scheduler may attach children (seeder.go streams
        # pieces for the same reason).  The header total would lie here:
        # registration writes it before any byte arrives.
        started = False
        last = 0
        while not done.wait(poll_interval_s):
            if not started and self.storage.n_pieces(task_id) >= 0:
                # Header exists → the task is registered locally.
                emit({"event": "started", "task_id": task_id})
                started = True
            n = self.storage.held_pieces(task_id)
            if n > last:
                last = n
                emit({"event": "piece", "count": n})
        out = {"event": "done"}
        out.update(result)
        emit(out)
        return result
