"""Half-close-correct byte relay shared by the CONNECT tunnel and the
SNI pass-through (client/daemon/proxy's tunnel path).

EOF on one side shuts only the OTHER side's write half; data keeps
flowing the remaining direction until both halves close or the idle
budget expires.
"""

from __future__ import annotations

import select
import socket


def relay_bytes(a: socket.socket, b: socket.socket, idle_timeout: float) -> None:
    from ..utils import faultinject

    open_dirs = {a: b, b: a}
    while open_dirs:
        readable, _, _ = select.select(list(open_dirs), [], [], idle_timeout)
        if not readable:
            return  # idle past the budget
        for sock in readable:
            dst = open_dirs.get(sock)
            if dst is None:
                continue
            try:
                # Drop/truncate here = mid-tunnel reset/torn pump: the
                # half-close teardown below must run, not leak the pair.
                data = faultinject.fire("relay.pump", sock.recv(65536))
            except OSError:
                data = b""
            if not data:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                del open_dirs[sock]
            else:
                dst.sendall(data)


def fetch_via_p2p(daemon, url: str, piece_size: int) -> bytes:
    """Route one URL through the daemon's P2P engine and return the bytes
    (transport.go's divert seam, shared by both proxy faces)."""
    result = daemon.download(
        url, piece_size=piece_size,
        content_length=daemon.conductor.probe_content_length(url),
    )
    if not result.ok:
        raise IOError(f"p2p download of {url} failed")
    return daemon.read_task_bytes(result.task_id)
