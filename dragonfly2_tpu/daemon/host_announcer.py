"""Periodic host announcer: daemon → scheduler stats refresh.

Reference: client/daemon/announcer (announcer.go:103-158) announces live
host stats (CPU/mem/disk/net via gopsutil) to the scheduler on an
interval so the evaluator's host features stay current; plus manager
keepalive (:304+).

Works against both the embedded SchedulerService (announce = store_host
refresh) and the RemoteScheduler wire client (announce_host RPC).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..scheduler.resource import Host
from ..utils import hostinfo

DEFAULT_INTERVAL = 30.0


class HostAnnouncer:
    def __init__(
        self,
        host: Host,
        scheduler,
        *,
        interval: float = DEFAULT_INTERVAL,
        collect_stats: bool = True,
        tenant: str = "",
    ) -> None:
        self.host = host
        self.scheduler = scheduler
        self.interval = interval
        self.collect_stats = collect_stats
        # Tenant identity stamped on announces (DESIGN.md §26): wire
        # clients carry it as client state (.tenant), the embedded
        # service takes it as a kwarg.
        self.tenant = tenant
        if tenant and hasattr(scheduler, "tenant"):
            scheduler.tenant = tenant
        # Optional post-announce hook (no args): the daemon CLI adopts
        # announce-answer payloads (tenant_qos, §26) through it.
        self.on_announced = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def announce_once(self) -> None:
        if self.collect_stats:
            info = hostinfo.collect()
            self.host.stats.cpu = info.cpu
            self.host.stats.memory = info.memory
            self.host.stats.disk = info.disk
        self.host.touch()
        from ..scheduler.service import SchedulerService

        if isinstance(self.scheduler, SchedulerService):
            # Embedded service: announce_host refreshes stats and writes
            # the columnar host state on arrival (DESIGN.md §18); the
            # tenant rides as a kwarg into admission accounting (§26).
            self.scheduler.announce_host(self.host, tenant=self.tenant)
        elif hasattr(self.scheduler, "announce_host"):
            # Wire client: the tenant was stamped onto the client above.
            self.scheduler.announce_host(self.host)
        else:
            self.scheduler.resource.store_host(self.host)  # bare Resource shims
        hook = self.on_announced
        if hook is not None:
            hook()

    def serve(self) -> None:
        if self._thread is not None:
            return
        try:
            self.announce_once()
        except Exception:  # noqa: BLE001 — scheduler may still be booting
            import logging

            logging.getLogger(__name__).exception("initial host announce failed")

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.announce_once()
                except Exception:  # noqa: BLE001 — announces must not kill the daemon
                    import logging

                    logging.getLogger(__name__).exception("host announce failed")

        self._thread = threading.Thread(target=loop, name="host-announcer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
