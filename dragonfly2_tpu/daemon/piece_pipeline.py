"""Piece data-plane pipeline: overlapped commit, batched reports, hedged
straggler fetch (DESIGN.md §22).

The conductor's piece workers used to run strictly sequential per piece:
fetch → digest+write → report RPC → next fetch.  Three helpers break the
serialization without changing any correctness contract:

- :class:`CommitPipeline` — a bounded hand-off queue + one committer
  thread per download: the worker fetches piece N+1 while piece N is
  digested (crc at write), written, marked ready and queued for report.
  A commit failure aborts the download exactly like an inline failure
  (submit starts returning False; the error surfaces at ``close``).

- :class:`PieceReportBatcher` — coalesces ``report_piece_finished`` RPCs
  into bounded-linger ``report_pieces_finished`` batches (one wire call
  per flush).  Schedulers without the batch method degrade to per-piece
  calls.  ``close()`` flushes, so every piece report lands BEFORE the
  closing ``report_peer_finished``, preserving the scheduler FSM's
  observable order (DF013/DF015 stay green).

- :class:`PieceLatencyTracker` + :func:`hedged_fetch` — per-download
  rolling fetch latencies derive a p99-based hedge threshold; a piece
  exceeding it races a second parent through the SAME fetch path (so
  retry/CircuitBreaker machinery applies to both arms).  First VALID
  body wins; the loser's body is discarded (its socket drains back to
  the pool or is dropped on error) and only the winner reaches the
  commit path — one commit per piece, by construction and by drill.

- :class:`CommitTee` — the pass-through read plane (DESIGN.md §25).
  The committer PUBLISHES each verified piece body to every registered
  stream consumer alongside the disk write, so the proxy / object
  gateway serve bytes straight from the commit path instead of reading
  them back off the disk they were written to a microsecond earlier.
  Buffers are refcounted across consumers; each consumer's buffer depth
  is bounded, and a slow reader SPILLS (its pieces degrade to the disk
  path) instead of backpressuring the committer — a stalled proxy
  client can never wedge the download.  The tee is an optimization over
  a durable source of truth: any delivery failure degrades to the disk
  read, never to a download failure.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.metrics import default_registry as _reg

logger = logging.getLogger(__name__)

# Registered on the process-default registry (DF017: once, at module
# scope) so the metric journal snapshots them alongside the sketches —
# pre-§23 these were free-floating Counter instances invisible to
# /metrics and the journal.
PIECE_HEDGE_TOTAL = _reg.counter(
    "daemon_piece_hedge_total",
    "Hedged piece fetches by outcome (fired = second arm launched; "
    "won = the hedge arm's body was committed)",
    ("outcome",),
)

REPORT_BATCH_TOTAL = _reg.counter(
    "daemon_piece_report_batches_total",
    "Piece-report flushes by kind (batched = one report_pieces_finished "
    "RPC; fallback = per-piece calls, scheduler has no batch method)",
    ("kind",),
)

# Fleet telemetry sketches (DESIGN.md §23): the per-piece latency tail
# and the report-batch linger, journaled crash-safe and merged
# fleet-wide by tools/fleet_assemble.py — fixed-bucket histograms lose
# exactly the tail these carry.
PIECE_FETCH_SECONDS = _reg.sketch(
    "daemon_piece_fetch_seconds",
    "Per-piece fetch wall latency (hedge-plan baseline samples)",
)
REPORT_LINGER_SECONDS = _reg.sketch(
    "daemon_report_linger_seconds",
    "Piece-report batch linger: first enqueue to flush dispatch",
)

# Pass-through read plane (DESIGN.md §25): every published piece is
# either DELIVERED into a consumer's bounded buffer (served with zero
# disk reads) or SPILLED (slow/closed consumer — the piece degrades to
# the disk path).  The zero-disk-read witness and the stream bench read
# these to prove which plane actually served.
STREAM_TEE_TOTAL = _reg.counter(
    "daemon_stream_tee_pieces_total",
    "Commit-tee piece offers by outcome (delivered = buffered for a "
    "consumer; spilled = bounded buffer full or consumer closed — the "
    "piece is served from disk instead)",
    ("outcome",),
)


def _not_found_class(exc: BaseException) -> bool:
    """Typed NOT_FOUND (the wire's unknown-method answer — also unknown
    peer, which the per-piece fallback re-raises anyway, so branching on
    the code alone is safe-by-retry)."""
    code = getattr(exc, "code", None)
    if code is None:
        return False
    try:
        from ..utils.dferrors import Code

        return int(code) == int(Code.NOT_FOUND)
    except (TypeError, ValueError):
        return False


class CommitPipeline:
    """Digest piece N while piece N+1 is on the wire.

    ``commit_fn(number, data, parent_id, cost_ns)`` runs on ONE committer
    thread (daemon) in submission order; the bounded queue (``depth``)
    backpressures workers when storage falls behind so memory stays
    O(depth × piece_size).  First commit error latches: ``submit``
    returns False from then on and ``close()`` returns the error.
    """

    def __init__(
        self,
        commit_fn: Callable[[int, bytes, str, int], None],
        *,
        depth: int = 4,
        name: str = "piece-commit",
    ) -> None:
        self._commit = commit_fn
        self._depth = max(1, depth)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._pending: deque = deque()
        self._closed = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    @property
    def error(self) -> Optional[BaseException]:
        with self._mu:
            return self._error

    def submit(self, number: int, data: bytes, parent_id: str, cost_ns: int) -> bool:
        """Queue one fetched piece for commit; blocks while the queue is
        full (backpressure).  False → the pipeline failed or closed, the
        caller must abort its download."""
        with self._cv:
            while (
                len(self._pending) >= self._depth
                and self._error is None
                and not self._closed
            ):
                self._cv.wait(0.05)
            if self._error is not None or self._closed:
                return False
            self._pending.append((number, data, parent_id, cost_ns))
            self._cv.notify_all()
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait(0.05)
                if not self._pending:
                    return  # closed and drained
                item = self._pending.popleft()
                self._cv.notify_all()
            try:
                self._commit(*item)
            except BaseException as exc:  # noqa: BLE001 — latched for close()
                logger.warning(
                    "piece commit failed (piece %d)", item[0], exc_info=True
                )
                with self._cv:
                    self._error = exc
                    self._pending.clear()
                    self._closed = True
                    self._cv.notify_all()
                return

    def close(self) -> Optional[BaseException]:
        """Drain remaining commits, stop the committer, return the first
        error (None = every submitted piece committed)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        while self._thread.is_alive():
            self._thread.join(5.0)
        with self._mu:
            return self._error


class PieceReportBatcher:
    """Bounded-linger coalescing of per-piece finished reports.

    Reports accumulate for up to ``linger_s`` (or ``max_batch`` items)
    and flush as ONE ``report_pieces_finished`` call when the scheduler
    offers it, else per-piece ``report_piece_finished`` calls.  A flush
    failure latches (``error``) — the conductor treats it exactly like an
    inline report failure.  ``close()`` performs the final flush so piece
    reports always precede ``report_peer_finished``.
    """

    def __init__(
        self,
        scheduler,
        peer,
        *,
        linger_s: float = 0.02,
        max_batch: int = 64,
        name: str = "piece-report-batch",
        traceparent: Optional[str] = None,
    ) -> None:
        self._scheduler = scheduler
        self._peer = peer
        # The flush thread has an empty span stack; the download span's
        # context rides in so the report RPCs (and their server handler
        # spans) stay in the download's trace.
        self._traceparent = traceparent
        self._linger_s = linger_s
        self._max_batch = max(1, max_batch)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._items: List[Tuple[int, str, int, int]] = []
        self._first_ts = 0.0
        self._closed = False
        self._batch_unsupported = False
        self._error: Optional[BaseException] = None
        self.flushes = 0
        self.reported = 0
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    @property
    def error(self) -> Optional[BaseException]:
        with self._mu:
            return self._error

    def submit(self, number: int, parent_id: str, length: int, cost_ns: int) -> bool:
        with self._cv:
            if self._error is not None or self._closed:
                return False
            if not self._items:
                # First report of this batch: the linger clock starts
                # here (REPORT_LINGER_SECONDS measures enqueue → flush).
                self._first_ts = time.monotonic()
            self._items.append((number, parent_id, length, cost_ns))
            self._cv.notify_all()
        return True

    def _take_batch(self) -> Optional[List[Tuple[int, str, int, int]]]:
        """Linger until a batch is worth flushing (or close); None → done."""
        with self._cv:
            while not self._items and not self._closed:
                self._cv.wait(0.05)
            if not self._items:
                return None
            if not self._closed and len(self._items) < self._max_batch:
                # Bounded linger: let trailing reports coalesce.
                deadline = time.monotonic() + self._linger_s
                while (
                    len(self._items) < self._max_batch
                    and not self._closed
                ):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
            batch = self._items[: self._max_batch]
            del self._items[: len(batch)]
            linger = time.monotonic() - self._first_ts
            if self._items:
                # Remainder starts a fresh linger window now.
                self._first_ts = time.monotonic()
        # Observe OUTSIDE the cv (sketch lock never nests under batcher
        # state): the fleet-mergeable record of how long reports waited
        # to coalesce — the knob `linger_s` bounds, now measurable.
        REPORT_LINGER_SECONDS.observe(linger)
        return batch

    def _flush(self, batch: List[Tuple[int, str, int, int]]) -> None:
        from ..utils import faultinject
        from ..utils.tracing import default_tracer

        # Chaos seam for the batched-report plane: a drop here is a lost
        # flush — the conductor must fail the download loudly, exactly
        # like a dropped per-piece report.
        faultinject.fire("daemon.report.batch")
        with default_tracer.remote_span(
            "daemon/report.flush", self._traceparent, reports=len(batch)
        ):
            self._flush_calls(batch)

    def _flush_calls(self, batch: List[Tuple[int, str, int, int]]) -> None:
        batch_fn = (
            None
            if self._batch_unsupported
            else getattr(self._scheduler, "report_pieces_finished", None)
        )
        if batch_fn is not None:
            try:
                batch_fn(
                    self._peer,
                    [
                        {
                            "number": n,
                            "parent_id": pid,
                            "length": length,
                            "cost_ns": cost_ns,
                        }
                        for n, pid, length, cost_ns in batch
                    ],
                )
            except Exception as exc:
                # N-1 wire skew (DESIGN.md §10d): a pre-batch scheduler
                # answers NOT_FOUND for the unknown method — degrade to
                # per-piece reports for the rest of this download.  Any
                # other failure is a real report failure and latches.
                if not _not_found_class(exc):
                    raise
                logger.info(
                    "scheduler lacks report_pieces_finished; "
                    "falling back to per-piece reports"
                )
                self._batch_unsupported = True
                for n, pid, length, cost_ns in batch:
                    self._scheduler.report_piece_finished(
                        self._peer, n, parent_id=pid, length=length,
                        cost_ns=cost_ns,
                    )
                REPORT_BATCH_TOTAL.inc(kind="fallback")
                with self._mu:
                    self.flushes += 1
                    self.reported += len(batch)
                return
            REPORT_BATCH_TOTAL.inc(kind="batched")
        else:
            for n, pid, length, cost_ns in batch:
                self._scheduler.report_piece_finished(
                    self._peer, n, parent_id=pid, length=length,
                    cost_ns=cost_ns,
                )
            REPORT_BATCH_TOTAL.inc(kind="fallback")
        with self._mu:
            self.flushes += 1
            self.reported += len(batch)

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._flush(batch)
            except BaseException as exc:  # noqa: BLE001 — latched for close()
                logger.warning(
                    "piece report flush failed (%d reports)", len(batch),
                    exc_info=True,
                )
                with self._cv:
                    self._error = exc
                    self._items.clear()
                    self._closed = True
                    self._cv.notify_all()
                return

    def close(self) -> Optional[BaseException]:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        while self._thread.is_alive():
            self._thread.join(5.0)
        with self._mu:
            return self._error


class PieceLatencyTracker:
    """Rolling per-download piece fetch latencies → hedge threshold.

    The threshold is p99 of the observed samples times ``multiplier``
    (floored at ``floor_s`` so a fast LAN never hedges on micro-jitter),
    and only exists once ``min_samples`` fetches have been observed —
    hedging needs evidence of what "normal" looks like before calling
    anything a straggler.
    """

    def __init__(
        self,
        *,
        min_samples: int = 16,
        floor_s: float = 0.05,
        multiplier: float = 1.5,
        maxlen: int = 512,
    ) -> None:
        self.min_samples = max(2, min_samples)
        self.floor_s = floor_s
        self.multiplier = multiplier
        self._mu = threading.Lock()
        self._samples: deque = deque(maxlen=maxlen)

    def observe(self, latency_s: float) -> None:
        # One sketch observe per fetch (outside this tracker's lock):
        # the fleet-mergeable record of the same sample the hedge
        # threshold derives from.
        PIECE_FETCH_SECONDS.observe(latency_s)
        with self._mu:
            self._samples.append(latency_s)

    def threshold_s(self) -> Optional[float]:
        with self._mu:
            n = len(self._samples)
            if n < self.min_samples:
                return None
            ordered = sorted(self._samples)
        p99 = ordered[min(int(n * 0.99), n - 1)]
        return max(p99 * self.multiplier, self.floor_s)


def hedged_fetch(
    fetch: Callable[[str], bytes],
    validate: Callable[[bytes], bool],
    primary: str,
    alternate: Optional[str],
    *,
    threshold_s: Optional[float],
    wait_timeout_s: float = 60.0,
) -> Tuple[bytes, str, bool]:
    """Fetch with a straggler hedge: run ``fetch(primary)``; if no result
    lands within ``threshold_s``, race ``fetch(alternate)`` and take the
    first VALID body → ``(data, winner_parent, hedge_fired)``.

    - ``threshold_s`` None (not enough latency evidence) or no alternate
      → plain primary fetch, errors propagate untouched.
    - A fast primary FAILURE is not a straggler: it propagates so the
      conductor's report/reschedule path runs (the hedge is for slowness,
      not for dead parents — the breaker owns those).
    - The losing arm's body is discarded; its thread drains the response
      and returns the pooled connection.  Nothing downstream ever sees
      two bodies for one piece.
    """
    if threshold_s is None or alternate is None:
        return fetch(primary), primary, False

    results: "queue.Queue[Tuple[str, Optional[bytes], Optional[BaseException]]]" = (
        queue.Queue()
    )

    def attempt(parent_id: str) -> None:
        try:
            data = fetch(parent_id)
            if not validate(data):
                raise IOError(f"invalid body from {parent_id}")
            results.put((parent_id, data, None))
        except BaseException as exc:  # noqa: BLE001 — carried to the chooser
            results.put((parent_id, None, exc))

    from ..utils import faultinject

    t_primary = threading.Thread(
        target=attempt, args=(primary,), name="piece-hedge-primary",
        daemon=True,
    )
    t_primary.start()
    try:
        pid, data, err = results.get(timeout=threshold_s)
    except queue.Empty:
        pid = None
        data = err = None
    if pid is not None:
        if err is not None:
            raise err
        return data, pid, False

    # Straggler: fire the hedge through the same fetch path.
    faultinject.fire("daemon.piece.hedge")
    PIECE_HEDGE_TOTAL.inc(outcome="fired")
    t_hedge = threading.Thread(
        target=attempt, args=(alternate,), name="piece-hedge-alt",
        daemon=True,
    )
    t_hedge.start()
    first_err: Optional[BaseException] = None
    for _ in range(2):
        pid, data, err = results.get(timeout=wait_timeout_s)
        if err is None:
            PIECE_HEDGE_TOTAL.inc(
                outcome="won" if pid == alternate else "primary"
            )
            return data, pid, True
        first_err = first_err or err
    PIECE_HEDGE_TOTAL.inc(outcome="error")
    assert first_err is not None
    raise first_err


# ---------------------------------------------------------------------------
# Pass-through read plane: the commit tee (DESIGN.md §25)
# ---------------------------------------------------------------------------


class RefCountedBuffer:
    """One verified piece body shared by every consumer that buffered it.

    The commit path hands the SAME bytes object to N consumers; each
    holds one reference and releases it on take/close.  When the last
    reference drops, the buffer lets go of the bytes so tee memory is
    bounded by live consumer buffers, never by publish history.
    """

    __slots__ = ("number", "_mu", "_data", "_refs")

    def __init__(self, number: int, data: bytes, refs: int) -> None:
        self.number = number
        self._mu = threading.Lock()
        self._data: Optional[bytes] = data
        self._refs = max(refs, 0)
        if self._refs == 0:
            self._data = None

    @property
    def refs(self) -> int:
        with self._mu:
            return self._refs

    @property
    def data(self) -> Optional[bytes]:
        with self._mu:
            return self._data

    def release(self) -> int:
        """Drop one reference; the last release frees the bytes."""
        with self._mu:
            if self._refs > 0:
                self._refs -= 1
            if self._refs == 0:
                self._data = None
            return self._refs


class TeeConsumer:
    """One stream reader's bounded window onto the commit tee.

    Pieces land out of order (parallel piece workers), so the buffer is
    number-addressed: ``take(number)`` pops the piece when the in-order
    reader reaches it.  The buffer never holds more than ``depth``
    pieces — an offer past the bound is a SPILL (the reader serves that
    piece from disk), which is what makes a stalled proxy client unable
    to grow tee memory or stall the committer.  State is guarded by the
    owning tee's lock (one lock for the whole tee plane).
    """

    def __init__(self, tee: "CommitTee", depth: int) -> None:
        self._tee = tee
        self.depth = max(1, depth)
        self._buffered: Dict[int, RefCountedBuffer] = {}
        self._closed = False
        self.delivered = 0
        self.spilled = 0

    def _offer(self, buf: RefCountedBuffer) -> bool:
        """Committer-side: buffer the piece or spill it.  Never blocks,
        never raises — the commit path's wall is sacred."""
        with self._tee._mu:
            if not self._closed and len(self._buffered) < self.depth:
                self._buffered[buf.number] = buf
                self.delivered += 1
                return True
            self.spilled += 1
        buf.release()
        from ..utils import faultinject

        # Slow-reader spill seam: a chaos scenario SIGKILLs here (crash
        # kind) for the mid-tee kill drill; any raising kind is absorbed
        # — the spill already happened, the disk path serves the piece.
        try:
            faultinject.fire("daemon.stream.spill")
        except Exception:  # noqa: BLE001 — spill is bookkeeping, not delivery
            logger.debug("injected fault at daemon.stream.spill", exc_info=True)
        STREAM_TEE_TOTAL.inc(outcome="spilled")
        return False

    # dflint: hotpath
    def take(self, number: int) -> Optional[bytes]:
        """Reader-side: pop piece ``number`` if the tee delivered it
        (zero disk reads), else None — the reader falls back to disk
        (spill, pre-registration commit, or cache-hit replay)."""
        with self._tee._mu:
            buf = self._buffered.pop(number, None)
        if buf is None:
            return None
        data = buf.data
        buf.release()
        return data

    def buffered_count(self) -> int:
        with self._tee._mu:
            return len(self._buffered)

    def close(self) -> None:
        """Detach from the tee: release every held buffer and stop
        receiving offers.  Idempotent; the committer may be mid-publish
        concurrently (it snapshots consumers, `_offer` re-checks)."""
        with self._tee._mu:
            if self._closed:
                return
            self._closed = True
            bufs = list(self._buffered.values())
            self._buffered.clear()
            if self in self._tee._consumers:
                self._tee._consumers.remove(self)
        for buf in bufs:
            buf.release()


class CommitTee:
    """Publish verified pieces to N registered stream consumers alongside
    the disk write (the pass-through read plane's producer half).

    Delivery is strictly best-effort over a durable fallback: a delivery
    failure (including an injected ``daemon.stream.tee`` fault) degrades
    every consumer to the disk path for that piece — it can never fail
    or slow the download beyond the bounded buffer insert.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._consumers: List[TeeConsumer] = []
        self.published = 0

    def register(self, *, depth: int = 8) -> TeeConsumer:
        consumer = TeeConsumer(self, depth)
        with self._mu:
            self._consumers.append(consumer)
        return consumer

    def consumer_count(self) -> int:
        with self._mu:
            return len(self._consumers)

    # dflint: hotpath
    def publish(self, number: int, data: bytes) -> int:
        """Offer one verified piece to every registered consumer; returns
        how many buffered it.  No consumers → pure no-op (the common
        non-streaming download pays one lock round-trip)."""
        with self._mu:
            consumers = list(self._consumers)
        if not consumers:
            return 0
        from ..utils import faultinject

        try:
            # Tee delivery seam: an injected drop models a failed
            # delivery — consumers degrade to the disk path for this
            # piece, the download is untouched.
            faultinject.fire("daemon.stream.tee")
        except Exception:  # noqa: BLE001 — tee is best-effort over disk
            logger.debug("tee delivery faulted; piece %d spills", number)
            STREAM_TEE_TOTAL.inc(outcome="spilled")
            return 0
        buf = RefCountedBuffer(number, data, len(consumers))
        delivered = sum([c._offer(buf) for c in consumers])
        with self._mu:
            self.published += 1
        if delivered:
            STREAM_TEE_TOTAL.inc(outcome="delivered")
        return delivered
