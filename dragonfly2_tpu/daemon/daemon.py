"""Daemon composition root (reference: client/daemon/daemon.go:118-417).

Wires storage, upload, conductor, pex, and the probe agent around one
Host identity.  ``InProcessFetcher`` is the piece transport seam: it
resolves a parent host id to that daemon's UploadManager — the in-process
stand-in for the HTTP piece data plane, with identical semantics
(concurrency caps, crc-verified reads).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..scheduler.networktopology import ProbeAgent
from ..scheduler.resource import Host
from ..scheduler.service import SchedulerService
from .conductor import Conductor, DownloadResult
from .pex import GossipBus, MemberMeta, PeerExchange
from .storage import DaemonStorage
from .traffic_shaper import TrafficShaper
from .upload import UploadManager


class InProcessFetcher:
    """Piece transport: parent host id → its daemon's upload manager."""

    def __init__(self, registry: Dict[str, "Daemon"]):
        self._registry = registry

    def fetch(self, parent_host_id: str, task_id: str, number: int) -> bytes:
        daemon = self._registry.get(parent_host_id)
        if daemon is None:
            raise KeyError(f"no daemon for host {parent_host_id}")
        return daemon.upload.serve_piece(task_id, number)

    def piece_bitmap(self, parent_host_id: str, task_id: str):
        """Piece-metadata sync for the in-process transport (same contract
        as HTTPPieceFetcher.piece_bitmap)."""
        daemon = self._registry.get(parent_host_id)
        if daemon is None:
            return None
        n = daemon.storage.n_pieces(task_id)
        if n <= 0:
            return None
        return bytes(daemon.storage.piece_bitmap(task_id, n))

    def wait_piece_bitmap(
        self, parent_host_id: str, task_id: str, have: int, wait_s: float
    ):
        """Piece-metadata SUBSCRIPTION (piecetask_synchronizer analog):
        block until the parent holds more than ``have`` pieces (a
        mid-download parent commits a new one) or ``wait_s`` elapses,
        then return the current bitmap."""
        daemon = self._registry.get(parent_host_id)
        if daemon is None:
            return None
        deadline = time.monotonic() + wait_s
        while True:
            n = daemon.storage.n_pieces(task_id)
            grew = n > 0 and daemon.storage.held_pieces(task_id) > have
            if grew or time.monotonic() >= deadline:
                return (
                    bytes(daemon.storage.piece_bitmap(task_id, n))
                    if n > 0 else None
                )
            time.sleep(0.01)


class Daemon:
    def __init__(
        self,
        host: Host,
        scheduler: SchedulerService,
        *,
        storage_root: str,
        daemon_registry: Optional[Dict[str, "Daemon"]] = None,
        gossip_bus: Optional[GossipBus] = None,
        source_fetcher=None,
        quota_bytes: int = 10 << 30,
        total_rate: float = 1e9,
        prefer_native: bool = True,
        concurrent_source_groups: int = 1,
        tenant: str = "",
    ) -> None:
        self.host = host
        self.scheduler = scheduler
        # Declared tenant (DESIGN.md §26): stamped on registers and
        # announces; tasks this daemon downloads are owned by it, so
        # serves of their pieces account (and throttle) against it.
        self.tenant = tenant
        self.storage = DaemonStorage(
            storage_root, quota_bytes=quota_bytes, prefer_native=prefer_native
        )
        self.upload = UploadManager(
            self.storage, concurrent_limit=host.concurrent_upload_limit
        )
        self.traffic_shaper = TrafficShaper(total_rate)
        self._registry = daemon_registry if daemon_registry is not None else {}
        self._registry[host.id] = self
        self.conductor = Conductor(
            host,
            self.storage,
            scheduler,
            piece_fetcher=InProcessFetcher(self._registry),
            source_fetcher=source_fetcher,
            traffic_shaper=self.traffic_shaper,
            concurrent_source_groups=concurrent_source_groups,
            tenant=tenant,
        )
        self.pex: Optional[PeerExchange] = None
        if gossip_bus is not None:
            self.pex = PeerExchange(
                MemberMeta(host_id=host.id, ip=host.ip, port=host.download_port),
                gossip_bus,
            )
            self.pex.serve()
            # The conductor needs the pex handle for its scheduler-down
            # fallback (gossip-discovered holders) — without this wiring
            # the fallback silently never engages (the CLI composition
            # attaches it the same way).
            self.conductor.pex = self.pex
        self.probe_agent: Optional[ProbeAgent] = None

    def enable_probes(self, ping) -> None:
        """Attach the probe agent (client/daemon/networktopology)."""
        if self.scheduler.networktopology is not None:
            self.probe_agent = ProbeAgent(
                self.host, self.scheduler.networktopology, ping
            )

    def probe_round(self) -> int:
        return self.probe_agent.sync_probes() if self.probe_agent else 0

    def set_qos_policy(self, policy) -> None:
        """Adopt a tenant QoS policy (manager-published, re-published on
        announce answers): upload-path bandwidth caps + the shaper's
        tenant weight split (DESIGN.md §26)."""
        self.upload.set_qos_policy(policy)
        self.traffic_shaper.set_policy(policy)

    def download(self, url: str, **kwargs) -> DownloadResult:
        from ..utils import idgen

        # Stamp task ownership BEFORE any bytes move: serves of this
        # task's pieces (to other peers, mid-download included) account
        # against this daemon's tenant.
        self.upload.register_task_tenant(
            kwargs.get("task_id") or idgen.task_id(url), self.tenant
        )
        result = self.conductor.download(url, **kwargs)
        # The conductor advertises every download it EXECUTED (all three
        # planes + tiny); only reuse results — served straight from disk,
        # e.g. after a restart reload raced ahead of reload()'s
        # re-advertisement — need one here.  Advertising twice would
        # double gossip traffic per download on the UDP bus.
        if result.ok and result.reused and self.pex is not None:
            self.pex.advertise(result.task_id, set(range(result.pieces)))
        return result

    def open_stream(self, url: str, **kwargs):
        """Stream-task entry (StartStreamTask analog): bytes flow as
        pieces commit — reuse, attach-to-running, or background download."""
        from ..utils import idgen

        self.upload.register_task_tenant(
            kwargs.get("task_id") or idgen.task_id(url), self.tenant
        )
        return self.conductor.open_stream(url, **kwargs)

    def read_task_bytes(self, task_id: str) -> bytes:
        """Reassemble a completed task's content (storage-level impl, shared
        by dfget output, the object gateway, the proxy, and dfdaemon)."""
        return self.storage.read_task_bytes(task_id)

    def delete_task(self, task_id: str) -> None:
        """Evict local data and withdraw the pex advertisement."""
        self.storage.delete_task(task_id)
        if self.pex is not None:
            self.pex.retract(task_id)

    def reclaim(self) -> list:
        """Quota GC with advertisement retraction (use instead of calling
        storage.reclaim directly when pex is enabled)."""
        reclaimed = self.storage.reclaim()
        if self.pex is not None:
            for task_id in reclaimed:
                self.pex.retract(task_id)
        return reclaimed

    def reload(self) -> int:
        """Crash-restart recovery: reopen on-disk tasks and re-advertise."""
        loaded = self.storage.reload_persistent_tasks(self.storage.scan_disk_tasks())
        if self.pex is not None:
            for task_id in loaded:
                # True piece-count bound from the task header, not a guess —
                # a daemon holding only the tail pieces must still advertise.
                n_pieces = self.storage.n_pieces(task_id)
                if n_pieces <= 0:
                    continue
                bm = self.storage.piece_bitmap(task_id, n_pieces)
                self.pex.advertise(task_id, {int(i) for i in bm.nonzero()[0]})
        return len(loaded)

    def stop(self) -> None:
        if self.pex is not None:
            self.pex.stop()
        self._registry.pop(self.host.id, None)
        self.storage.close()
