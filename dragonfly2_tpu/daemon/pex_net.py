"""Networked peer-exchange gossip: the GossipBus seam over UDP.

Reference: client/daemon/pex/ rides hashicorp/memberlist — gossip
membership with metadata broadcast, per-peer piece advertisements,
reclaim-on-leave, and anti-entropy state sync
(peer_exchange.go:34-50, member_manager.go, peer_pool.go).

``NetworkedGossipBus`` is the wire implementation of the same seam the
in-process ``GossipBus`` fills (daemon/pex.py): one bus per daemon
process, one UDP socket, JSON datagrams:

    {"t":"join","meta":{...}}          membership announce (rebroadcast once)
    {"t":"leave","host_id":h}          explicit leave → reclaim
    {"t":"adv","src":h,"task":t,"ranges":[[a,b],...]}   piece advertisement
    {"t":"ret","src":h,"task":t}       retract (eviction)
    {"t":"hb","host_id":h}             heartbeat (failure detection)
    {"t":"sync_req","meta":{...}}      ask for a full state snapshot
    {"t":"sync","members":[...],"holdings":[[h,t,ranges],...]}

Membership is full-mesh (every member keeps every member's address —
fine at swarm sizes where the reference runs memberlist too); liveness
is heartbeat-based: a member silent for ``suspect_after`` intervals is
dropped and its advertisements reclaimed, exactly like memberlist's
leave event.  Anti-entropy: on join a node sync_reqs a seed, and every
``gossip_interval`` it sync_reqs one random member — lost datagrams
converge within one round.

Piece sets travel as sorted [start, end] ranges so a contiguous
holding of any size fits one datagram.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .pex import MemberMeta, PeerExchange

logger = logging.getLogger(__name__)

_MAX_DGRAM = 60_000


def pieces_to_ranges(pieces: Set[int]) -> List[List[int]]:
    out: List[List[int]] = []
    for p in sorted(pieces):
        if out and p == out[-1][1] + 1:
            out[-1][1] = p
        else:
            out.append([p, p])
    return out


def ranges_to_pieces(ranges: List[List[int]]) -> Set[int]:
    s: Set[int] = set()
    for a, b in ranges:
        s.update(range(int(a), int(b) + 1))
    return s


class NetworkedGossipBus:
    """UDP gossip transport for exactly one local PeerExchange."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        seeds: Optional[List[Tuple[str, int]]] = None,
        gossip_interval_s: float = 1.0,
        suspect_after: int = 3,
        advertise_ip: str = "",
    ) -> None:
        self.seeds = list(seeds or [])
        self.gossip_interval_s = gossip_interval_s
        self.suspect_after = suspect_after
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self.address: Tuple[str, int] = self._sock.getsockname()
        # The address OTHER nodes dial back: a wildcard bind (0.0.0.0)
        # must never travel in the meta — remote peers would send replies
        # to themselves.
        adv = advertise_ip or self.address[0]
        if adv in ("0.0.0.0", "::"):
            adv = "127.0.0.1"
        self.advertised: Tuple[str, int] = (adv, self.address[1])
        self._mu = threading.Lock()
        self._pex: Optional[PeerExchange] = None
        # host_id → (MemberMeta, gossip_addr, last_seen)
        self._peers: Dict[str, Tuple[MemberMeta, Tuple[str, int], float]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- GossipBus seam (pex.py PeerExchange calls these) --------------------

    def join(self, pex: PeerExchange) -> None:
        self._pex = pex
        for name, fn in (("pex-recv", self._recv_loop), ("pex-tick", self._tick_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        msg = {"t": "join", "meta": self._meta_wire(pex.meta)}
        for addr in self.seeds:
            self._send(msg, addr)
            self._send({"t": "sync_req", "meta": self._meta_wire(pex.meta)}, addr)

    def leave(self, host_id: str) -> None:
        self._broadcast({"t": "leave", "host_id": host_id})
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def broadcast_advertise(self, src: str, task_id: str, pieces: Set[int]) -> None:
        self._broadcast(
            {"t": "adv", "src": src, "task": task_id,
             "ranges": pieces_to_ranges(pieces)}
        )

    def broadcast_retract(self, src: str, task_id: str) -> None:
        self._broadcast({"t": "ret", "src": src, "task": task_id})

    # -- wire ---------------------------------------------------------------

    def _meta_wire(self, meta: MemberMeta) -> dict:
        return {
            "host_id": meta.host_id, "ip": meta.ip, "port": meta.port,
            "gossip": [self.advertised[0], self.advertised[1]],
        }

    def _send(self, msg: dict, addr: Tuple[str, int]) -> None:
        from ..utils import faultinject

        try:
            faultinject.fire("pex.send")
            data = json.dumps(msg).encode()
            if len(data) > _MAX_DGRAM:
                logger.warning(
                    "pex: dropping %s message of %d bytes (> %d) to %s",
                    msg.get("t"), len(data), _MAX_DGRAM, addr,
                )
                return
            self._sock.sendto(data, tuple(addr))
        except OSError:
            pass  # dflint: disable=DF001 — UDP gossip: drop is the semantics

    def _broadcast(self, msg: dict) -> None:
        with self._mu:
            addrs = [a for _, a, _ in self._peers.values()]
        for addr in addrs:
            self._send(msg, addr)

    def _recv_loop(self) -> None:
        from ..utils import faultinject

        while not self._stop.is_set():
            try:
                data, addr = self._sock.recvfrom(_MAX_DGRAM + 4096)
            except OSError:
                return
            try:
                # Drop = datagram lost (skip), truncate = torn datagram
                # that must parse-fail cleanly, never poison the
                # membership table.
                data = faultinject.fire("pex.recv", data)
            except ConnectionError:
                continue
            try:
                msg = json.loads(data)
                self._handle(msg, addr)
            except Exception:  # noqa: BLE001 — malformed gossip must not kill the loop
                logger.debug("pex: bad datagram from %s", addr, exc_info=True)

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.gossip_interval_s):
            if self._pex is None:
                continue
            with self._mu:
                isolated = not self._peers
            if isolated and self.seeds:
                # The one-shot join datagrams may have been lost — keep
                # knocking on the seed list until somebody answers, or the
                # docstring's "converge within one round" is a lie.
                for addr in self.seeds:
                    self._send(
                        {"t": "join", "meta": self._meta_wire(self._pex.meta)},
                        addr,
                    )
                    self._send(
                        {"t": "sync_req",
                         "meta": self._meta_wire(self._pex.meta)},
                        addr,
                    )
                continue
            me = {"t": "hb", "host_id": self._pex.meta.host_id}
            self._broadcast(me)
            # Failure detection: reclaim members we have not heard from.
            cutoff = time.monotonic() - self.gossip_interval_s * self.suspect_after
            with self._mu:
                dead = [h for h, (_, _, seen) in self._peers.items() if seen < cutoff]
                for h in dead:
                    self._peers.pop(h, None)
            for h in dead:
                self._pex._on_leave(h)
            # Anti-entropy: sync with one random member.
            with self._mu:
                addrs = [a for _, a, _ in self._peers.values()]
            if addrs:
                self._send(
                    {"t": "sync_req", "meta": self._meta_wire(self._pex.meta)},
                    random.choice(addrs),
                )

    # -- message handling ----------------------------------------------------

    def _learn(self, meta_wire: dict) -> None:
        pex = self._pex
        if pex is None or meta_wire["host_id"] == pex.meta.host_id:
            return
        meta = MemberMeta(
            host_id=meta_wire["host_id"], ip=meta_wire.get("ip", ""),
            port=int(meta_wire.get("port", 0)),
        )
        gossip_addr = tuple(meta_wire.get("gossip", ("", 0)))
        with self._mu:
            known = meta.host_id in self._peers
            self._peers[meta.host_id] = (meta, gossip_addr, time.monotonic())
        pex._on_join(meta)
        if not known:
            # First contact: introduce ourselves + share our holdings so
            # one-way joins converge without waiting for anti-entropy.
            self._send({"t": "join", "meta": self._meta_wire(pex.meta)}, gossip_addr)
            for task_id, pieces in pex.local_holdings():
                self._send(
                    {"t": "adv", "src": pex.meta.host_id, "task": task_id,
                     "ranges": pieces_to_ranges(pieces)},
                    gossip_addr,
                )

    def _handle(self, msg: dict, addr: Tuple[str, int]) -> None:
        pex = self._pex
        if pex is None:
            return
        kind = msg.get("t")
        if kind == "join":
            self._learn(msg["meta"])
        elif kind == "leave":
            h = msg["host_id"]
            with self._mu:
                self._peers.pop(h, None)
            pex._on_leave(h)
        elif kind == "hb":
            h = msg["host_id"]
            with self._mu:
                entry = self._peers.get(h)
                if entry is not None:
                    self._peers[h] = (entry[0], entry[1], time.monotonic())
        elif kind == "adv":
            if msg["src"] != pex.meta.host_id:
                pex._on_advertise(
                    msg["src"], msg["task"], ranges_to_pieces(msg["ranges"])
                )
        elif kind == "ret":
            if msg["src"] != pex.meta.host_id:
                pex._on_retract(msg["src"], msg["task"])
        elif kind == "sync_req":
            self._learn(msg["meta"])
            dest = tuple(msg["meta"].get("gossip", addr))
            for part in self._snapshot_parts():
                self._send(part, dest)
        elif kind == "sync":
            for meta_wire in msg.get("members", []):
                self._learn(meta_wire)
            for h, task_id, ranges in msg.get("holdings", []):
                if h != pex.meta.host_id:
                    pex._on_advertise(h, task_id, ranges_to_pieces(ranges))

    def _snapshot_parts(self, chunk: int = 200) -> List[dict]:
        """Full-state sync reply, split into datagram-sized messages: a
        big pool must not exceed _MAX_DGRAM and get silently dropped —
        that would disable anti-entropy exactly when it matters."""
        pex = self._pex
        assert pex is not None
        with self._mu:
            members = [self._meta_wire_of(m, a) for m, a, _ in self._peers.values()]
        members.append(self._meta_wire(pex.meta))
        holdings = [
            [pex.meta.host_id, t, pieces_to_ranges(p)]
            for t, p in pex.local_holdings()
        ]
        for h, task_id, pieces in pex.pool_snapshot():
            holdings.append([h, task_id, pieces_to_ranges(pieces)])
        parts: List[dict] = []
        for i in range(0, max(len(members), 1), chunk):
            parts.append({"t": "sync", "members": members[i:i + chunk],
                          "holdings": []})
        for i in range(0, len(holdings), chunk):
            parts.append({"t": "sync", "members": [],
                          "holdings": holdings[i:i + chunk]})
        return parts

    @staticmethod
    def _meta_wire_of(meta: MemberMeta, gossip_addr: Tuple[str, int]) -> dict:
        return {
            "host_id": meta.host_id, "ip": meta.ip, "port": meta.port,
            "gossip": list(gossip_addr),
        }
