"""SNI-hijack proxy: TLS interception on the HTTPS port.

Reference: client/daemon/proxy's SNI path — the daemon listens on TLS
ports, reads the ClientHello's server_name extension WITHOUT terminating
the handshake, and either (a) hijacks matched hosts: completes the TLS
handshake itself with a CA-minted leaf certificate for that hostname and
serves the inner HTTP request from P2P, or (b) relays unmatched
connections byte-for-byte to the real origin (the peeked bytes were
never consumed, so the upstream sees a pristine ClientHello).

The ClientHello parser is hand-rolled over the public TLS 1.2/1.3 wire
layout (RFC 8446 §4.1.2): record header → handshake header → skip
random/session/ciphers/compression → walk extensions to server_name (0).
"""

from __future__ import annotations

import re
import shutil
import socket
import ssl
import struct
import tempfile
import threading
import time
from typing import Dict, List, Optional, Pattern

from ..security.ca import CertificateAuthority, PeerIdentity
from .relay import fetch_via_p2p, relay_bytes

MAX_HELLO = 16 * 1024


def parse_client_hello_sni(data: bytes) -> Optional[str]:
    """Extract the SNI hostname from raw ClientHello bytes, else None."""
    try:
        if len(data) < 5 or data[0] != 0x16:  # handshake record
            return None
        record_len = struct.unpack(">H", data[3:5])[0]
        body = data[5 : 5 + record_len]
        if len(body) < 4 or body[0] != 0x01:  # ClientHello
            return None
        hello_len = int.from_bytes(body[1:4], "big")
        hello = body[4 : 4 + hello_len]
        pos = 2 + 32  # legacy_version + random
        sid_len = hello[pos]
        pos += 1 + sid_len
        cipher_len = struct.unpack(">H", hello[pos : pos + 2])[0]
        pos += 2 + cipher_len
        comp_len = hello[pos]
        pos += 1 + comp_len
        if pos + 2 > len(hello):
            return None  # no extensions
        ext_total = struct.unpack(">H", hello[pos : pos + 2])[0]
        pos += 2
        end = min(pos + ext_total, len(hello))
        while pos + 4 <= end:
            ext_type, ext_len = struct.unpack(">HH", hello[pos : pos + 4])
            pos += 4
            if ext_type == 0:  # server_name
                # list length (2) + entry type (1) + name length (2)
                name_len = struct.unpack(">H", hello[pos + 3 : pos + 5])[0]
                return hello[pos + 5 : pos + 5 + name_len].decode("idna")
            pos += ext_len
        return None
    except (IndexError, struct.error, UnicodeError):
        return None


def _peek_client_hello(conn: socket.socket, timeout: float) -> bytes:
    """MSG_PEEK until the full first record is visible (bytes stay queued
    in the kernel, so a relayed upstream still receives them).

    MSG_PEEK on a partial record returns the same bytes instantly — the
    socket timeout never fires while data is queued — so progress is
    tracked explicitly: no growth → short sleep, hard deadline overall
    (otherwise one stalled client pins a core)."""
    from ..utils import faultinject

    conn.settimeout(timeout)
    deadline = time.monotonic() + timeout
    prev = -1
    data = b""
    while True:
        faultinject.fire("sni.peek")
        data = conn.recv(MAX_HELLO, socket.MSG_PEEK)
        if not data:
            return b""
        if len(data) >= 5:
            need = 5 + struct.unpack(">H", data[3:5])[0]
            if len(data) >= need or len(data) >= MAX_HELLO:
                return data
        if time.monotonic() >= deadline:
            return data
        if len(data) == prev:
            time.sleep(0.02)
        prev = len(data)


class _HostCerts:
    """Per-SNI-host leaf certificates minted from the daemon CA, cached
    as ready ssl server contexts (proxy.go's cert cache).

    Entries re-mint at half the leaf TTL: a long-running daemon must
    never serve an expired certificate from the cache."""

    def __init__(self, ca: CertificateAuthority) -> None:
        self.ca = ca
        self._mu = threading.Lock()
        self._contexts: Dict[str, tuple] = {}  # host → (ctx, refresh_at)
        from ..security.ca import DEFAULT_CERT_TTL

        self._refresh_s = DEFAULT_CERT_TTL.total_seconds() / 2

    def context_for(self, host: str) -> ssl.SSLContext:
        now = time.monotonic()
        with self._mu:
            hit = self._contexts.get(host)
        if hit is not None and now < hit[1]:
            return hit[0]
        identity = PeerIdentity.issue(self.ca, common_name=host, hostnames=[host])
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # Browsers have no client certs: server-auth only, unlike the
        # service-mesh contexts in security.tls.
        directory = tempfile.mkdtemp(prefix="df-sni-")
        try:
            paths = identity.write(directory)
            ctx.load_cert_chain(paths["cert"], paths["key"])
        finally:
            shutil.rmtree(directory, ignore_errors=True)
        with self._mu:
            self._contexts[host] = (ctx, now + self._refresh_s)
        return ctx


class SNIProxy:
    """TLS listener: hijack matched SNI hosts into P2P, relay the rest."""

    def __init__(
        self,
        daemon,
        *,
        ca: CertificateAuthority,
        hijack: List[Pattern],
        router=None,
        host: str = "127.0.0.1",
        port: int = 0,
        relay_port: int = 443,
        upstream_resolver=None,
        piece_size: int = 4 << 20,
        handshake_timeout: float = 10.0,
        idle_timeout: float = 300.0,
    ) -> None:
        self.daemon = daemon
        self.hijack = [re.compile(p) if isinstance(p, str) else p for p in hijack]
        self.router = router
        self.relay_port = relay_port
        # Interception deployments point hijacked DNS names at THIS
        # listener; relaying an unmatched name through normal resolution
        # would then dial ourselves in a loop.  The resolver hook maps
        # SNI → real upstream address; without one, self-connects are
        # detected and refused.
        self.upstream_resolver = upstream_resolver
        self.piece_size = piece_size
        self.handshake_timeout = handshake_timeout
        self.idle_timeout = idle_timeout
        self.certs = _HostCerts(ca)
        self.stats = {"hijacked": 0, "relayed": 0, "rejected": 0}
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    @property
    def port(self) -> int:
        return self.address[1]

    def serve(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._accept_loop, name="sni-proxy", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopping.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            hello = _peek_client_hello(conn, self.handshake_timeout)
            sni = parse_client_hello_sni(hello)
            if sni is not None and any(p.search(sni) for p in self.hijack):
                self._hijack(conn, sni)
            elif sni is not None:
                self._relay(conn, sni)
            else:
                self.stats["rejected"] += 1
                conn.close()
        except Exception:  # noqa: BLE001 — connection boundary
            self.stats["rejected"] += 1
            try:
                conn.close()
            except OSError:
                pass

    # -- hijack: terminate TLS, serve the inner request from P2P ------------

    def _hijack(self, conn: socket.socket, sni: str) -> None:
        from ..utils import faultinject

        faultinject.fire("sni.hijack")
        ctx = self.certs.context_for(sni)
        with ctx.wrap_socket(conn, server_side=True) as tls:
            tls.settimeout(self.handshake_timeout)
            request = b""
            while b"\r\n\r\n" not in request and len(request) < MAX_HELLO:
                chunk = tls.recv(4096)
                if not chunk:
                    break
                request += chunk
            line = request.split(b"\r\n", 1)[0].decode("latin-1", "replace")
            parts = line.split(" ")
            if len(parts) < 2 or parts[0] != "GET":
                tls.sendall(b"HTTP/1.1 405 Method Not Allowed\r\n\r\n")
                return
            url = f"https://{sni}{parts[1]}"
            use_p2p, effective = (True, url)
            if self.router is not None:
                use_p2p, effective = self.router.route(url)
            try:
                if use_p2p:
                    body = self._fetch_p2p(effective)
                else:
                    import urllib.request

                    with urllib.request.urlopen(effective, timeout=30) as resp:
                        body = resp.read()
            except Exception:  # noqa: BLE001
                tls.sendall(b"HTTP/1.1 502 Bad Gateway\r\n\r\n")
                return
            self.stats["hijacked"] += 1
            tls.sendall(
                b"HTTP/1.1 200 OK\r\nContent-Length: "
                + str(len(body)).encode()
                + b"\r\nConnection: close\r\n\r\n"
                + body
            )

    def _fetch_p2p(self, url: str) -> bytes:
        return fetch_via_p2p(self.daemon, url, self.piece_size)

    # -- relay: the peeked bytes are still in the kernel queue --------------

    def _relay(self, conn: socket.socket, sni: str) -> None:
        target = (sni, self.relay_port)
        if self.upstream_resolver is not None:
            target = self.upstream_resolver(sni)
        try:
            if self.upstream_resolver is None:
                resolved = socket.getaddrinfo(
                    target[0], target[1], proto=socket.IPPROTO_TCP
                )
                own_ip, own_port = self.address[0], self.address[1]
                for *_, addr in resolved:
                    if addr[1] == own_port and (
                        addr[0] == own_ip
                        or (own_ip == "0.0.0.0" and addr[0].startswith("127."))
                    ):
                        self.stats["rejected"] += 1
                        conn.close()
                        return
            upstream = socket.create_connection(target, timeout=10)
        except OSError:
            conn.close()
            return
        self.stats["relayed"] += 1
        conn.settimeout(None)
        try:
            relay_bytes(conn, upstream, self.idle_timeout)
        finally:
            upstream.close()
            conn.close()
