"""Daemon local storage: piece files + quota GC.

Uses the native C++ piece store when buildable (dragonfly2_tpu/native),
else a pure-Python engine with the same on-disk layout semantics.
Reference: client/daemon/storage/storage_manager.go (TaskStorageDriver
:54-135, ReloadPersistentTask :703-760, Reclaimer :82-91).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import native

logger = logging.getLogger(__name__)


class _PyPieceStore:
    """Pure-Python fallback with the same API as native.NativePieceStore."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._meta: Dict[str, dict] = {}
        self._mu = threading.Lock()

    def _dir(self, task_id: str) -> str:
        return os.path.join(self.root, task_id)

    def _load_meta(self, task_id: str) -> Optional[dict]:
        with self._mu:
            if task_id in self._meta:
                return self._meta[task_id]
        header_path = os.path.join(self._dir(task_id), "header.json")
        if not os.path.exists(header_path):
            return None
        with open(header_path) as f:
            meta = json.load(f)
        meta["pieces"] = {}
        # Piece commits are an append-only journal (one JSON line each) so
        # per-piece metadata I/O is O(1), matching the native engine; a torn
        # trailing line (crash mid-append) is skipped.
        journal = os.path.join(self._dir(task_id), "pieces.jsonl")
        if os.path.exists(journal):
            with open(journal) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    meta["pieces"][int(rec["n"])] = {
                        "length": rec["length"],
                        "crc": rec["crc"],
                    }
        with self._mu:
            self._meta[task_id] = meta
        return meta

    def _append_journal(self, task_id: str, number: int, info: dict) -> None:
        journal = os.path.join(self._dir(task_id), "pieces.jsonl")
        with open(journal, "a") as f:
            f.write(
                json.dumps({"n": number, "length": info["length"], "crc": info["crc"]})
                + "\n"
            )

    def create_task(self, task_id: str, piece_size: int, content_length: int) -> None:
        os.makedirs(self._dir(task_id), exist_ok=True)
        if self._load_meta(task_id) is None:
            meta = {
                "piece_size": piece_size,
                "content_length": content_length,
                "pieces": {},
            }
            with self._mu:
                self._meta[task_id] = meta
            header_path = os.path.join(self._dir(task_id), "header.json")
            tmp = header_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"piece_size": piece_size, "content_length": content_length}, f)
            os.replace(tmp, header_path)

    def load_task(self, task_id: str) -> bool:
        return self._load_meta(task_id) is not None

    def write_piece(self, task_id: str, number: int, data: bytes) -> int:
        meta = self._load_meta(task_id)
        if meta is None:
            raise KeyError(task_id)
        path = os.path.join(self._dir(task_id), "data")
        with self._mu:
            # Serialized create+write: a concurrent first-write pair must
            # not both open "wb" (the second truncates the first's piece).
            if not os.path.exists(path):
                open(path, "wb").close()
            with open(path, "r+b") as f:
                f.seek(number * meta["piece_size"])
                f.write(data)
            info = {"length": len(data), "crc": zlib.crc32(data)}
            meta["pieces"][number] = info
            self._append_journal(task_id, number, info)
        return len(data)

    def piece_size(self, task_id: str) -> int:
        meta = self._load_meta(task_id)
        return meta["piece_size"] if meta else -1

    def read_piece(self, task_id: str, number: int, *, max_len: Optional[int] = None, verify: bool = True) -> bytes:
        meta = self._load_meta(task_id)
        if meta is None or number not in meta["pieces"]:
            raise KeyError(f"piece {number} of {task_id}")
        info = meta["pieces"][number]
        length = info["length"] if max_len is None else min(max_len, info["length"])
        with open(os.path.join(self._dir(task_id), "data"), "rb") as f:
            f.seek(number * meta["piece_size"])
            data = f.read(length)
        # A max_len-limited read can't cover the whole-piece digest; the
        # write-time crc stands for it (read_piece_at documents the same).
        if verify and length == info["length"] and zlib.crc32(data) != info["crc"]:
            raise IOError(f"crc mismatch piece {number} of {task_id}")
        return data

    def read_piece_at(
        self, task_id: str, number: int, offset: int, max_len: int
    ) -> bytes:
        """Sub-piece read: ``max_len`` bytes of piece ``number`` starting
        ``offset`` bytes in — a Range request for 100 bytes reads 100
        bytes, not a 4 MiB piece.  The whole-piece crc can't cover a
        partial read; the write-time digest stands for the span."""
        meta = self._load_meta(task_id)
        if meta is None or number not in meta["pieces"]:
            raise KeyError(f"piece {number} of {task_id}")
        info = meta["pieces"][number]
        if offset >= info["length"] or max_len <= 0:
            return b""
        take = min(max_len, info["length"] - offset)
        with open(os.path.join(self._dir(task_id), "data"), "rb") as f:
            f.seek(number * meta["piece_size"] + offset)
            return f.read(take)

    def piece_file_span(
        self, task_id: str, number: int
    ) -> Optional[Tuple[str, int, int]]:
        """(path, byte offset, length) of a committed piece inside the
        plain data file — the zero-copy (``os.sendfile``) serve handle.
        None when the piece isn't committed."""
        meta = self._load_meta(task_id)
        if meta is None or number not in meta["pieces"]:
            return None
        return (
            os.path.join(self._dir(task_id), "data"),
            number * meta["piece_size"],
            meta["pieces"][number]["length"],
        )

    def piece_count(self, task_id: str) -> int:
        meta = self._load_meta(task_id)
        return len(meta["pieces"]) if meta else 0

    def piece_bitmap(self, task_id: str, n_pieces: int) -> np.ndarray:
        out = np.zeros(n_pieces, dtype=np.uint8)
        meta = self._load_meta(task_id)
        if meta:
            for n in meta["pieces"]:
                if n < n_pieces:
                    out[n] = 1
        return out

    def task_bytes(self, task_id: str) -> int:
        meta = self._load_meta(task_id)
        if not meta:
            return 0
        return sum(p["length"] for p in meta["pieces"].values())

    def content_length(self, task_id: str) -> int:
        meta = self._load_meta(task_id)
        return meta["content_length"] if meta else -1

    def delete_task(self, task_id: str) -> None:
        import shutil

        with self._mu:
            self._meta.pop(task_id, None)
        shutil.rmtree(self._dir(task_id), ignore_errors=True)

    def close(self) -> None:
        pass


class DaemonStorage:
    """Task-level storage manager with quota GC.

    ``prefer_native=True`` uses the C++ engine when it builds; tests can
    force the Python engine for hermeticity.
    """

    def __init__(
        self,
        root: str,
        *,
        quota_bytes: int = 10 << 30,
        prefer_native: bool = True,
    ) -> None:
        self.root = root
        self.quota_bytes = quota_bytes
        engine = None
        if prefer_native and native.available():
            try:
                engine = native.NativePieceStore(root)
            except native.NativeError:
                engine = None
        self.engine = engine or _PyPieceStore(root)
        self._mu = threading.Lock()
        self._tasks: Dict[str, dict] = {}  # task_id → {piece_size, atime}

    @property
    def is_native(self) -> bool:
        return not isinstance(self.engine, _PyPieceStore)

    # -- task lifecycle ------------------------------------------------------

    def register_task(self, task_id: str, *, piece_size: int, content_length: int) -> None:
        self.engine.create_task(task_id, piece_size, content_length)
        with self._mu:
            self._tasks[task_id] = {"piece_size": piece_size, "atime": time.time()}

    def reload_persistent_tasks(self, task_ids: List[str]) -> List[str]:
        """Crash restart: reopen tasks that survived on disk
        (storage_manager.go:703-760 ReloadPersistentTask)."""
        loaded = []
        for tid in task_ids:
            if self.engine.load_task(tid):
                with self._mu:
                    self._tasks[tid] = {
                        "piece_size": 0,
                        "atime": time.time(),
                    }
                loaded.append(tid)
        return loaded

    def scan_disk_tasks(self) -> List[str]:
        """Task dirs present on disk (restart discovery)."""
        try:
            return sorted(
                d
                for d in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, d))
            )
        except FileNotFoundError:
            return []

    # -- pieces --------------------------------------------------------------

    def write_piece(self, task_id: str, number: int, data: bytes) -> int:
        with self._mu:
            if task_id in self._tasks:
                self._tasks[task_id]["atime"] = time.time()
        return self.engine.write_piece(task_id, number, data)

    def touch_task(self, task_id: str) -> None:
        """LRU-evidence touch for commits that bypassed ``write_piece`` —
        the in-engine fetch loop (DESIGN.md §28) writes pieces directly
        through the native engine; without the touch a task filled that
        way would look idle to quota reclaim."""
        with self._mu:
            if task_id in self._tasks:
                self._tasks[task_id]["atime"] = time.time()

    def read_piece(self, task_id: str, number: int, *, verify: bool = True) -> bytes:
        with self._mu:
            if task_id in self._tasks:
                self._tasks[task_id]["atime"] = time.time()
        return self.engine.read_piece(task_id, number, verify=verify)

    def read_piece_at(
        self, task_id: str, number: int, offset: int, max_len: int
    ) -> bytes:
        """Sub-piece read for Range serving: only the requested span hits
        the disk when the engine supports offset reads; engines without
        them (the native store's ctypes surface) fall back to a
        whole-piece read + slice."""
        with self._mu:
            if task_id in self._tasks:
                self._tasks[task_id]["atime"] = time.time()
        at = getattr(self.engine, "read_piece_at", None)
        if at is not None:
            return at(task_id, number, offset, max_len)
        data = self.engine.read_piece(task_id, number)
        return data[offset : offset + max_len]

    def piece_file_span(
        self, task_id: str, number: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy serve handle: (path, offset, length) of a committed
        piece inside the engine's plain data file, or None when the
        engine doesn't expose one (native store — its own in-engine
        server already serves via sendfile)."""
        span_fn = getattr(self.engine, "piece_file_span", None)
        return span_fn(task_id, number) if span_fn is not None else None

    def range_file_span(
        self, task_id: str, start: int, length: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy handle for a BYTE RANGE: pieces are laid out at
        ``number * piece_size`` in one data file, so a content byte range
        maps 1:1 onto a contiguous file span — IF every overlapping piece
        is committed.  None otherwise (serve falls back to piece reads)."""
        ps = self.piece_size(task_id)
        total = self.content_length(task_id)
        if ps <= 0 or total < 0 or length <= 0 or start < 0:
            return None
        end = min(start + length, total)
        if end <= start:
            return None
        first, last = start // ps, (end - 1) // ps
        path = None
        for num in range(first, last + 1):
            span = self.piece_file_span(task_id, num)
            if span is None:
                return None
            path = span[0]
        return (path, start, end - start)

    def piece_bitmap(self, task_id: str, n_pieces: int) -> np.ndarray:
        return self.engine.piece_bitmap(task_id, n_pieces)

    def has_piece(self, task_id: str, number: int) -> bool:
        bm = self.engine.piece_bitmap(task_id, number + 1)
        return bool(bm[number])

    def task_bytes(self, task_id: str) -> int:
        return self.engine.task_bytes(task_id)

    def held_pieces(self, task_id: str) -> int:
        """Pieces actually written and committed — NOT the header total
        (n_pieces): progress reporting must count data on disk."""
        try:
            return self.engine.piece_count(task_id)
        except Exception as exc:  # noqa: BLE001 — unknown task → nothing held
            logger.debug("piece_count(%s): %s", task_id, exc)
            return 0

    def content_length(self, task_id: str) -> int:
        """Header content length; -1 when the task is unknown."""
        return self.engine.content_length(task_id)

    def piece_size(self, task_id: str) -> int:
        """Header piece size; -1 when the task is unknown."""
        return self.engine.piece_size(task_id)

    def n_pieces(self, task_id: str) -> int:
        """Piece count from the task header; -1 when the header is absent
        or invalid (single owner of the ceil-div + validity idiom)."""
        total = self.engine.content_length(task_id)
        ps = self.engine.piece_size(task_id)
        if total < 0 or ps <= 0:
            return -1
        return (total + ps - 1) // ps

    def read_task_bytes(self, task_id: str) -> bytes:
        """Reassemble a completed task's content from its pieces."""
        total = self.engine.content_length(task_id)
        ps = self.engine.piece_size(task_id)
        if total < 0 or ps <= 0:
            raise KeyError(f"task {task_id} has no header")
        out = bytearray()
        remaining = total
        n = 0
        while remaining > 0:
            piece = self.read_piece(task_id, n)
            out += piece[: min(len(piece), remaining)]
            remaining -= len(piece)
            n += 1
        return bytes(out)

    def total_bytes(self) -> int:
        with self._mu:
            tids = list(self._tasks)
        return sum(self.engine.task_bytes(t) for t in tids)

    def delete_task(self, task_id: str) -> None:
        with self._mu:
            self._tasks.pop(task_id, None)
        self.engine.delete_task(task_id)

    # -- quota GC (Reclaimer) ------------------------------------------------

    def reclaim(self) -> List[str]:
        """Evict least-recently-used tasks until under quota
        (storage_manager.go Reclaimer :82-91)."""
        reclaimed: List[str] = []
        while self.total_bytes() > self.quota_bytes:
            with self._mu:
                if not self._tasks:
                    break
                victim = min(self._tasks, key=lambda t: self._tasks[t]["atime"])
            self.delete_task(victim)
            reclaimed.append(victim)
        return reclaimed

    def close(self) -> None:
        self.engine.close()
