"""Upload manager: serve local pieces to other peers.

Reference: client/daemon/upload/upload_manager.go:59-76 — an HTTP piece
server answering range requests from peers.  Transport-neutral core: the
in-process swarm calls ``serve_piece`` directly; an HTTP binding wraps the
same method.  Concurrency is capped the way the scheduler models it
(Host.concurrent_upload_limit).

Two serve shapes (DESIGN.md §22):

- **buffered** — ``serve_piece`` / ``serve_piece_span`` materialize the
  bytes (the in-process transport, TLS serving, and every chaos drill
  that tears bodies ride this path);
- **zero-copy** — ``piece_sendfile_span`` / ``range_sendfile_span`` hand
  the HTTP server a ``(path, offset, length)`` file span so the bytes go
  kernel→socket via ``os.sendfile`` without ever entering Python.  Both
  shapes share ONE accounting gate (``begin_upload``/``end_upload``), so
  the concurrency cap and the upload counters mean the same thing on
  either path — and tests prove the two byte-identical.

Tenant QoS (DESIGN.md §26): tasks are stamped with the tenant that
created them (``register_task_tenant``); with a ``QoSPolicy`` installed,
the shared gate also enforces each tenant's ``upload_rate_bytes_s`` cap
with a post-paid token bucket — a request is admitted while the
tenant's balance is positive and the ACTUAL bytes are charged at
``end_upload`` (piece sizes are not known before the read), so a
flooding tenant's serves go 503 (``UploadThrottled``) while other
tenants' pieces keep flowing.  Per-tenant byte totals feed the bounded
``tenant_class`` metric label, never raw tenant ids (DF017).
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Dict, Optional, Tuple

from ..utils.metrics import default_registry as _reg
from .storage import DaemonStorage

if TYPE_CHECKING:  # duck-typed at runtime (no qos import on boot)
    from ..qos.policy import QoSPolicy

UPLOAD_THROTTLED_TOTAL = _reg.counter(
    "daemon_upload_throttled_total",
    "Piece serves refused by a tenant's upload-bandwidth cap",
    ["tenant_class"],
)
UPLOAD_TENANT_BYTES_TOTAL = _reg.counter(
    "daemon_upload_tenant_bytes_total",
    "Bytes served from the upload path, by tenant class",
    ["tenant_class"],
)

_DEFAULT_TENANT = "default"

# Hard bound on tenant-keyed accounting state (buckets + byte totals).
# Requester attribution is already gated on KNOWN tenants, so this only
# bites if a runaway registrar stamps thousands of distinct owners —
# overflow folds into the default bucket instead of growing without
# limit (the DF017 discipline applied to memory, not just labels).
_MAX_TRACKED_TENANTS = 4096


class UploadBusy(RuntimeError):
    pass


class UploadThrottled(UploadBusy):
    """A tenant's upload-bandwidth cap refused this serve (the wire
    servers answer 503 exactly like the concurrency cap — the client's
    reschedule/backoff machinery already knows the shape)."""


class _TenantBandwidth:
    """Post-paid byte bucket: admit while balance > 0, charge actual
    bytes afterwards; the balance refills at the capped rate and may go
    negative (the debt model standard for bandwidth shaping where sizes
    are only known after the read)."""

    __slots__ = ("rate", "balance", "last")

    def __init__(self, rate: float) -> None:
        self.rate = rate
        self.balance = rate  # one second of burst headroom
        self.last = time.monotonic()

    def refill(self, now: float) -> None:
        self.balance = min(self.rate, self.balance + (now - self.last) * self.rate)
        self.last = now


class UploadManager:
    def __init__(
        self,
        storage: DaemonStorage,
        *,
        concurrent_limit: int = 50,
        qos_policy: "Optional[QoSPolicy]" = None,
    ) -> None:
        self.storage = storage
        self.concurrent_limit = concurrent_limit
        self._mu = threading.Lock()
        self._active = 0
        self.upload_count = 0
        self.upload_failed_count = 0
        self.bytes_served = 0
        self.throttled_count = 0
        # Tenant plane: task → owning tenant (stamped at download
        # registration), per-tenant post-paid byte buckets, per-tenant
        # served-byte totals (raw ids live HERE, never on metric labels).
        self._policy = qos_policy
        self._task_tenant: Dict[str, str] = {}
        self._registered_tenants: set = set()
        self._tenant_bw: Dict[str, _TenantBandwidth] = {}
        self.tenant_bytes: Dict[str, int] = {}

    @property
    def active(self) -> int:
        with self._mu:
            return self._active

    # -- tenant plane --------------------------------------------------------

    def set_qos_policy(self, policy: "Optional[QoSPolicy]") -> None:
        with self._mu:
            self._policy = policy
            self._tenant_bw.clear()  # rebuilt lazily from the new caps

    def register_task_tenant(self, task_id: str, tenant: str) -> None:
        """Stamp the tenant that created ``task_id`` — serves of the
        task's pieces account (and throttle) against it.  Registration
        also marks the tenant as KNOWN, so its wire-stamped requests on
        other tenants' tasks are honored by requester-pays."""
        with self._mu:
            self._task_tenant[task_id] = tenant or _DEFAULT_TENANT
            self._registered_tenants.add(tenant or _DEFAULT_TENANT)

    def tenant_of(self, task_id: Optional[str]) -> str:
        with self._mu:
            return self._task_tenant.get(task_id or "", _DEFAULT_TENANT)

    def _bw_locked(self, tenant: str) -> Optional[_TenantBandwidth]:
        policy = self._policy
        if policy is None:
            return None
        rate = float(policy.for_tenant(tenant).upload_rate_bytes_s)
        if rate <= 0.0:
            self._tenant_bw.pop(tenant, None)
            return None
        bw = self._tenant_bw.get(tenant)
        if bw is None or bw.rate != rate:
            bw = self._tenant_bw[tenant] = _TenantBandwidth(rate)
        return bw

    # -- shared accounting gate (both serve shapes) --------------------------

    def _known_tenant_locked(self, tenant: str) -> bool:
        """A tenant this daemon can vouch for: a QoS-policy row or a
        locally registered task owner."""
        if tenant in self._registered_tenants:
            return True
        policy = self._policy
        return policy is not None and tenant in policy

    def _tracked_tenant_locked(self, tenant: str) -> str:
        """Accounting key for ``tenant``, folding overflow into the
        default bucket once the per-tenant maps hit their bound."""
        if (
            tenant == _DEFAULT_TENANT
            or tenant in self.tenant_bytes
            or tenant in self._tenant_bw
        ):
            return tenant
        if (
            len(self.tenant_bytes) >= _MAX_TRACKED_TENANTS
            or len(self._tenant_bw) >= _MAX_TRACKED_TENANTS
        ):
            return _DEFAULT_TENANT
        return tenant

    def _charged_tenant_locked(
        self, task_id: Optional[str], requester_tenant: Optional[str]
    ) -> str:
        """Who pays for this serve: the REQUESTING tenant when the wire
        carried one (X-Dragonfly-Tenant) AND it names a tenant this
        daemon already knows — a QoS-policy row or a registered task
        owner — else the task's owner.  Before requester attribution
        existed, a stranger's cross-tenant pulls drained the owner's
        byte bucket (DESIGN.md §28); but the header is UNAUTHENTICATED,
        so an unknown name is treated as absent: honoring it verbatim
        would let any client spoof a victim tenant's bucket into debt
        (the very attack requester-pays fixes, now remotely steerable)
        or rotate fabricated names into fresh default-class buckets
        past their real cap."""
        if requester_tenant and self._known_tenant_locked(requester_tenant):
            return self._tracked_tenant_locked(requester_tenant)
        return self._tracked_tenant_locked(
            self._task_tenant.get(task_id or "", _DEFAULT_TENANT)
        )

    def begin_upload(
        self,
        task_id: Optional[str] = None,
        requester_tenant: Optional[str] = None,
    ) -> None:
        """Claim one upload slot; raises UploadBusy past the cap and
        UploadThrottled when the charged tenant's bandwidth cap is in
        debt (the requester when known, else the task owner).  Callers
        MUST pair with ``end_upload`` (the sendfile server path wraps
        its own stream between the two)."""
        from ..utils import faultinject

        # Throttle chaos seam (DF004): injected drops/delays here prove
        # a wedged/refused gate degrades to the client's reschedule
        # path, never a stuck serve.
        faultinject.fire("daemon.upload.throttle")
        with self._mu:
            if self._active >= self.concurrent_limit:
                raise UploadBusy(f"{self._active} active uploads")
            tenant = self._charged_tenant_locked(task_id, requester_tenant)
            bw = self._bw_locked(tenant)
            if bw is not None:
                bw.refill(time.monotonic())
                if bw.balance <= 0.0:
                    self.throttled_count += 1
                    cls = (
                        self._policy.class_of(tenant)
                        if self._policy is not None else "silver"
                    )
                    UPLOAD_THROTTLED_TOTAL.inc(tenant_class=cls)
                    raise UploadThrottled(
                        f"tenant upload cap: {bw.balance:.0f} byte balance"
                    )
            self._active += 1

    def end_upload(
        self,
        ok: bool,
        nbytes: int = 0,
        task_id: Optional[str] = None,
        requester_tenant: Optional[str] = None,
    ) -> None:
        with self._mu:
            self._active -= 1
            if ok:
                self.upload_count += 1
                self.bytes_served += nbytes
                tenant = self._charged_tenant_locked(task_id, requester_tenant)
                self.tenant_bytes[tenant] = (
                    self.tenant_bytes.get(tenant, 0) + nbytes
                )
                bw = self._bw_locked(tenant)
                if bw is not None and nbytes:
                    bw.refill(time.monotonic())
                    bw.balance -= nbytes
                if nbytes and self._policy is not None:
                    UPLOAD_TENANT_BYTES_TOTAL.inc(
                        amount=nbytes, tenant_class=self._policy.class_of(tenant)
                    )
            else:
                self.upload_failed_count += 1

    # -- buffered serving ----------------------------------------------------

    # dflint: hotpath
    def serve_piece(
        self, task_id: str, number: int,
        requester_tenant: Optional[str] = None,
    ) -> bytes:
        """One piece upload; raises UploadBusy past the concurrency cap,
        KeyError when the piece isn't local."""
        from ..utils import faultinject

        # Upload-path chaos seam (drop/delay/dferror before the read,
        # truncate on the body): covers BOTH piece transports — the HTTP
        # server and the in-process fetcher call through here.
        faultinject.fire("daemon.upload.serve_piece")
        self.begin_upload(task_id, requester_tenant)
        ok = False
        try:
            data = self.storage.read_piece(task_id, number)
            # The body seam may raise (injected drop): that upload FAILED.
            data = faultinject.fire("daemon.upload.body", data)
            ok = True
            return data
        finally:
            self.end_upload(ok, len(data) if ok else 0, task_id,
                            requester_tenant)

    def serve_piece_span(
        self, task_id: str, number: int, offset: int, max_len: int,
        requester_tenant: Optional[str] = None,
    ) -> bytes:
        """Buffered SUB-PIECE upload: only the requested span is read
        (storage.read_piece_at) — a tiny Range request no longer
        materializes a whole 4 MiB piece.  Same cap/counters/seams as
        serve_piece."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.serve_piece")
        self.begin_upload(task_id, requester_tenant)
        ok = False
        try:
            data = self.storage.read_piece_at(task_id, number, offset, max_len)
            data = faultinject.fire("daemon.upload.body", data)
            ok = True
            return data
        finally:
            self.end_upload(ok, len(data) if ok else 0, task_id,
                            requester_tenant)

    def serve_range(
        self, task_id: str, start: int, length: int, piece_size: int,
        requester_tenant: Optional[str] = None,
    ) -> bytes:
        """Byte-range read assembled from SUB-PIECE reads (HTTP Range
        semantics): each overlapping piece contributes only its requested
        span instead of a whole-piece materialize-then-slice."""
        out = bytearray()
        pos = start
        end = start + length
        while pos < end:
            num = pos // piece_size
            off = pos - num * piece_size
            chunk = self.serve_piece_span(task_id, num, off, end - pos,
                                          requester_tenant)
            if not chunk:
                break
            out += chunk
            pos += len(chunk)
        return bytes(out)

    # -- zero-copy serving ---------------------------------------------------

    def piece_sendfile_span(
        self, task_id: str, number: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy serve handle for one piece, or None → caller uses the
        buffered path.  A scenario that tears BODIES (truncate faults on
        the upload/serve body seams) needs byte payloads to cut, so it
        forces the buffered path; drop/delay/dferror/crash faults fire
        right here and behave identically on either path."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.sendfile")
        if faultinject.truncates("daemon.upload.body") or faultinject.truncates(
            "piece.server.body"
        ):
            return None
        return self.storage.piece_file_span(task_id, number)

    def range_sendfile_span(
        self, task_id: str, start: int, length: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy handle for a byte range (pieces are contiguous in the
        engine's data file); None → buffered serve_range fallback."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.sendfile")
        if faultinject.truncates("daemon.upload.body") or faultinject.truncates(
            "piece.server.body"
        ):
            return None
        return self.storage.range_file_span(task_id, start, length)
