"""Upload manager: serve local pieces to other peers.

Reference: client/daemon/upload/upload_manager.go:59-76 — an HTTP piece
server answering range requests from peers.  Transport-neutral core: the
in-process swarm calls ``serve_piece`` directly; an HTTP binding wraps the
same method.  Concurrency is capped the way the scheduler models it
(Host.concurrent_upload_limit).
"""

from __future__ import annotations

import threading

from .storage import DaemonStorage


class UploadBusy(RuntimeError):
    pass


class UploadManager:
    def __init__(self, storage: DaemonStorage, *, concurrent_limit: int = 50) -> None:
        self.storage = storage
        self.concurrent_limit = concurrent_limit
        self._mu = threading.Lock()
        self._active = 0
        self.upload_count = 0
        self.upload_failed_count = 0

    @property
    def active(self) -> int:
        with self._mu:
            return self._active

    def serve_piece(self, task_id: str, number: int) -> bytes:
        """One piece upload; raises UploadBusy past the concurrency cap,
        KeyError when the piece isn't local."""
        from ..utils import faultinject

        # Upload-path chaos seam (drop/delay/dferror before the read,
        # truncate on the body): covers BOTH piece transports — the HTTP
        # server and the in-process fetcher call through here.
        faultinject.fire("daemon.upload.serve_piece")
        with self._mu:
            if self._active >= self.concurrent_limit:
                raise UploadBusy(f"{self._active} active uploads")
            self._active += 1
        try:
            data = self.storage.read_piece(task_id, number)
            with self._mu:
                self.upload_count += 1
            return faultinject.fire("daemon.upload.body", data)
        except Exception:
            with self._mu:
                self.upload_failed_count += 1
            raise
        finally:
            with self._mu:
                self._active -= 1

    def serve_range(self, task_id: str, start: int, length: int, piece_size: int) -> bytes:
        """Byte-range read assembled from pieces (HTTP Range semantics)."""
        out = bytearray()
        pos = start
        end = start + length
        while pos < end:
            num = pos // piece_size
            piece = self.serve_piece(task_id, num)
            off = pos - num * piece_size
            take = min(len(piece) - off, end - pos)
            if take <= 0:
                break
            out += piece[off : off + take]
            pos += take
        return bytes(out)
