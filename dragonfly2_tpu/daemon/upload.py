"""Upload manager: serve local pieces to other peers.

Reference: client/daemon/upload/upload_manager.go:59-76 — an HTTP piece
server answering range requests from peers.  Transport-neutral core: the
in-process swarm calls ``serve_piece`` directly; an HTTP binding wraps the
same method.  Concurrency is capped the way the scheduler models it
(Host.concurrent_upload_limit).

Two serve shapes (DESIGN.md §22):

- **buffered** — ``serve_piece`` / ``serve_piece_span`` materialize the
  bytes (the in-process transport, TLS serving, and every chaos drill
  that tears bodies ride this path);
- **zero-copy** — ``piece_sendfile_span`` / ``range_sendfile_span`` hand
  the HTTP server a ``(path, offset, length)`` file span so the bytes go
  kernel→socket via ``os.sendfile`` without ever entering Python.  Both
  shapes share ONE accounting gate (``begin_upload``/``end_upload``), so
  the concurrency cap and the upload counters mean the same thing on
  either path — and tests prove the two byte-identical.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from .storage import DaemonStorage


class UploadBusy(RuntimeError):
    pass


class UploadManager:
    def __init__(self, storage: DaemonStorage, *, concurrent_limit: int = 50) -> None:
        self.storage = storage
        self.concurrent_limit = concurrent_limit
        self._mu = threading.Lock()
        self._active = 0
        self.upload_count = 0
        self.upload_failed_count = 0
        self.bytes_served = 0

    @property
    def active(self) -> int:
        with self._mu:
            return self._active

    # -- shared accounting gate (both serve shapes) --------------------------

    def begin_upload(self) -> None:
        """Claim one upload slot; raises UploadBusy past the cap.  Callers
        MUST pair with ``end_upload`` (the sendfile server path wraps its
        own stream between the two)."""
        with self._mu:
            if self._active >= self.concurrent_limit:
                raise UploadBusy(f"{self._active} active uploads")
            self._active += 1

    def end_upload(self, ok: bool, nbytes: int = 0) -> None:
        with self._mu:
            self._active -= 1
            if ok:
                self.upload_count += 1
                self.bytes_served += nbytes
            else:
                self.upload_failed_count += 1

    # -- buffered serving ----------------------------------------------------

    # dflint: hotpath
    def serve_piece(self, task_id: str, number: int) -> bytes:
        """One piece upload; raises UploadBusy past the concurrency cap,
        KeyError when the piece isn't local."""
        from ..utils import faultinject

        # Upload-path chaos seam (drop/delay/dferror before the read,
        # truncate on the body): covers BOTH piece transports — the HTTP
        # server and the in-process fetcher call through here.
        faultinject.fire("daemon.upload.serve_piece")
        self.begin_upload()
        ok = False
        try:
            data = self.storage.read_piece(task_id, number)
            # The body seam may raise (injected drop): that upload FAILED.
            data = faultinject.fire("daemon.upload.body", data)
            ok = True
            return data
        finally:
            self.end_upload(ok, len(data) if ok else 0)

    def serve_piece_span(
        self, task_id: str, number: int, offset: int, max_len: int
    ) -> bytes:
        """Buffered SUB-PIECE upload: only the requested span is read
        (storage.read_piece_at) — a tiny Range request no longer
        materializes a whole 4 MiB piece.  Same cap/counters/seams as
        serve_piece."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.serve_piece")
        self.begin_upload()
        ok = False
        try:
            data = self.storage.read_piece_at(task_id, number, offset, max_len)
            data = faultinject.fire("daemon.upload.body", data)
            ok = True
            return data
        finally:
            self.end_upload(ok, len(data) if ok else 0)

    def serve_range(self, task_id: str, start: int, length: int, piece_size: int) -> bytes:
        """Byte-range read assembled from SUB-PIECE reads (HTTP Range
        semantics): each overlapping piece contributes only its requested
        span instead of a whole-piece materialize-then-slice."""
        out = bytearray()
        pos = start
        end = start + length
        while pos < end:
            num = pos // piece_size
            off = pos - num * piece_size
            chunk = self.serve_piece_span(task_id, num, off, end - pos)
            if not chunk:
                break
            out += chunk
            pos += len(chunk)
        return bytes(out)

    # -- zero-copy serving ---------------------------------------------------

    def piece_sendfile_span(
        self, task_id: str, number: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy serve handle for one piece, or None → caller uses the
        buffered path.  A scenario that tears BODIES (truncate faults on
        the upload/serve body seams) needs byte payloads to cut, so it
        forces the buffered path; drop/delay/dferror/crash faults fire
        right here and behave identically on either path."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.sendfile")
        if faultinject.truncates("daemon.upload.body") or faultinject.truncates(
            "piece.server.body"
        ):
            return None
        return self.storage.piece_file_span(task_id, number)

    def range_sendfile_span(
        self, task_id: str, start: int, length: int
    ) -> Optional[Tuple[str, int, int]]:
        """Zero-copy handle for a byte range (pieces are contiguous in the
        engine's data file); None → buffered serve_range fallback."""
        from ..utils import faultinject

        faultinject.fire("daemon.upload.sendfile")
        if faultinject.truncates("daemon.upload.body") or faultinject.truncates(
            "piece.server.body"
        ):
            return None
        return self.storage.range_file_span(task_id, start, length)
