"""HTTP forward proxy diverting matched requests into P2P.

Reference: client/daemon/proxy — regex rules route GETs into the P2P
download path (proxy.go:275-310), registry-mirror rewriting, pass-through
for everything else; transport.go's round-tripper is the divert seam.

Here: a stdlib HTTP proxy server whose rule set maps URL regexes →
P2P download via the daemon's conductor; unmatched GETs are fetched
directly (urllib); CONNECT requests are tunneled as raw byte relays
(HTTPS pass-through — proxy.go's tunnel path; SNI-hijack into P2P is a
round-2 target).

Pass-through serving (DESIGN.md §25): diverted GETs STREAM the task via
``open_stream`` — the response body is fed from the commit tee while
the swarm download runs (zero disk reads on the fast path) — and honor
single-range ``Range:`` headers (RFC 7233 via utils/httprange) as 206
responses over the IN-FLIGHT task: only the overlapping piece window is
scheduled first, the client never waits for full completion.
"""

from __future__ import annotations

import re
import socket
import threading
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Pattern, Tuple

from ..utils.httprange import (
    RangeNotSatisfiable,
    content_range,
    parse_range,
    unsatisfiable_content_range,
)
from .relay import fetch_via_p2p, relay_bytes


@dataclass
class ProxyRule:
    """proxy.go's Proxy rules: regex + use-p2p flag (+ optional rewrite)."""

    pattern: Pattern
    use_p2p: bool = True
    redirect: str = ""  # registry-mirror style prefix rewrite

    @classmethod
    def compile(cls, regex: str, *, use_p2p: bool = True, redirect: str = "") -> "ProxyRule":
        return cls(pattern=re.compile(regex), use_p2p=use_p2p, redirect=redirect)


class ProxyRouter:
    """Rule matching + divert decision (transport.go shouldUseDragonfly)."""

    def __init__(self, rules: Optional[List[ProxyRule]] = None):
        self.rules = rules or []

    def route(self, url: str) -> Tuple[bool, str]:
        """→ (use_p2p, effective_url)."""
        for rule in self.rules:
            if rule.pattern.search(url):
                effective = url
                if rule.redirect:
                    effective = rule.pattern.sub(rule.redirect, url, count=1)
                return rule.use_p2p, effective
        return False, url


class P2PProxy:
    def __init__(
        self,
        daemon,
        router: ProxyRouter,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        piece_size: int = 4 << 20,
        direct_timeout: float = 30.0,
        tunnel_idle_timeout: float = 300.0,
    ):
        self.daemon = daemon
        self.router = router
        self.piece_size = piece_size
        self.direct_timeout = direct_timeout
        self.tunnel_idle_timeout = tunnel_idle_timeout
        self.stats = {"p2p": 0, "direct": 0, "tunnel": 0}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send_416(self, total: int) -> None:
                self.send_response(416)
                self.send_header(
                    "Content-Range", unsatisfiable_content_range(total)
                )
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                # Absolute-form (true forward-proxy clients send
                # `GET http://host/path`) or path-embedded
                # (`GET /http://host/path`, gateway-style callers — any
                # scheme the rule set routes, incl. dfstore://).
                url = self.path
                if re.match(r"^/[a-z][a-z0-9+.-]*://", url):
                    url = url[1:]
                use_p2p, effective = proxy.router.route(url)
                rng_header = self.headers.get("Range")
                if use_p2p:
                    # STREAM the P2P task (StartStreamTask consumer): the
                    # response body flows from the commit tee as the
                    # download commits — a client starts receiving long
                    # before the task finishes, with no disk round-trip.
                    # A Range request maps onto the overlapping piece
                    # window of the IN-FLIGHT task (206 over a task that
                    # may still be mid-swarm).
                    try:
                        handle, rng = proxy._open_p2p_stream(
                            effective, rng_header
                        )
                    except RangeNotSatisfiable as exc:
                        self._send_416(exc.total)
                        return
                    except Exception:  # noqa: BLE001 — proxy boundary
                        self.send_error(502)
                        return
                    proxy.stats["p2p"] += 1
                    total = max(handle.content_length, 0)
                    if rng is not None:
                        start, end = rng
                        self.send_response(206)
                        self.send_header(
                            "Content-Range", content_range(start, end, total)
                        )
                        self.send_header(
                            "Content-Length", str(end - start + 1)
                        )
                    else:
                        self.send_response(200)
                        self.send_header("Content-Length", str(total))
                    self.send_header("Accept-Ranges", "bytes")
                    self.end_headers()
                    try:
                        for chunk in handle.chunks():
                            self.wfile.write(chunk)
                    except (IOError, OSError):
                        # Mid-stream failure: the status is already on
                        # the wire — dropping the connection is the only
                        # honest signal (short body ≠ success).
                        handle.close()
                        self.close_connection = True
                    return
                try:
                    body = proxy._fetch_direct(effective)
                    proxy.stats["direct"] += 1
                except Exception:  # noqa: BLE001 — proxy boundary
                    self.send_error(502)
                    return
                # Direct fetches honor the same Range shapes so a rule
                # flip (p2p ↔ direct) never changes range semantics.
                try:
                    rng = parse_range(rng_header, len(body))
                except RangeNotSatisfiable:
                    self._send_416(len(body))
                    return
                if rng is not None:
                    start, end = rng
                    self.send_response(206)
                    self.send_header(
                        "Content-Range", content_range(start, end, len(body))
                    )
                    body = body[start : end + 1]
                else:
                    self.send_response(200)
                self.send_header("Accept-Ranges", "bytes")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_CONNECT(self):
                # HTTPS pass-through: relay raw bytes between the client
                # and the target (the handler thread owns the tunnel).
                try:
                    host_part, _, port_part = self.path.rpartition(":")
                    upstream = socket.create_connection(
                        (host_part, int(port_part)), timeout=10
                    )
                except (OSError, ValueError):
                    self.send_error(502)
                    return
                self.send_response(200, "Connection Established")
                self.end_headers()
                proxy.stats["tunnel"] += 1
                client = self.connection
                try:
                    from ..utils import faultinject

                    faultinject.fire("proxy.tunnel")
                    # Bytes the client pipelined behind the CONNECT headers
                    # (e.g. a TLS ClientHello racing the 200) are sitting in
                    # rfile's buffer, NOT the socket — forward them first or
                    # the handshake stalls.
                    try:
                        buffered = self.rfile.read1(65536) if self.rfile.peek(1) else b""
                    except (OSError, ValueError):
                        buffered = b""
                    if buffered:
                        upstream.sendall(buffered)
                    relay_bytes(client, upstream, proxy.tunnel_idle_timeout)
                finally:
                    upstream.close()
                self.close_connection = True

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def _fetch_p2p(self, url: str) -> bytes:
        return fetch_via_p2p(self.daemon, url, self.piece_size)

    def _open_p2p_stream(self, url: str, rng_header: Optional[str] = None):
        """Divert seam, streaming face: sizing now, bytes as pieces land
        (conductor.open_stream) → ``(handle, (start, end) | None)``.

        When the origin answers a length probe, the Range header parses
        BEFORE the stream opens (an unsatisfiable range never touches
        the swarm, and the piece pull gets the priority hint up front);
        otherwise the stream's own sizing provides the total and the
        window narrows late (best-effort priority).
        """
        total = self.daemon.conductor.probe_content_length(url)
        rng = None
        if total is not None and total >= 0:
            rng = parse_range(rng_header, total)  # may raise 416
            start, length = (rng[0], rng[1] - rng[0] + 1) if rng else (0, None)
            handle = self.daemon.open_stream(
                url, piece_size=self.piece_size, content_length=total,
                start=start, length=length,
            )
            return handle, rng
        handle = self.daemon.open_stream(url, piece_size=self.piece_size)
        try:
            rng = parse_range(rng_header, handle.content_length)
        except RangeNotSatisfiable:
            handle.close()
            raise
        if rng is not None:
            handle.narrow(rng[0], rng[1] + 1)
        return handle, rng

    def _fetch_direct(self, url: str) -> bytes:
        from ..utils import faultinject

        faultinject.fire("proxy.direct")
        with urllib.request.urlopen(url, timeout=self.direct_timeout) as resp:
            return faultinject.fire("proxy.direct.body", resp.read())

    @property
    def port(self) -> int:
        return self.address[1]

    def serve(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="p2p-proxy", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
