"""Peer daemon data plane (reference: client/daemon/).

The download engine that turns scheduler decisions into bytes on disk:

- ``storage``        — local piece store (C++ engine via native bindings,
                       Python fallback) + disk-quota reclaimer
                       (client/daemon/storage/storage_manager.go).
- ``upload``         — serves pieces to other peers
                       (client/daemon/upload/upload_manager.go); in-process
                       transport here, the HTTP/range layer binds onto it.
- ``conductor``      — per-task download orchestration: register →
                       parents → piece workers → back-to-source fallback
                       (client/daemon/peer/peertask_conductor.go).
- ``traffic_shaper`` — per-task bandwidth allocation
                       (client/daemon/peer/traffic_shaper.go).
- ``pex``            — peer exchange pool: membership + per-peer piece
                       advertisement (client/daemon/pex/).
- ``daemon``         — composition root (client/daemon/daemon.go).
"""

from .storage import DaemonStorage  # noqa: F401
from .upload import UploadManager  # noqa: F401
from .conductor import Conductor, DownloadResult, PieceFetcher  # noqa: F401
from .traffic_shaper import TrafficShaper  # noqa: F401
from .pex import PeerExchange  # noqa: F401
from .daemon import Daemon  # noqa: F401
