"""Object-storage gateway: S3-ish operations onto P2P + backend store.

Reference: client/daemon/objectstorage (the daemon's S3/OSS-compatible
HTTP gateway, objectstorage.go:86-103) + client/dfstore semantics
(dfstore.go:54-111 — Get/Put/Copy/Delete/IsExist + metadata through the
daemon).

Reads go P2P-first: the object's task id keys the swarm, so a hot object
is served by peers and the backend sees one fetch per cluster.  Writes
land in the backend and seed the local piece store so this daemon is the
swarm's first parent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from ..objectstorage import ObjectMetadata, ObjectStorageBackend
from ..utils import idgen
from ..utils.httprange import RangeNotSatisfiable, parse_range


@dataclass
class GatewayConfig:
    bucket: str = "dragonfly"
    piece_size: int = 4 << 20


class ObjectGateway:
    def __init__(self, daemon, backend: ObjectStorageBackend, config: Optional[GatewayConfig] = None):
        self.daemon = daemon
        self.backend = backend
        self.config = config or GatewayConfig()
        if not backend.bucket_exists(self.config.bucket):
            backend.create_bucket(self.config.bucket)

    def _object_url(self, key: str) -> str:
        return f"dfstore://{self.config.bucket}/{key.strip('/')}"

    def _task_id(self, key: str) -> str:
        return idgen.task_id(self._object_url(key))

    # -- dfstore ops ---------------------------------------------------------

    def put_object(self, key: str, data: bytes) -> ObjectMetadata:
        meta = self.backend.put_object(self.config.bucket, key, data)
        # Seed the P2P swarm: write the pieces locally AND register with the
        # scheduler as a succeeded peer, so this daemon is handed out as the
        # first parent (the reference's seed-peer trigger path,
        # scheduler/resource/seed_peer.go TriggerTask).
        url = self._object_url(key)
        ps = self.config.piece_size
        n_pieces = max((len(data) + ps - 1) // ps, 1)
        task_id = self._task_id(key)
        self.daemon.storage.register_task(
            task_id, piece_size=ps, content_length=len(data)
        )
        for n in range(n_pieces):
            self.daemon.storage.write_piece(task_id, n, data[n * ps : (n + 1) * ps])

        scheduler = self.daemon.scheduler
        reg = scheduler.register_peer(host=self.daemon.host, url=url, task_id=task_id)
        scheduler.set_task_info(reg.peer, len(data), n_pieces, ps)
        for n in range(n_pieces):
            scheduler.report_piece_finished(
                reg.peer,
                n,
                parent_id="",
                length=min(ps, len(data) - n * ps),
                cost_ns=1,
            )
        scheduler.report_peer_finished(reg.peer)

        if self.daemon.pex is not None:
            self.daemon.pex.advertise(task_id, set(range(n_pieces)))
        return meta

    def get_object(self, key: str) -> bytes:
        """P2P first (other daemons may hold it); backend fallback."""
        try:
            return b"".join(self.get_object_stream(key))
        except (IOError, OSError, KeyError):
            # P2P completely failed → straight backend read.
            return self.backend.get_object(self.config.bucket, key)

    def get_object_stream(self, key: str, *, start: int = 0,
                          length: Optional[int] = None):
        """Streaming read (StartStreamTask consumer): chunks flow from
        the commit tee as the P2P download commits pieces — a hot object
        starts serving before the swarm transfer finishes, with no disk
        round-trip on the fast path.  ``start``/``length`` serve a byte
        window over the in-flight task (the overlapping pieces schedule
        first).  Raises on P2P failure; ``get_object`` adds the backend
        fallback for byte-level callers."""
        return self._open_stream(key, start=start, length=length).chunks()

    def _open_stream(self, key: str, *, start: int = 0,
                     length: Optional[int] = None):
        url = self._object_url(key)
        meta = (
            self.backend.head_object(self.config.bucket, key)
            if self.backend.object_exists(self.config.bucket, key)
            else None
        )
        content_length = meta.content_length if meta else None
        return self.daemon.open_stream(
            url,
            piece_size=self.config.piece_size,
            content_length=content_length,
            start=start,
            length=length,
        )

    def get_object_range(
        self, key: str, range_header: Optional[str]
    ) -> Tuple[Tuple[int, int, int], Iterator[bytes]]:
        """RFC-7233 ranged read over the (possibly in-flight) task:
        ``Range`` header → ``((start, end_inclusive, total), chunks)``.
        A missing/ignorable header serves the full body (start=0,
        end=total-1 — the caller answers 200 instead of 206); an
        unsatisfiable range raises :class:`RangeNotSatisfiable` (416)
        WITHOUT touching the swarm when the backend knows the length."""
        meta = (
            self.backend.head_object(self.config.bucket, key)
            if self.backend.object_exists(self.config.bucket, key)
            else None
        )
        if meta is not None:
            total = meta.content_length
            rng = parse_range(range_header, total)  # may raise 416
            start, length = (
                (rng[0], rng[1] - rng[0] + 1) if rng else (0, None)
            )
            handle = self._open_stream(key, start=start, length=length)
        else:
            # P2P-only object: the stream's own sizing is the total.
            handle = self._open_stream(key)
            total = handle.content_length
            try:
                rng = parse_range(range_header, total)
            except RangeNotSatisfiable:
                handle.close()
                raise
            if rng is not None:
                handle.narrow(rng[0], rng[1] + 1)
        span = rng if rng is not None else (0, max(total - 1, 0))
        return (span[0], span[1], total), handle.chunks()

    def head_object(self, key: str) -> ObjectMetadata:
        return self.backend.head_object(self.config.bucket, key)

    def object_exists(self, key: str) -> bool:
        return self.backend.object_exists(self.config.bucket, key)

    def delete_object(self, key: str) -> None:
        self.backend.delete_object(self.config.bucket, key)
        task_id = self._task_id(key)
        if hasattr(self.daemon, "delete_task"):
            self.daemon.delete_task(task_id)

    def copy_object(self, src: str, dst: str) -> ObjectMetadata:
        return self.backend.copy_object(self.config.bucket, src, dst)

    def list_objects(self, prefix: str = "") -> List[ObjectMetadata]:
        return self.backend.list_objects(self.config.bucket, prefix)


class GatewaySourceFetcher:
    """Back-to-source client for dfstore:// URLs: pieces come from the
    object backend (registered into the daemon's source chain so P2P
    misses fall back to the store, reference's object gateway semantics)."""

    def __init__(self, backend: ObjectStorageBackend):
        self.backend = backend

    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        assert url.startswith("dfstore://"), url
        bucket, key = url[len("dfstore://") :].split("/", 1)
        data = self.backend.get_object(bucket, key)
        return data[number * piece_size : (number + 1) * piece_size]
