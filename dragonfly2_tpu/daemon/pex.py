"""Peer exchange: gossip membership + piece advertisement.

Reference: client/daemon/pex/ — hashicorp/memberlist gossip broadcasts
member metadata and per-peer piece advertisements; peers reclaim entries
on member leave (peer_exchange.go:34-50, member_manager.go, peer_pool.go).

In-process equivalent: a shared gossip bus (the transport seam) over which
each daemon's PeerExchange broadcasts joins/leaves and piece holdings.
The pool answers "who has pieces of task T" without a scheduler
round-trip — the daemon's subtask-reuse and seed-peer discovery path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Set


@dataclass
class MemberMeta:
    host_id: str
    ip: str = ""
    port: int = 0


class GossipBus:
    """The in-process 'network': fan-out of membership + advertisements."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._members: Dict[str, "PeerExchange"] = {}

    def join(self, pex: "PeerExchange") -> None:
        with self._mu:
            others = list(self._members.values())
            self._members[pex.meta.host_id] = pex
        for other in others:
            other._on_join(pex.meta)
            pex._on_join(other.meta)
            # New member learns existing holdings.
            for task_id, pieces in other.local_holdings():
                pex._on_advertise(other.meta.host_id, task_id, pieces)

    def leave(self, host_id: str) -> None:
        with self._mu:
            self._members.pop(host_id, None)
            others = list(self._members.values())
        for other in others:
            other._on_leave(host_id)

    def broadcast_advertise(self, src_host_id: str, task_id: str, pieces: Set[int]) -> None:
        with self._mu:
            others = [p for h, p in self._members.items() if h != src_host_id]
        for other in others:
            other._on_advertise(src_host_id, task_id, pieces)

    def broadcast_retract(self, src_host_id: str, task_id: str) -> None:
        with self._mu:
            others = [p for h, p in self._members.items() if h != src_host_id]
        for other in others:
            other._on_retract(src_host_id, task_id)


class PeerExchange:
    def __init__(self, meta: MemberMeta, bus: GossipBus) -> None:
        self.meta = meta
        self.bus = bus
        self._mu = threading.Lock()
        self._members: Dict[str, MemberMeta] = {}
        # task_id → host_id → piece set (peer_pool.go)
        self._pool: Dict[str, Dict[str, Set[int]]] = {}
        self._local: Dict[str, Set[int]] = {}

    # -- lifecycle -----------------------------------------------------------

    def serve(self) -> None:
        self.bus.join(self)

    def stop(self) -> None:
        self.bus.leave(self.meta.host_id)

    # -- local advertisement -------------------------------------------------

    def advertise(self, task_id: str, pieces: Set[int]) -> None:
        with self._mu:
            self._local.setdefault(task_id, set()).update(pieces)
            snapshot = set(self._local[task_id])
        self.bus.broadcast_advertise(self.meta.host_id, task_id, snapshot)

    def retract(self, task_id: str) -> None:
        """Local data evicted (quota reclaim / delete): withdraw the
        advertisement so peers stop routing piece fetches here."""
        with self._mu:
            self._local.pop(task_id, None)
        self.bus.broadcast_retract(self.meta.host_id, task_id)

    def local_holdings(self) -> List[tuple]:
        with self._mu:
            return [(t, set(p)) for t, p in self._local.items()]

    # -- queries -------------------------------------------------------------

    def members(self) -> List[MemberMeta]:
        with self._mu:
            return list(self._members.values())

    def member(self, host_id: str) -> "MemberMeta | None":
        with self._mu:
            return self._members.get(host_id)

    def pool_snapshot(self) -> List[tuple]:
        """[(host_id, task_id, pieces)] — the full advertisement pool (the
        anti-entropy sync payload)."""
        with self._mu:
            return [
                (h, t, set(p))
                for t, by_host in self._pool.items()
                for h, p in by_host.items()
            ]

    def find_peers_with_task(self, task_id: str) -> List[str]:
        with self._mu:
            return list(self._pool.get(task_id, {}))

    def find_peers_with_piece(self, task_id: str, number: int) -> List[str]:
        with self._mu:
            return [
                h for h, pieces in self._pool.get(task_id, {}).items() if number in pieces
            ]

    # -- bus callbacks -------------------------------------------------------

    def _on_join(self, meta: MemberMeta) -> None:
        with self._mu:
            self._members[meta.host_id] = meta

    def _on_leave(self, host_id: str) -> None:
        """Member left: drop it and reclaim its advertisements
        (peer_exchange reclaim-on-leave)."""
        with self._mu:
            self._members.pop(host_id, None)
            for task_pool in self._pool.values():
                task_pool.pop(host_id, None)

    def _on_advertise(self, host_id: str, task_id: str, pieces: Set[int]) -> None:
        with self._mu:
            self._pool.setdefault(task_id, {}).setdefault(host_id, set()).update(pieces)

    def _on_retract(self, host_id: str, task_id: str) -> None:
        with self._mu:
            pool = self._pool.get(task_id)
            if pool is not None:
                pool.pop(host_id, None)
