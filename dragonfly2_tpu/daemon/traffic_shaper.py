"""Per-task bandwidth allocation (reference: client/daemon/peer/traffic_shaper.go:36-133).

The reference's "sampling" shaper re-divides total bandwidth across active
tasks each second, proportional to observed need.  Same model: tasks
register, record consumed bytes, and ``allocate`` computes each task's
budget for the next window — used bandwidth attracts budget, idle tasks
shrink to a floor.
"""

from __future__ import annotations

import threading
from typing import Dict


class TrafficShaper:
    def __init__(self, total_rate: float, *, min_share: float = 0.05) -> None:
        """total_rate: bytes/sec across all tasks."""
        self.total_rate = total_rate
        self.min_share = min_share
        self._mu = threading.Lock()
        self._used: Dict[str, int] = {}
        self._budget: Dict[str, float] = {}

    def add_task(self, task_id: str) -> None:
        with self._mu:
            self._used.setdefault(task_id, 0)
            n = len(self._used)
            for t in self._used:
                self._budget[t] = self.total_rate / n

    def remove_task(self, task_id: str) -> None:
        with self._mu:
            self._used.pop(task_id, None)
            self._budget.pop(task_id, None)

    def record(self, task_id: str, nbytes: int) -> None:
        with self._mu:
            if task_id in self._used:
                self._used[task_id] += nbytes

    def budget(self, task_id: str) -> float:
        with self._mu:
            return self._budget.get(task_id, 0.0)

    def allocate(self) -> Dict[str, float]:
        """Close the sampling window: re-divide rate proportional to use."""
        with self._mu:
            n = len(self._used)
            if n == 0:
                return {}
            total_used = sum(self._used.values())
            # Clamp the floor so n·floor never exceeds the total rate — with
            # many tasks an unclamped floor turns `distributable` negative
            # and inverts the allocation (busiest task gets least).
            floor = min(self.total_rate * self.min_share, self.total_rate / n)
            if total_used == 0:
                for t in self._used:
                    self._budget[t] = self.total_rate / n
            else:
                distributable = self.total_rate - floor * n
                for t, used in self._used.items():
                    self._budget[t] = floor + distributable * (used / total_used)
            for t in self._used:
                self._used[t] = 0
            return dict(self._budget)
