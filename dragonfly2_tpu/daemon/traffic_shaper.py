"""Per-task bandwidth allocation (reference: client/daemon/peer/traffic_shaper.go:36-133).

The reference's "sampling" shaper re-divides total bandwidth across active
tasks each second, proportional to observed need.  Same model: tasks
register, record consumed bytes, and ``allocate`` computes each task's
budget for the next window — used bandwidth attracts budget, idle tasks
shrink to a floor.

Multi-tenant hierarchy (DESIGN.md §26): with a ``QoSPolicy`` installed,
allocation is two-level — the total rate splits across TENANTS by
declared weight (clipped at each tenant's ``upload_rate_bytes_s`` cap,
the clipped remainder redistributed to uncapped tenants), then each
tenant's share splits across its tasks proportional to observed use,
exactly the single-level discipline.  With one tenant (or no policy)
the tenant split degenerates to the whole rate and behavior is
unchanged.

``add_task`` carves the min-share floor out of the EXISTING allocation
instead of resetting everyone to an equal split: a hot task's
history-weighted budget survives a cold task joining (it scales by
``(rate − floor) / rate`` until the next ``allocate`` window closes,
rather than collapsing to ``rate / n``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

import threading

if TYPE_CHECKING:  # policy is duck-typed at runtime (no qos import cost)
    from ..qos.policy import QoSPolicy

DEFAULT_TENANT = "default"


class TrafficShaper:
    def __init__(self, total_rate: float, *, min_share: float = 0.05) -> None:
        """total_rate: bytes/sec across all tasks."""
        self.total_rate = total_rate
        self.min_share = min_share
        self._mu = threading.Lock()
        self._used: Dict[str, int] = {}
        self._budget: Dict[str, float] = {}
        self._tenant_of: Dict[str, str] = {}
        self._policy: "Optional[QoSPolicy]" = None
        # True once allocate() has run over OBSERVED use: only then are
        # budgets history-weighted and worth preserving across joins.
        self._history = False

    def set_policy(self, policy: "Optional[QoSPolicy]") -> None:
        """Install/clear the tenant QoS policy (weights + upload caps);
        takes effect at the next ``allocate`` window close."""
        with self._mu:
            self._policy = policy

    def add_task(self, task_id: str, tenant: str = DEFAULT_TENANT) -> None:
        with self._mu:
            if task_id in self._used:
                self._tenant_of[task_id] = tenant or DEFAULT_TENANT
                return
            self._used[task_id] = 0
            self._tenant_of[task_id] = tenant or DEFAULT_TENANT
            n = len(self._used)
            floor = min(self.total_rate * self.min_share, self.total_rate / n)
            existing_total = sum(
                b for t, b in self._budget.items() if t in self._used
            )
            if not self._history or existing_total <= 0.0:
                # No observed-use allocation yet: an equal split is all
                # the information there is (the pre-history behavior).
                for t in self._used:
                    self._budget[t] = self.total_rate / n
                return
            # Carve the joiner's floor out proportionally: every
            # existing budget scales by (rate − floor)/rate, so the
            # history-weighted proportions ``allocate`` computed survive
            # the join instead of resetting to an equal split.
            scale = max(0.0, (self.total_rate - floor)) / self.total_rate
            for t in self._used:
                if t != task_id:
                    self._budget[t] = self._budget.get(
                        t, self.total_rate / n
                    ) * scale
            self._budget[task_id] = floor

    def remove_task(self, task_id: str) -> None:
        with self._mu:
            self._used.pop(task_id, None)
            self._budget.pop(task_id, None)
            self._tenant_of.pop(task_id, None)

    def record(self, task_id: str, nbytes: int) -> None:
        with self._mu:
            if task_id in self._used:
                self._used[task_id] += nbytes

    def budget(self, task_id: str) -> float:
        with self._mu:
            return self._budget.get(task_id, 0.0)

    # -- window close --------------------------------------------------------

    def _tenant_rates_locked(self) -> Dict[str, float]:
        """Per-tenant rate split for the active tenant set: weight-
        proportional, clipped at each tenant's declared upload cap, the
        clipped surplus redistributed across UNCAPPED tenants by weight
        (one redistribution round; a fully-capped fleet leaves the
        surplus unallocated — caps are caps)."""
        tenants = sorted({self._tenant_of[t] for t in self._used})
        policy = self._policy
        if policy is None or len(tenants) <= 1:
            return {t: self.total_rate for t in tenants} or {}
        weights = {t: max(policy.weight_of(t), 1e-9) for t in tenants}
        wsum = sum(weights.values())
        caps = {
            t: policy.for_tenant(t).upload_rate_bytes_s or float("inf")
            for t in tenants
        }
        shares = {t: self.total_rate * weights[t] / wsum for t in tenants}
        rates = {t: min(shares[t], caps[t]) for t in tenants}
        surplus = self.total_rate - sum(rates.values())
        open_w = sum(weights[t] for t in tenants if rates[t] < caps[t])
        if surplus > 1e-9 and open_w > 0:
            for t in tenants:
                if rates[t] < caps[t]:
                    rates[t] = min(
                        caps[t], rates[t] + surplus * weights[t] / open_w
                    )
        return rates

    def allocate(self) -> Dict[str, float]:
        """Close the sampling window: tenant split by weight (see
        ``_tenant_rates_locked``), then use-proportional task budgets
        inside each tenant's share."""
        with self._mu:
            if not self._used:
                return {}
            if any(self._used.values()):
                self._history = True
            rates = self._tenant_rates_locked()
            by_tenant: Dict[str, list] = {}
            for t in self._used:
                by_tenant.setdefault(self._tenant_of[t], []).append(t)
            for tenant, tasks in by_tenant.items():
                rate = rates.get(tenant, self.total_rate)
                n = len(tasks)
                total_used = sum(self._used[t] for t in tasks)
                # Clamp the floor so n·floor never exceeds the tenant
                # rate — with many tasks an unclamped floor turns
                # `distributable` negative and inverts the allocation
                # (busiest task gets least).
                floor = min(rate * self.min_share, rate / n)
                if total_used == 0:
                    for t in tasks:
                        self._budget[t] = rate / n
                else:
                    distributable = rate - floor * n
                    for t in tasks:
                        self._budget[t] = floor + distributable * (
                            self._used[t] / total_used
                        )
            for t in self._used:
                self._used[t] = 0
            return dict(self._budget)
