"""Parent-selection engine (reference: scheduler/scheduling/scheduling.go).

Semantics preserved:
- retry loop with back-to-source escalation (scheduling.go:85-215):
  peers needing back-to-source (flag set, or candidate search failed
  ``retry_back_to_source_limit`` times while the task still has
  back-to-source budget) get a NeedBackToSource response; past
  ``retry_limit`` total scheduling fails hard.
- filter pipeline (scheduling.go:500-573 filterCandidateParents): sample
  ``filter_parent_limit`` random peers from the task DAG, drop blocklisted,
  same-host, orphaned normal peers (in-degree 0, not back-to-source /
  succeeded / seed), bad nodes, full upload slots, and cycle-creating edges.
- evaluator ranks the survivors; top ``candidate_parent_limit`` become
  parents (scheduling.go:384 FindCandidateParents) and edges are added to
  the task DAG.
- defaults: filter 15 / candidate 4, retry 5, back-to-source retry 4,
  interval 500 ms (scheduler/config/constants.go:33-37, :66-73).

Transport-neutral: responses are returned as plain result objects rather
than written to a gRPC stream, so the engine runs identically under the
in-process swarm simulator, the unit tests, and the native RPC server.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum, auto
from typing import Callable, List, Optional, Set

from ..utils.dag import DAGError
from ..utils.types import HostType
from .evaluator import Evaluator
from .resource import PEER_BACK_TO_SOURCE, PEER_SUCCEEDED, Peer


@dataclass
class SchedulingConfig:
    """scheduler/config/config.go SchedulerConfig (:121-142) + cluster limits."""

    candidate_parent_limit: int = 4
    filter_parent_limit: int = 15
    retry_limit: int = 5
    retry_back_to_source_limit: int = 4
    retry_interval: float = 0.5  # seconds


class ScheduleResultKind(Enum):
    PARENTS = auto()           # NormalTaskResponse: candidate parents attached
    NEED_BACK_TO_SOURCE = auto()
    FAILED = auto()            # exceeded retry limit


@dataclass
class ScheduleResult:
    kind: ScheduleResultKind
    parents: List[Peer] = field(default_factory=list)
    description: str = ""
    retries: int = 0


class Scheduling:
    """The engine (scheduling.go Scheduling iface :43-62)."""

    def __init__(
        self,
        evaluator: Evaluator,
        config: Optional[SchedulingConfig] = None,
        *,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.evaluator = evaluator
        self.config = config or SchedulingConfig()
        self._sleep = sleep

    # -- candidate search ---------------------------------------------------

    def filter_candidate_parents(
        self, peer: Peer, blocklist: Optional[Set[str]] = None
    ) -> List[Peer]:
        blocklist = blocklist or set()
        prelim: List[Peer] = []
        for cand in peer.task.load_random_peers(self.config.filter_parent_limit):
            if cand.id in blocklist or cand.id in peer.block_parents:
                continue
            # Two daemons downloading from each other deadlocks piece sync.
            if cand.host.id == peer.host.id:
                continue
            try:
                in_degree = peer.task.peer_in_degree(cand.id)
            except DAGError:
                # Candidate reaped by GC between sampling and inspection —
                # skip it, like the reference's InDegree error branch
                # (scheduling.go:526-530).
                continue
            # A normal peer with no parent that isn't fetching from source
            # and hasn't finished has nothing to serve.
            if (
                cand.host.type is HostType.NORMAL
                and in_degree == 0
                and cand.fsm.current not in (PEER_BACK_TO_SOURCE, PEER_SUCCEEDED)
            ):
                continue
            prelim.append(cand)
        if not prelim:
            return []
        # One vectorized bad-node pass over the survivors (the cost
        # statistics dominate this filter); every check is per-candidate
        # independent, so batching it after the cheap screens keeps the
        # accepted set identical to the reference's one-at-a-time order.
        bad = self.evaluator.is_bad_nodes(prelim)
        candidates: List[Peer] = []
        for cand, cand_bad in zip(prelim, bad):
            if cand_bad:
                continue
            if cand.host.free_upload_count() <= 0:
                continue
            if not peer.task.can_add_peer_edge(cand.id, peer.id):
                continue
            candidates.append(cand)
        return candidates

    def find_candidate_parents(
        self, peer: Peer, blocklist: Optional[Set[str]] = None
    ) -> List[Peer]:
        """Filter + rank + cap (scheduling.go:384-446)."""
        candidates = self.filter_candidate_parents(peer, blocklist)
        if not candidates:
            return []
        ranked = self.evaluator.evaluate_parents(
            candidates, peer, max(peer.task.total_piece_count, 0)
        )
        return ranked[: self.config.candidate_parent_limit]

    def find_success_parent(
        self, peer: Peer, blocklist: Optional[Set[str]] = None
    ) -> Optional[Peer]:
        """Succeeded parents only (piece metadata source, scheduling.go:448-498)."""
        candidates = [
            c
            for c in self.filter_candidate_parents(peer, blocklist)
            if c.fsm.current == PEER_SUCCEEDED
        ]
        if not candidates:
            return None
        ranked = self.evaluator.evaluate_parents(
            candidates, peer, max(peer.task.total_piece_count, 0)
        )
        return ranked[0]

    # -- the scheduling loop ------------------------------------------------

    def schedule_once(
        self, peer: Peer, blocklist: Optional[Set[str]] = None
    ) -> ScheduleResult:
        """Single-shot reschedule for server-push paths: no retry loop, no
        sleeping (pushes run on stream handler / stall-monitor threads),
        and — unlike the retry loop — the peer's CURRENT edges are only
        detached once replacement candidates exist, so a failed attempt
        leaves the child's real assignment untouched.
        """
        parents = self.find_candidate_parents(peer, blocklist)
        if not parents:
            return ScheduleResult(
                kind=ScheduleResultKind.FAILED,
                description="no candidates (single-shot)",
            )
        # Attach-first: candidates never include current parents (the
        # filter's can_add_peer_edge rejects existing edges), so the new
        # edges land alongside the old ones, and only once at least one
        # replacement holds do the previous parents detach.  Losing every
        # upload-slot race therefore leaves the child's real assignment
        # untouched — the failure mode ADVICE r2 found (detach-first left
        # the child edgeless and invisible to reschedule_stalled).
        try:
            old_parents = peer.task.load_parents(peer.id)
        except DAGError:
            # The child left between candidate search and here (its vertex
            # is gone); attachments below will lose too and report FAILED —
            # raising would convert an unrelated peer's piece report into
            # an RPC error on the push path (service.py bad-parent sweep).
            old_parents = []
        attached = [p for p in parents if peer.task.add_peer_edge(p, peer)]
        if not attached:
            return ScheduleResult(
                kind=ScheduleResultKind.FAILED,
                description="upload-slot races lost (single-shot)",
            )
        for old in old_parents:
            peer.task.delete_peer_edge(old, peer.id)
        return ScheduleResult(kind=ScheduleResultKind.PARENTS, parents=attached)

    def schedule_candidate_parents(
        self, peer: Peer, blocklist: Optional[Set[str]] = None
    ) -> ScheduleResult:
        """v2 loop (scheduling.go:85-215)."""
        n = 0
        while True:
            if peer.task.can_back_to_source():
                if peer.need_back_to_source:
                    return ScheduleResult(
                        kind=ScheduleResultKind.NEED_BACK_TO_SOURCE,
                        description="peer needs back-to-source",
                        retries=n,
                    )
                if n >= self.config.retry_back_to_source_limit:
                    return ScheduleResult(
                        kind=ScheduleResultKind.NEED_BACK_TO_SOURCE,
                        description="scheduling exceeded RetryBackToSourceLimit",
                        retries=n,
                    )
            if n >= self.config.retry_limit:
                return ScheduleResult(
                    kind=ScheduleResultKind.FAILED,
                    description="scheduling exceeded RetryLimit",
                    retries=n,
                )

            # Reschedule from a clean slate: detach current parents.
            peer.task.delete_peer_in_edges(peer.id)

            parents = self.find_candidate_parents(peer, blocklist)
            if not parents:
                n += 1
                self._sleep(self.config.retry_interval)
                continue

            attached = []
            for parent in parents:
                if peer.task.add_peer_edge(parent, peer):
                    attached.append(parent)
            if not attached:
                # Every edge-add lost its upload-slot race — treat like a
                # found-nothing round so the peer keeps progressing toward
                # back-to-source instead of stalling with zero parents.
                n += 1
                self._sleep(self.config.retry_interval)
                continue
            return ScheduleResult(
                kind=ScheduleResultKind.PARENTS, parents=attached, retries=n
            )
