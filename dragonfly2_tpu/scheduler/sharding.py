"""Sharded scheduler fleet (DESIGN.md §24).

The serving path is columnar and lock-free per instance (§18) but a
single scheduler still walls at one process.  The reference runs
scheduler *clusters* with manager-driven dynconfig assignment
(scheduler_cluster records; pkg/balancer's consistent-hash picker) —
this module is the horizontal story on top of it:

- ``ShardRing`` — consistent-hash ring over scheduler instances (virtual
  nodes, **deterministic** sha-based hashing so every process computes
  the same ownership — ``hash()`` randomization would split the fleet's
  brain), with a bounded-load ``pick`` (Mirrokni et al.: walk successors
  past members above ``load_factor × mean`` so one hot shard spills to
  its ring neighbors instead of melting).
- ``ShardDirectory`` — the manager-side durable membership record: the
  ACTIVE scheduler set, versioned, persisted through the (replicated)
  StateBackend namespace ``shard_membership`` (DF014-checked: writes
  under ``_mu``, recovery loader in the constructor).  A membership
  change bumps ``version``; the manager publishes the ring payload with
  the cluster dynconfig, so every client converges on the same ring.
- ``ShardGuard`` — scheduler-side ownership enforcement: task-scoped
  calls for tasks this shard does not own answer a REDIRECT-style
  steering error (``WrongShardError`` carries the owner and ring
  version); a ring-version bump triggers ``handoff()`` — the affected
  tasks are marked, their peers steered to the new owner on their next
  call, the move recorded under the ``scheduler/shard.handoff`` span
  (DF016-inventoried; the chaos drill renders it on the critical path).
- ``AdmissionController`` — per-shard load shedding fed by the §23
  sketch signals (windowed announce p99 vs budget + in-flight cap):
  lowest-priority work sheds first, refusals carry Retry-After like
  §20's standby 503 discipline.

Lock ordering: ``ShardGuard._mu`` and ``AdmissionController._mu`` are
leaf locks (no calls out while held); ``ShardDirectory._mu`` guards its
table writes only.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

if TYPE_CHECKING:  # lock-graph resolver type (§16): _table nests under _mu
    from ..manager.state import StateBackend

from ..utils import faultinject
from ..utils.metrics import Sketch
from ..utils.tracing import default_tracer
from ..utils.types import Priority
from . import metrics

DEFAULT_REPLICAS = 100  # virtual nodes per shard
DEFAULT_LOAD_FACTOR = 1.25  # bounded-load spill threshold (× mean load)


def shard_hash(key: str) -> int:
    """Deterministic 64-bit ring position.  sha1 (not ``hash()``): the
    daemon, every shard, and the manager must all place a task id at the
    SAME point of the ring across processes and interpreter restarts —
    PYTHONHASHSEED randomization would shear routing from ownership."""
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class ShardRing:
    """Consistent-hash ring over ``{shard_id: url}`` members.

    ``owner`` is the plain consistent-hash successor (the minimal-
    movement mapping the property tests pin); ``pick`` adds the
    bounded-load walk.  Instances are cheap value objects — routers and
    guards swap in a freshly built ring on every version bump rather
    than mutating a shared one under readers.
    """

    def __init__(
        self,
        members: Optional[Dict[str, str]] = None,
        *,
        replicas: int = DEFAULT_REPLICAS,
        version: int = 0,
    ) -> None:
        self.replicas = replicas
        self.version = version
        self._members: Dict[str, str] = {}
        self._ring: List[int] = []
        self._owners: Dict[int, str] = {}
        for sid, url in (members or {}).items():
            self.add(sid, url)

    # -- membership ----------------------------------------------------------

    def add(self, shard_id: str, url: str = "") -> None:
        if shard_id in self._members:
            self._members[shard_id] = url or self._members[shard_id]
            return
        self._members[shard_id] = url
        for i in range(self.replicas):
            h = shard_hash(f"{shard_id}#{i}")
            bisect.insort(self._ring, h)
            self._owners[h] = shard_id

    def remove(self, shard_id: str) -> None:
        if shard_id not in self._members:
            return
        del self._members[shard_id]
        for i in range(self.replicas):
            h = shard_hash(f"{shard_id}#{i}")
            idx = bisect.bisect_left(self._ring, h)
            if idx < len(self._ring) and self._ring[idx] == h:
                self._ring.pop(idx)
            self._owners.pop(h, None)

    def members(self) -> Dict[str, str]:
        return dict(self._members)

    def url_of(self, shard_id: str) -> Optional[str]:
        return self._members.get(shard_id)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, shard_id: str) -> bool:
        return shard_id in self._members

    # -- placement -----------------------------------------------------------

    def _successors(self, key: str) -> Iterable[str]:
        """Distinct members in ring order starting at the key's point."""
        if not self._ring:
            return
        start = bisect.bisect_right(self._ring, shard_hash(key))
        seen: set = set()
        n = len(self._ring)
        for off in range(n):
            sid = self._owners[self._ring[(start + off) % n]]
            if sid not in seen:
                seen.add(sid)
                yield sid

    def owner(self, key: str) -> Optional[str]:
        """The plain consistent-hash owner (None on an empty ring)."""
        for sid in self._successors(key):
            return sid
        return None

    def pick(
        self,
        key: str,
        *,
        load_of: Optional[Callable[[str], float]] = None,
        load_factor: float = DEFAULT_LOAD_FACTOR,
    ) -> Optional[str]:
        """Bounded-load placement: the owner unless it is above
        ``load_factor × mean`` of the fleet, in which case the walk
        spills to the first ring successor under the bound (falling back
        to the owner when everyone is hot — shedding, not routing, is
        the overload answer then)."""
        if load_of is None or len(self._members) <= 1:
            return self.owner(key)
        loads = {sid: max(0.0, float(load_of(sid))) for sid in self._members}
        bound = load_factor * (sum(loads.values()) / len(loads)) if loads else 0.0
        first = None
        for sid in self._successors(key):
            if first is None:
                first = sid
            if bound <= 0.0 or loads.get(sid, 0.0) <= bound:
                return sid
        return first

    # -- wire form (dynconfig payload) ---------------------------------------

    def to_payload(self) -> Dict[str, object]:
        return {
            "version": self.version,
            "replicas": self.replicas,
            "members": [
                {"id": sid, "url": url}
                for sid, url in sorted(self._members.items())
            ],
        }

    @classmethod
    def from_payload(cls, payload: Dict[str, object]) -> "ShardRing":
        members = {
            str(m["id"]): str(m.get("url", ""))
            for m in payload.get("members", [])
            if isinstance(m, dict) and m.get("id")
        }
        return cls(
            members,
            replicas=int(payload.get("replicas", DEFAULT_REPLICAS)),
            version=int(payload.get("version", 0)),
        )


class ShardDirectory:
    """Durable, versioned shard membership (manager side).

    The ACTIVE scheduler instances of a cluster form the ring; a set
    change (register, keepalive expiry, deregister) bumps the version
    and persists ``{version, members}`` through the StateBackend — on
    the replicated backend (§20) the row survives a leader bounce, so a
    promoted standby publishes the SAME ring version instead of
    restarting the fleet's ownership from zero.
    """

    NAMESPACE = "shard_membership"

    def __init__(
        self, backend: "StateBackend", *, replicas: int = DEFAULT_REPLICAS
    ) -> None:
        self._mu = threading.Lock()
        self.replicas = replicas
        self._table = backend.table("shard_membership")
        # Recovery loader (DF014): the persisted ring row is the boot
        # state; version continuity across restarts is what keeps the
        # fleet from re-handing-off every task on a manager bounce.
        self._rows: Dict[str, dict] = self._table.load_all()

    def _row(self, cluster_id: str) -> dict:
        return self._rows.get(cluster_id) or {"version": 0, "members": {}}

    def publish(
        self, cluster_id: str, active: Sequence[Tuple[str, str]]
    ) -> Dict[str, object]:
        """Reconcile the ACTIVE member set against the persisted row and
        return the ring payload for the cluster dynconfig.  Bumps +
        persists the version only when membership actually changed."""
        incoming = {sid: url for sid, url in active}
        with self._mu:
            row = self._row(cluster_id)
            if incoming != row["members"]:
                row = {
                    "version": int(row["version"]) + 1,
                    "members": incoming,
                }
                self._rows[cluster_id] = row
                self._table.put(cluster_id, row)
                metrics.SHARD_RING_VERSION.set(
                    row["version"], cluster=cluster_id
                )
            return {
                "version": row["version"],
                "replicas": self.replicas,
                "members": [
                    {"id": sid, "url": url}
                    for sid, url in sorted(row["members"].items())
                ],
            }

    def version(self, cluster_id: str) -> int:
        with self._mu:
            return int(self._row(cluster_id)["version"])


def handoff_span(
    task_id: str, *, from_shard: str = "", to_shard: str = "",
    ring_version: int = 0,
):
    """Client-side half of the cross-shard migration edge: wraps a
    task's re-announce/re-register on its new owner, so the flight
    recorder renders the handoff on the download's critical path (the
    guard's membership sweep opens the same span server-side)."""
    return default_tracer.span(
        "scheduler/shard.handoff",
        task_id=task_id,
        from_shard=from_shard,
        to_shard=to_shard,
        ring_version=ring_version,
    )


# -- steering / shedding wire errors -----------------------------------------


class WrongShardError(Exception):
    """REDIRECT-style steering answer: the task's swarm lives (or now
    lives) on another shard.  Carried over the wire as HTTP 421 with the
    owner's address so the client re-announces there instead of burning
    retries against a non-owner."""

    def __init__(
        self, task_id: str, *, owner_id: str = "", owner_url: str = "",
        ring_version: int = 0,
    ) -> None:
        super().__init__(
            f"task {task_id} is owned by shard {owner_id or '?'} "
            f"(ring v{ring_version})"
        )
        self.task_id = task_id
        self.owner_id = owner_id
        self.owner_url = owner_url
        self.ring_version = ring_version


class ShardSaturatedError(Exception):
    """Admission refusal: this shard is past its load bound and the
    request's priority class is in the shed band.  Carried over the wire
    as HTTP 503 + Retry-After (the §20 standby discipline): the client
    backs off instead of hammering a melting shard."""

    def __init__(self, *, retry_after_s: float = 1.0, reason: str = "") -> None:
        super().__init__(reason or "shard saturated")
        self.retry_after_s = retry_after_s
        self.reason = reason or "shard saturated"


class AdmissionController:
    """Per-shard admission control + load shedding (§23 burn signals).

    Two saturation signals, both cheap enough for the announce path:

    - **in-flight bound** — concurrent admitted requests vs ``max_inflight``
      (the queue-depth proxy; rises instantly when arrival outruns
      service);
    - **latency burn** — the windowed announce p99 from a private §23
      mergeable sketch vs ``p99_budget_s`` (the SLO-shaped signal: burn
      ``= p99 / budget``; >1 means the latency budget is being eaten).

    Shedding is priority-banded, lowest class first: overload fraction
    ``f`` in (0, 1] sheds priorities ``>= ceil((1 - f) * LEVEL6)`` — at
    f=0.15 only LEVEL6 background work sheds; at f=1 everything but
    LEVEL0 does.  LEVEL0 (interactive) is never shed by the band (it
    only fails when the in-flight bound is exceeded at 2× — the hard
    wall protecting the process itself).

    Tenant QoS (DESIGN.md §26), with a ``TenantAccounting`` attached:

    - every request is accounted per tenant; a tenant past its declared
      ``announce_qps`` cap (possibly autopilot-tightened) is refused
      outright;
    - a tenant's declared priority class FLOORS its requests' priority
      (a "background" tenant cannot claim LEVEL0);
    - under overload the shed floor scales by the tenant's
      ``noise_factor`` — the over-quota tenant's lowest bands shed
      FIRST, a within-quota tenant keeps its bands until overload
      deepens;
    - the SLO autopilot's ``shed_bias`` adds straight into the overload
      fraction, tightening the floor fleet-wide while a declared SLO
      burns (qos/autopilot.py).
    """

    def __init__(
        self,
        *,
        max_inflight: int = 512,
        p99_budget_s: float = 0.050,
        window_s: float = 5.0,
        retry_after_s: float = 1.0,
        accounting=None,
    ) -> None:
        self._mu = threading.Lock()
        self.max_inflight = max_inflight
        self.p99_budget_s = p99_budget_s
        self.window_s = window_s
        self.retry_after_s = retry_after_s
        # qos.accounting.TenantAccounting — the ONE object behind the
        # announce path's per-tenant costs; None = tenant-blind admission
        # (the pre-§26 behavior).
        self.accounting = accounting
        # Autopilot output: added into overload() while a declared SLO
        # burns; 0.0 on the steady state.
        self._shed_bias = 0.0
        self._inflight = 0
        # Private sketches (NOT the registry-global ANNOUNCE_SECONDS):
        # with N in-process shards (sim/bench) the default registry is
        # shared, and a per-shard shed decision fed by fleet-wide
        # latency would shed the wrong shard.  Two-epoch rotation makes
        # the cumulative sketch a WINDOWED signal — a recovered shard
        # sheds from its current epoch, not last hour's burst.  The
        # unregistered construction is deliberate: epochs are created
        # and dropped per window, never exposed as a registry series.
        self._cur = Sketch(  # dflint: disable=DF017 — private epoch
            "scheduler_shard_admission_seconds", ""
        )
        self._prev: Optional[Sketch] = None
        self._epoch_started = time.monotonic()

    # -- signal --------------------------------------------------------------

    def observe(self, seconds: float) -> None:
        now = time.monotonic()
        with self._mu:
            if now - self._epoch_started >= self.window_s:
                self._prev = self._cur
                self._cur = Sketch(  # dflint: disable=DF017 — private epoch
                    "scheduler_shard_admission_seconds", ""
                )
                self._epoch_started = now
            cur = self._cur
        cur.observe(seconds)

    def inflight(self) -> int:
        with self._mu:
            return self._inflight

    def _windowed_p99(self) -> Optional[float]:
        with self._mu:
            cur, prev = self._cur, self._prev
        p99 = cur.quantile(0.99)
        if p99 is None and prev is not None:
            p99 = prev.quantile(0.99)
        return p99

    def set_shed_bias(self, bias: float) -> None:
        """Autopilot input: raises the effective overload fraction (the
        shed floor tightens) while a declared SLO burns; 0 restores the
        measured signals alone."""
        with self._mu:
            self._shed_bias = max(0.0, min(1.0, float(bias)))

    def shed_bias(self) -> float:
        with self._mu:
            return self._shed_bias

    def overload(self) -> float:
        """Saturation fraction in [0, 1]: max of the two burn signals
        plus the autopilot's shed bias, 0 while inside budget with no
        SLO burning."""
        with self._mu:
            inflight = self._inflight
            bias = self._shed_bias
        q_burn = inflight / self.max_inflight if self.max_inflight else 0.0
        p99 = self._windowed_p99()
        l_burn = (p99 / self.p99_budget_s) if p99 else 0.0
        # Inside-budget readings are 0 overload; past budget the excess
        # maps linearly into (0, 1] (2× budget == fully overloaded).
        # The autopilot's bias ADDS to the normalized fraction — a
        # burning fleet SLO tightens the floor even while this shard's
        # own signals read healthy (the declared SLO may measure an
        # end-to-end latency the admission sketch cannot see).
        base = max(0.0, min(1.0, max(q_burn, l_burn) - 1.0))
        return min(1.0, base + bias)

    # -- decision ------------------------------------------------------------

    def admit(
        self, priority: Priority = Priority.LEVEL0, *, tenant: str = ""
    ) -> None:
        """Raise ``ShardSaturatedError`` when this request's priority
        class is in the current shed band (lowest classes first; the
        over-quota tenant's bands first among tenants)."""
        accounting = self.accounting
        noise = 1.0
        if accounting is not None:
            qos = accounting.policy.for_tenant(tenant)
            # The tenant's declared class floors the request's priority:
            # a background tenant cannot claim LEVEL0 interactivity.
            priority = Priority(max(int(priority), int(qos.priority)))
            if not accounting.note(tenant):
                # Announce-rate cap (declared, or autopilot-tightened
                # for over-quota tenants): refused outright, before any
                # per-request work — the whole point of the cap.
                faultinject.fire("scheduler.qos.shed")
                from ..qos.metrics import QOS_RATE_CAPPED_TOTAL

                accounting.record_shed(tenant)
                QOS_RATE_CAPPED_TOTAL.inc(
                    tenant_class=accounting.class_of(tenant)
                )
                raise ShardSaturatedError(
                    retry_after_s=self.retry_after_s,
                    reason="tenant announce-rate cap",
                )
            noise = accounting.noise_factor(tenant)
        over = self.overload()
        with self._mu:
            hard_wall = self._inflight >= 2 * self.max_inflight
        if hard_wall:
            metrics.SHARD_SHED_TOTAL.inc(priority=f"level{int(priority)}")
            raise ShardSaturatedError(
                retry_after_s=self.retry_after_s,
                reason=f"in-flight {self._inflight} >= 2x bound",
            )
        if over <= 0.0 or priority is Priority.LEVEL0:
            return
        # The noisy tenant's floor drops fastest: at the same overload a
        # 3×-over-quota tenant sheds bands three times deeper than a
        # within-quota one (noise ∈ [1, 3], qos/accounting.py).
        shed_floor = (1.0 - min(1.0, over * noise)) * int(Priority.LEVEL6)
        if int(priority) >= shed_floor:
            faultinject.fire("scheduler.qos.shed")
            metrics.SHARD_SHED_TOTAL.inc(priority=f"level{int(priority)}")
            if accounting is not None:
                from ..qos.metrics import QOS_SHED_TOTAL

                accounting.record_shed(tenant)
                QOS_SHED_TOTAL.inc(
                    tenant_class=accounting.class_of(tenant),
                    priority=f"level{int(priority)}",
                )
            raise ShardSaturatedError(
                retry_after_s=self.retry_after_s * (1.0 + over),
                reason=(
                    f"overload {over:.2f}: shedding priority >= "
                    f"{shed_floor:.1f}"
                ),
            )

    def track(self):
        """Context manager for an admitted request: in-flight accounting
        + latency observation into the shed signal."""
        return _AdmissionTrack(self)


class _AdmissionTrack:
    def __init__(self, ctl: AdmissionController) -> None:
        self._ctl = ctl
        self._t0 = 0.0

    def __enter__(self) -> "_AdmissionTrack":
        with self._ctl._mu:
            self._ctl._inflight += 1
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._ctl.observe(time.monotonic() - self._t0)
        with self._ctl._mu:
            self._ctl._inflight -= 1


class ShardGuard:
    """Scheduler-side shard ownership: ring adoption, REDIRECT steering,
    and the membership-change handoff sweep.

    Attached to a ``SchedulerService`` (``service.shard_guard``); the
    service consults it at the task-scoped entry points.  Ring updates
    arrive through ``on_config`` (a dynconfig observer — the manager
    publishes the ring with the cluster config) or ``update_ring``
    (in-process fleets).
    """

    def __init__(
        self,
        shard_id: str,
        *,
        admission: Optional[AdmissionController] = None,
    ) -> None:
        self.shard_id = shard_id
        self.admission = admission
        self._mu = threading.Lock()
        self._ring: Optional[ShardRing] = None
        # Tasks this shard owned before a ring bump moved them: their
        # peers get steered (REDIRECT) on their next call instead of
        # silently double-serving a split-brain swarm.
        self._handed_off: Dict[str, str] = {}  # task_id -> new owner id
        # resource is attached by the service so handoff() can sweep the
        # live task table without a circular constructor.
        self.resource = None

    # -- ring adoption -------------------------------------------------------

    def on_config(self, config: Dict[str, object]) -> None:
        """Dynconfig observer: adopt ``scheduler_ring`` payloads.  Skips
        malformed/stale payloads (an observer exception would take down
        the dynconfig refresh for every other observer)."""
        payload = config.get("scheduler_ring")
        if not isinstance(payload, dict) or not payload.get("members"):
            return
        try:
            self.update_ring(ShardRing.from_payload(payload))
        except (KeyError, TypeError, ValueError):
            return

    def ring(self) -> Optional[ShardRing]:
        with self._mu:
            return self._ring

    def ring_version(self) -> int:
        with self._mu:
            return self._ring.version if self._ring is not None else 0

    def update_ring(self, ring: ShardRing) -> List[str]:
        """Adopt a new ring; on a version advance run the handoff sweep.
        Returns the task ids handed off (empty when none moved)."""
        with self._mu:
            current = self._ring
            if current is not None and ring.version <= current.version:
                return []
            self._ring = ring
        metrics.SHARD_RING_VERSION.set(ring.version, cluster="local")
        return self.handoff(ring)

    # -- handoff (membership change) -----------------------------------------

    def handoff(self, ring: ShardRing) -> List[str]:
        """Sweep the live task table for tasks this shard no longer owns
        under the new ring; mark them for REDIRECT steering.  The sweep
        is the cross-shard migration edge the flight recorder must show:
        it runs under the ``scheduler/shard.handoff`` span.
        """
        resource = self.resource
        if resource is None or len(ring) == 0:
            return []
        # Chaos seam: a handoff that dies mid-sweep must leave only
        # steerable state behind (marks are per-task, idempotent).
        faultinject.fire("shard.handoff")
        moved: List[str] = []
        with default_tracer.span(
            "scheduler/shard.handoff",
            shard=self.shard_id,
            ring_version=ring.version,
        ) as span:
            for task in resource.task_manager.items():
                owner = ring.owner(task.id)
                if owner is not None and owner != self.shard_id:
                    moved.append(task.id)
            with self._mu:
                # REBUILT each sweep (never merged): tasks the newest
                # ring returns to this shard unmark, and marks for tasks
                # long since GC'd don't accumulate forever.
                self._handed_off = {
                    tid: ring.owner(tid) or "" for tid in moved
                }
            span.attributes["tasks_moved"] = len(moved)
        if moved:
            metrics.SHARD_HANDOFFS_TOTAL.inc(amount=len(moved))
        return moved

    # -- steering ------------------------------------------------------------

    def check_task(self, task_id: str) -> None:
        """Raise the REDIRECT steering answer when ``task_id`` is owned
        elsewhere (by ring position, or because a handoff moved it)."""
        with self._mu:
            ring = self._ring
            new_owner = self._handed_off.get(task_id)
        if ring is None or len(ring) == 0:
            return
        owner = new_owner or ring.owner(task_id)
        if owner is None or owner == self.shard_id:
            return
        metrics.SHARD_REDIRECTS_TOTAL.inc()
        raise WrongShardError(
            task_id,
            owner_id=owner,
            owner_url=ring.url_of(owner) or "",
            ring_version=ring.version,
        )

    def admit(
        self, priority: Priority = Priority.LEVEL0, *, tenant: str = ""
    ) -> None:
        if self.admission is not None:
            self.admission.admit(priority, tenant=tenant)

    def track(self):
        if self.admission is not None:
            return self.admission.track()
        return _NullTrack()


class _NullTrack:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None
