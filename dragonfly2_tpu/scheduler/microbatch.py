"""Cross-request scorer micro-batching for the scheduler serving path.

The reference reserved a Triton/KServe *batched* inference seam for the
parent evaluator (``GRPCInferenceService``, ``model.graphdef`` +
``config.pbtxt``) but never wired it; our in-process scorer was called
once per announce.  ``ScorerBatcher`` restores the batched-inference
shape without the RPC: concurrent ``score()`` calls from the RPC handler
threads coalesce into ONE padded scorer call.

Mechanics (DESIGN.md §14):

- **leader/follower coalescing** — the first thread to enqueue becomes
  the flush leader; it lingers a bounded ``linger_s`` (~1-2 ms) while
  followers pile on, then takes the whole queue in one swap.  No
  background dispatcher thread: an idle batcher costs nothing and there
  is nothing to shut down.
- **bucketed pad sizes** — for scorers that declare ``static_shapes =
  True`` (jit-compiled / TPU inference backends), the concatenated rows
  are zero-padded up to a fixed bucket ladder so the backend sees a
  handful of static shapes instead of a recompile per occupancy.  Plain
  numpy scorers are shape-indifferent, so they get exact-size batches —
  padding them is pure wasted compute.
- **singleton bypass** — a flush that collected exactly one request
  calls the scorer on the raw, unpadded arrays.
- **atomic hot-swap** — the scorer reference is snapshotted once per
  flush, so ``ModelSubscriber.refresh`` swapping mid-batch can never
  hand half a batch to each model version.
- **fault seam** — dispatch fires ``scheduler.eval.batch``
  (utils.faultinject, DF004 inventory).  A dropped/failed coalesced call
  degrades to per-request scoring; announces never stall on the batcher
  (chaos drill in tests/test_chaos.py).
- **canary arms / pinned snapshots** — requests carry a ``candidate``
  flag (DESIGN.md §15 canary serving) and, when the caller resolved a
  scorer atomically with its CanaryRoute decision, the exact scorer
  snapshot (DESIGN.md §18).  A flush groups by SNAPSHOT and scores each
  group with its own scorer, so coalescing survives a canary — or a
  float→quantized rollout transition mid-linger — without ever mixing
  model versions or precisions inside one call.  A candidate
  uninstalled mid-queue pins its unpinned requests to the active
  scorer.
- **weighted-fair tenant lanes** (DESIGN.md §26) — requests queue in
  per-tenant FIFO lanes and the leader drains them with deficit round
  robin: each drain FIRST lands every backlogged lane's head request
  (on credit — the deficit goes negative, charging it against the
  lane's future share), then passes over the lanes growing each lane's
  deficit by ``quantum × weight`` and draining whole requests while
  the deficit covers their rows.  A 100-weight flood therefore cannot
  starve a 1-weight tenant (every drain serves every backlogged lane
  at least its head) while throughput still tracks the weights, and
  per-tenant arrival order is preserved (lanes are deques, head pops
  only).  Deficits carry across cap-limited flushes; a lane that
  empties resets (classic DRR).  With ONE active tenant the drain is a
  whole-queue swap — bit-equal to the pre-QoS single-queue behavior
  (the §14 oracle discipline, property-tested).  A flush past
  ``max_batch_rows`` leaves the excess queued and the leader loops
  until the lanes are dry, so followers never stall leaderless.

The scorer contract this relies on is row-independence: ``score`` must
score each row from that row (+ its buckets) alone, so padded rows and
co-batched strangers cannot bleed into each other (trainer/export.py
``EdgeScorer`` docstring — the batched-score contract).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

import numpy as np

from ..utils import faultinject
from . import metrics

logger = logging.getLogger(__name__)

DEFAULT_PAD_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# DRR quantum: rows of deficit a weight-1.0 lane earns per drain pass
# (sized to a typical candidate set so one pass serves one announce).
DEFAULT_DRR_QUANTUM = 32

DEFAULT_LANE = "default"


class ScorerUnavailable(RuntimeError):
    """No scorer installed at flush time (deactivated mid-queue); the
    evaluator catches this and falls back to rule-based ranking."""


class _Request:
    __slots__ = (
        "features", "src", "dst", "candidate", "scorer", "tenant", "rows",
        "done", "result", "error",
    )

    def __init__(
        self, features, src, dst, candidate=False, scorer=None, tenant=""
    ) -> None:
        self.features = features
        self.src = src
        self.dst = dst
        # Tenant lane key (DESIGN.md §26): "" rides the default lane.
        self.tenant = tenant or DEFAULT_LANE
        self.rows = int(features.shape[0])
        # Canary arm (DESIGN.md §15): True routes this request to the
        # flush's candidate-scorer snapshot instead of the active one.
        self.candidate = candidate
        # Pinned scorer snapshot, captured by the caller ATOMICALLY with
        # its CanaryRoute decision (DESIGN.md §18): a rollout transition
        # mid-linger (float → quantized candidate swap) must never score
        # this request with a different snapshot than the one its route
        # decision saw, and requests pinned to different snapshots must
        # never share one coalesced call.  None = use the flush snapshot
        # (legacy behavior, also what pins a candidate-gone request to
        # the active scorer).
        self.scorer = scorer
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class ScorerBatcher:
    """EdgeScorer wrapper: same ``score`` surface, coalesced execution."""

    def __init__(
        self,
        scorer=None,
        *,
        linger_s: float = 0.0015,
        max_batch_rows: int = 4096,
        pad_buckets=DEFAULT_PAD_BUCKETS,
        drr_quantum: int = DEFAULT_DRR_QUANTUM,
        qos_policy=None,
    ) -> None:
        self._cv = threading.Condition()
        # Per-tenant FIFO lanes (DESIGN.md §26): an OrderedDict so the
        # drain's round-robin order is arrival order of the lanes.
        self._lanes: "OrderedDict[str, deque]" = OrderedDict()
        # DRR deficit per backlogged lane; carries across cap-limited
        # flushes, resets when a lane empties (classic DRR).
        self._deficit: dict = {}
        # Rotating start pointer for the drain's lane order.
        self._rr = 0
        self._pending_rows = 0
        self._leader_active = False
        self._scorer = scorer
        self.drr_quantum = max(1, int(drr_quantum))
        # QoS policy (qos.policy.QoSPolicy, duck-typed on weight_of):
        # None = every lane weighs 1.0.
        self._qos_policy = qos_policy
        # Canary candidate scorer (None = no canary in flight); snapshotted
        # per flush exactly like the active scorer.
        self._candidate = None
        self.linger_s = linger_s
        self.max_batch_rows = max_batch_rows
        self.pad_buckets = tuple(sorted(pad_buckets))
        # Occupancy stats (bench_sched reads these; prometheus gets the
        # histogram in _dispatch).
        self.batches = 0
        self.batched_requests = 0
        self.fallbacks = 0

    # -- hot-swap (ModelSubscriber.refresh) ----------------------------------

    def set_scorer(self, scorer) -> None:
        with self._cv:
            self._scorer = scorer

    def set_candidate(self, scorer) -> None:
        """Install/clear the canary candidate scorer (MLEvaluator.set_canary)."""
        with self._cv:
            self._candidate = scorer

    def set_qos_policy(self, policy) -> None:
        """Install/clear the tenant QoS policy feeding the DRR weights
        (dynconfig observer; None = unweighted lanes)."""
        with self._cv:
            self._qos_policy = policy

    def _weight(self, tenant: str) -> float:
        policy = self._qos_policy
        if policy is None:
            return 1.0
        try:
            return max(float(policy.weight_of(tenant)), 1e-9)
        except Exception as exc:  # noqa: BLE001 — a bad policy must not wedge flushes
            logger.warning("qos policy weight_of(%r) failed: %s", tenant, exc)
            return 1.0

    @property
    def has_scorer(self) -> bool:
        return self._scorer is not None

    @property
    def wants_features(self) -> bool:
        return getattr(self._scorer, "wants_features", True)

    # -- the EdgeScorer surface ----------------------------------------------

    def score(self, features, *, src_buckets=None, dst_buckets=None, candidate=False, scorer=None, tenant=""):  # dflint: hotpath
        features = np.asarray(features, dtype=np.float32)
        req = _Request(features, src_buckets, dst_buckets, candidate, scorer, tenant)
        with self._cv:
            lane = self._lanes.get(req.tenant)
            if lane is None:
                lane = self._lanes[req.tenant] = deque()
            lane.append(req)
            self._pending_rows += req.rows
            lead = not self._leader_active
            if lead:
                self._leader_active = True
            elif self._pending_rows >= self.max_batch_rows:
                # Only a FULL queue is worth interrupting the leader's
                # linger for; waking it per enqueue burned a context
                # switch per follower on the serving profile.
                self._cv.notify_all()
        if lead:
            self._flush_as_leader()
        # Bounded wait + loop (DF008 timeout sweep): the leader's finally
        # block always sets done, so this never times out in practice —
        # but a wedged flush now logs and stays visible to watchdog stack
        # dumps instead of parking every follower forever.
        while not req.done.wait(5.0):  # dflint: disable=DF007 — bounded wait loop, not per-row work
            logger.warning(
                "scorer batch flush slow or wedged; follower still waiting "
                "(%d rows queued)", features.shape[0],
            )
        if req.error is not None:
            raise req.error
        return req.result

    # -- flush machinery -----------------------------------------------------

    def _flush_as_leader(self) -> None:
        deadline = time.monotonic() + self.linger_s
        try:
            while True:
                with self._cv:
                    while self._pending_rows < self.max_batch_rows:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    batch = self._drain_locked()
                    leftover = self._pending_rows > 0
                    # ONE snapshot of BOTH scorers for the whole flush; a
                    # canary uninstalled mid-queue pins its requests to the
                    # active scorer (never an error, never half-a-batch on
                    # each model version).
                    scorer = self._scorer
                    candidate = self._candidate if self._candidate is not None else scorer
                    if not leftover:
                        self._leader_active = False
                if batch:
                    self._dispatch(batch, scorer, candidate)
                if not leftover:
                    return
                # Cap-limited drain left requests queued: keep the
                # leadership and flush again immediately (no second
                # linger — the backlog IS the coalescing).
                deadline = time.monotonic()
        except BaseException:
            # A dispatch escape must not leave the queue leaderless
            # forever — followers would park on their done events.
            with self._cv:
                self._leader_active = False
            raise

    def _drain_locked(self) -> List[_Request]:
        """Take up to ``max_batch_rows`` rows off the lanes in
        deficit-round-robin order (module doc).  Single active lane =
        whole-queue swap, bit-equal to the pre-QoS behavior."""
        lanes = self._lanes
        if not lanes:
            return []
        if len(lanes) == 1:
            tenant, dq = next(iter(lanes.items()))
            batch = list(dq)
            lanes.clear()
            self._deficit.clear()
            self._pending_rows = 0
            return batch
        batch: List[_Request] = []
        rows = 0
        # Rotating lane order: the guarantee pass's cap spillover must
        # not always favor the same arrival-order prefix.
        keys = list(lanes.keys())
        start = self._rr % len(keys)
        self._rr += 1
        order = keys[start:] + keys[:start]
        # Anti-starvation guarantee: every backlogged lane lands its
        # HEAD request in every drain — deficit arithmetic alone can
        # park a 1-weight lane behind a 100-weight flood for several
        # cap-limited flushes (weight × quantum ≥ the row cap means the
        # flood eats the whole batch before the small lane's turn).
        for tenant in order:
            dq = lanes.get(tenant)
            if not dq or rows >= self.max_batch_rows:
                continue
            req = dq.popleft()
            # The head rides on credit: the deficit goes negative so the
            # DRR passes below charge it against the lane's future share
            # (weights stay honest over time).
            self._deficit[tenant] = (
                self._deficit.get(tenant, 0.0) - req.rows
            )
            batch.append(req)
            rows += req.rows
            if not dq:
                lanes.pop(tenant, None)
                self._deficit.pop(tenant, None)
        while rows < self.max_batch_rows and any(
            lanes.get(t) for t in order
        ):
            progressed = False
            for tenant in order:
                dq = lanes.get(tenant)
                if not dq:
                    continue
                self._deficit[tenant] = (
                    self._deficit.get(tenant, 0.0)
                    + self.drr_quantum * self._weight(tenant)
                )
                while (
                    dq
                    and rows < self.max_batch_rows
                    and self._deficit[tenant] >= dq[0].rows
                ):
                    req = dq.popleft()
                    self._deficit[tenant] -= req.rows
                    batch.append(req)
                    rows += req.rows
                    progressed = True
                if not dq:
                    # Lane drained: drop it and reset its deficit
                    # (classic DRR — an idle lane must not bank credit).
                    lanes.pop(tenant, None)
                    self._deficit.pop(tenant, None)
            if not progressed and rows < self.max_batch_rows:
                # Pathological quanta (microscopic weights vs a huge
                # head request): force the first backlogged head through
                # rather than spinning deficit passes — progress per
                # pass is a structural guarantee, not a tuning outcome.
                for tenant in order:
                    dq = lanes.get(tenant)
                    if dq:
                        self._deficit[tenant] = max(
                            self._deficit.get(tenant, 0.0),
                            float(dq[0].rows),
                        )
                        break
        self._pending_rows -= rows
        return batch

    def _pad_size(self, rows: int) -> int:
        i = bisect.bisect_left(self.pad_buckets, rows)
        if i < len(self.pad_buckets):
            return self.pad_buckets[i]
        top = self.pad_buckets[-1]
        return ((rows + top - 1) // top) * top

    def _dispatch(self, batch: List[_Request], scorer, candidate=None) -> None:
        """Split the flush by SCORER SNAPSHOT (requests for different
        model versions/precisions must not share a scorer call) and
        score each group coalesced with its own snapshot.

        A request's snapshot is, in priority order: the scorer it was
        pinned to at enqueue time (captured atomically with its
        CanaryRoute decision — a rollout transition mid-linger can
        therefore never produce a mixed-precision call), else the
        flush's candidate snapshot for canary-tagged requests (active
        when the candidate vanished mid-queue — pinned, never an
        error), else the flush's active snapshot."""
        groups: "OrderedDict[int, Tuple[object, List[_Request]]]" = OrderedDict()
        for r in batch:
            if r.scorer is not None:
                engine = r.scorer
            elif r.candidate:
                engine = candidate if candidate is not None else scorer
            else:
                engine = scorer
            key = id(engine)
            grp = groups.get(key)
            if grp is None:
                groups[key] = (engine, [r])
            else:
                grp[1].append(r)
        for engine, group in groups.values():
            self._dispatch_group(group, engine)

    def _dispatch_group(self, batch: List[_Request], scorer) -> None:
        # One ``scheduler/eval.flush`` span per coalesced scorer call
        # (per flush, never per announce): batch size + the dftrace
        # compile counter ride as attributes, so a slow flush in a trace
        # is immediately attributable to a steady-state retrace
        # (DESIGN.md §17/§21).
        from ..utils import dftrace
        from ..utils.tracing import default_tracer

        witness = dftrace.witness()
        t0 = time.perf_counter()
        with default_tracer.span(
            "scheduler/eval.flush",
            batch=len(batch),
            rows=sum(r.features.shape[0] for r in batch),
            jit_compiles=(
                witness.total_compiles() if witness is not None else 0
            ),
        ):
            self._dispatch_group_traced(batch, scorer)
        # Flush latency into the mergeable sketch (DESIGN.md §23): one
        # observe per FLUSH, never per announce — the fleet p99 of the
        # scorer path survives a SIGKILL via the metric journal.
        metrics.EVAL_FLUSH_SECONDS.observe(time.perf_counter() - t0)

    def _dispatch_group_traced(self, batch: List[_Request], scorer) -> None:
        try:
            if scorer is None:
                raise ScorerUnavailable("scorer deactivated while queued")
            feat_dim = batch[0].features.shape[1]
            if len(batch) == 1 or any(
                r.features.shape[1] != feat_dim for r in batch
            ):
                # Singleton bypass — and the hot-swap corner where queued
                # requests were featurized for scorers with different
                # input widths (no common padded matrix exists).
                self._score_each(batch, scorer)
                return
            rows = [r.features.shape[0] for r in batch]
            total = sum(rows)
            # Pad ladder only for static-shape (jit/TPU) backends; a
            # numpy scorer runs the exact concatenated size — padding it
            # is pure wasted compute (BENCHMARKS.md).
            if getattr(scorer, "static_shapes", False):
                padded = self._pad_size(total)
                feats = np.zeros((padded, feat_dim), dtype=np.float32)
                src = np.zeros(padded, dtype=np.int64)
                dst = np.zeros(padded, dtype=np.int64)
            else:
                padded = total
                feats = np.empty((total, feat_dim), dtype=np.float32)
                src = np.empty(total, dtype=np.int64)
                dst = np.empty(total, dtype=np.int64)
            off = 0
            for r in batch:
                n = r.features.shape[0]
                feats[off : off + n] = r.features
                src[off : off + n] = r.src if r.src is not None else 0
                dst[off : off + n] = r.dst if r.dst is not None else 0
                off += n
            faultinject.fire("scheduler.eval.batch")
            scores = np.asarray(
                scorer.score(feats, src_buckets=src, dst_buckets=dst)
            )
            off = 0
            for r, n in zip(batch, rows):
                r.result = scores[off : off + n]
                off += n
            self._note_batch(len(batch))
        except ScorerUnavailable as exc:
            for r in batch:
                r.error = exc
        except Exception as exc:  # noqa: BLE001 — degrade, never stall announces
            logger.warning(
                "coalesced scorer batch of %d request(s) failed (%s); "
                "degrading to per-request scoring", len(batch), exc,
            )
            with self._cv:
                self.fallbacks += 1
            metrics.EVAL_BATCH_FALLBACK_TOTAL.inc()
            self._score_each(batch, scorer)
        finally:
            for r in batch:
                r.done.set()

    def _score_each(self, batch: List[_Request], scorer) -> None:
        """Per-request scoring: the singleton bypass and the degraded mode
        after a failed coalesced call (one bad request must not sink its
        batch-mates)."""
        for r in batch:
            try:
                r.result = np.asarray(
                    scorer.score(
                        r.features, src_buckets=r.src, dst_buckets=r.dst
                    )
                )
            except Exception as exc:  # noqa: BLE001 — per-request verdicts
                logger.warning("per-request scoring failed: %s", exc)
                r.error = exc
        self._note_batch(len(batch))

    def _note_batch(self, n_requests: int) -> None:
        metrics.EVAL_BATCH_SIZE.observe(n_requests)
        with self._cv:
            self.batches += 1
            self.batched_requests += n_requests

    def mean_occupancy(self) -> float:
        with self._cv:
            return self.batched_requests / self.batches if self.batches else 0.0
