"""Cross-replica topology sharing + local durability.

Reference: the probe graph lives in Redis (scheduler/networktopology/
network_topology.go:55-88, pkg/redis) — shared across scheduler replicas
and surviving restarts.  The TPU build's Redis analog is the MANAGER:

- ``TopologySync`` pushes this scheduler's edge summaries to
  ``POST /api/v1/topology`` and pulls the other replicas' from
  ``GET /api/v1/topology?exclude=<self>``, merging newest-wins into the
  live store (NetworkTopology.merge_remote_edges) — a probe landed on
  scheduler A informs the nt evaluator's ranking on B within one sync
  interval;
- durability is a per-scheduler JSON state file
  (NetworkTopology.save/load) reloaded at boot, so a restart keeps its
  RTT knowledge even with no manager configured.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Optional

from .networktopology import NetworkTopology

logger = logging.getLogger(__name__)


class TopologySync:
    def __init__(
        self,
        topology: NetworkTopology,
        manager_url,
        scheduler_id: str,
        *,
        token: Optional[str] = None,
        interval_s: float = 30.0,
        timeout: float = 10.0,
        state_path: Optional[str] = None,
    ) -> None:
        from ..rpc.resolver import ManagerEndpoints

        self.topology = topology
        # Replica list / shared ManagerEndpoints: sync fails over with
        # every other manager client in the process.
        self.endpoints = ManagerEndpoints.of(manager_url, client="topology")
        self.scheduler_id = scheduler_id
        self.token = token
        self.interval_s = interval_s
        self.timeout = timeout
        # Persisted alongside each sync so a crash costs at most one
        # interval of probes.
        self.state_path = state_path
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def sync_once(self) -> int:
        """Push local edges, pull + merge the other replicas'; returns the
        number of remote edges adopted.  Manager outages degrade to the
        local store (and the disk state keeps durability)."""
        from ..utils import faultinject

        adopted = 0

        def one_endpoint(base: str):
            faultinject.fire("scheduler.topology.sync")
            body = json.dumps({
                "scheduler_id": self.scheduler_id,
                "edges": self.topology.export_edges(),
            }).encode()
            req = urllib.request.Request(
                base + "/api/v1/topology", data=body,
                headers=self._headers(), method="POST",
            )
            urllib.request.urlopen(req, timeout=self.timeout).close()

            with urllib.request.urlopen(
                urllib.request.Request(
                    base + f"/api/v1/topology?exclude={self.scheduler_id}",
                    headers=self._headers(),
                ),
                timeout=self.timeout,
            ) as resp:
                return json.loads(resp.read()).get("edges", [])

        try:
            remote = self.endpoints.call(one_endpoint)
            adopted = self.topology.merge_remote_edges(remote)
        except Exception as exc:  # noqa: BLE001 — outage ≠ crash
            logger.debug("topology sync failed: %s", exc)
        if self.state_path:
            try:
                self.topology.save(self.state_path)
            except OSError as exc:
                logger.warning("topology state save failed: %s", exc)
        return adopted

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                self.sync_once()

        self._thread = threading.Thread(
            target=loop, name="topology-sync", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.state_path:
            try:
                self.topology.save(self.state_path)
            except OSError:
                pass
