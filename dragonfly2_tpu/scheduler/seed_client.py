"""Scheduler→seed-peer trigger client (TriggerDownloadTask analog).

Reference: on a cold task the scheduler asks a seed peer to download it
with a priority, over the seed daemon's ``ObtainSeeds`` stream, and can
attach children as soon as the seed holds pieces
(scheduler/resource/seed_peer.go:93-229,
client/daemon/rpcserver/seeder.go:41-151).

``RemoteSeedPeerClient`` plugs into ``SchedulerService.seed_peer_trigger``:
it picks the best announced seed host (SUPER > STRONG > WEAK, then most
free upload slots), opens the daemon's chunked /obtain_seeds stream, and
returns as soon as the seed REGISTERED AND HOLDS ≥1 PIECE — the moment
children become schedulable against it — while the seed keeps
downloading in the background.  Works across processes: the only
coupling is the host announce (which already carries the daemon's
control port) and HTTP.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Iterable, Optional

from ..utils.types import HostType, Priority
from .resource import Host, Resource

logger = logging.getLogger(__name__)

_SEED_RANK = {
    HostType.SUPER_SEED: 0,
    HostType.STRONG_SEED: 1,
    HostType.WEAK_SEED: 2,
}


def pick_seed_host(hosts: Iterable[Host]) -> Optional[Host]:
    candidates = [
        h for h in hosts if h.type.is_seed and h.port > 0 and h.ip
    ]
    if not candidates:
        return None
    return min(
        candidates,
        key=lambda h: (_SEED_RANK.get(h.type, 9), -h.free_upload_count()),
    )


class RemoteSeedPeerClient:
    """callable(url, task_id) -> bool, for SchedulerService.seed_peer_trigger."""

    def __init__(
        self,
        resource: Resource,
        *,
        priority: Priority = Priority.LEVEL0,
        # Must stay BELOW the daemons' register-RPC client timeout (10 s
        # default): the trigger runs inline in register_peer, and a wait
        # longer than the caller's deadline fails the child's registration
        # even while the seed warm-up succeeds.
        first_piece_timeout_s: float = 8.0,
    ) -> None:
        self.resource = resource
        self.priority = priority
        self.first_piece_timeout_s = first_piece_timeout_s

    def __call__(self, url: str, task_id: str) -> bool:
        seed = pick_seed_host(self.resource.host_manager.items())
        if seed is None:
            return False
        endpoint = f"http://{seed.ip}:{seed.port}/obtain_seeds"
        body = json.dumps(
            {"url": url, "task_id": task_id, "priority": int(self.priority)}
        ).encode()
        req = urllib.request.Request(
            endpoint, data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        from ..utils import faultinject

        try:
            faultinject.fire("seed.trigger")
            resp = urllib.request.urlopen(req, timeout=self.first_piece_timeout_s)
        except Exception as exc:  # noqa: BLE001 — trigger failure → back-to-source
            logger.warning("seed trigger %s failed: %s", endpoint, exc)
            return False
        drained = False
        try:
            # Consume events until the seed holds a piece (schedulable) or
            # the stream ends.  urllib decodes the chunked framing; each
            # line is one JSON event.
            for raw in resp:
                try:
                    event = json.loads(raw)
                except ValueError:
                    continue
                kind = event.get("event")
                if kind == "piece" and event.get("count", 0) > 0:
                    # Keep draining in the background so the daemon's
                    # writes never block on a dead pipe; the drain thread
                    # owns closing the response.
                    import threading

                    drained = True
                    threading.Thread(
                        target=self._drain, args=(resp,), daemon=True
                    ).start()
                    return True
                if kind == "done":
                    return bool(event.get("ok")) and event.get("pieces", 0) > 0
        except Exception as exc:  # noqa: BLE001 — stream died mid-way
            logger.warning("seed stream %s died: %s", endpoint, exc)
        finally:
            if not drained:
                try:
                    resp.close()
                except Exception as exc:  # noqa: BLE001
                    logger.debug("seed stream close: %s", exc)
        return False

    @staticmethod
    def _drain(resp) -> None:
        try:
            for _ in resp:
                pass
        except Exception as exc:  # noqa: BLE001
            logger.debug("seed stream drain died: %s", exc)
        finally:
            try:
                resp.close()
            except Exception as exc:  # noqa: BLE001
                logger.debug("seed stream close: %s", exc)
