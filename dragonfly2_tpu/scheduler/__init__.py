"""Scheduler control plane (reference: scheduler/).

In-memory cluster state (hosts/tasks/peers with FSMs and a per-task peer
DAG), the parent-selection engine with its pluggable evaluators, the
network-topology probe store, and the training-record production path.

The TPU-first twist versus the reference: the ML evaluator is real here
(the reference's is a TODO at scheduler/scheduling/evaluator/evaluator.go:84-86).
Instead of a Triton RPC on the scheduling hot path, the trainer exports a
score table / compiled scorer that the evaluator consults locally.
"""

from .resource import (  # noqa: F401
    Host,
    HostManager,
    Peer,
    PeerManager,
    Resource,
    Task,
    TaskManager,
)
from .announcer import Announcer  # noqa: F401
from .evaluator import CanaryRoute, Evaluator, MLEvaluator, new_evaluator  # noqa: F401
from .featcache import HostFeatureCache  # noqa: F401
from .microbatch import ScorerBatcher, ScorerUnavailable  # noqa: F401
from .model_loader import ModelSubscriber  # noqa: F401
from .networktopology import NetworkTopology, Probe, ProbeAgent, TopologyConfig  # noqa: F401
from .scheduling import ScheduleResult, ScheduleResultKind, Scheduling, SchedulingConfig  # noqa: F401
from .service import RegisterResult, SchedulerService  # noqa: F401
from .sharding import (  # noqa: F401
    AdmissionController,
    ShardDirectory,
    ShardGuard,
    ShardRing,
    ShardSaturatedError,
    WrongShardError,
)
