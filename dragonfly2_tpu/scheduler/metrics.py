"""Scheduler metrics (reference: scheduler/metrics/metrics.go:44-180 —
~40 prometheus series: announce/register/download/piece totals+failures,
traffic by type, concurrency gauges).

Defined on the process-default registry; the service layer incs them at
the same seams the reference's handlers do. `expose_text()` is served by
the metrics port.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

REGISTER_PEER_TOTAL = _reg.counter(
    "scheduler_register_peer_total", "RegisterPeer requests", ["result"]
)
SCHEDULE_TOTAL = _reg.counter(
    "scheduler_schedule_total", "Scheduling outcomes", ["outcome"]
)
SCHEDULE_RETRIES = _reg.histogram(
    "scheduler_schedule_retries", "Retries per scheduling round",
    buckets=(0, 1, 2, 3, 4, 5),
)
PIECE_RESULT_TOTAL = _reg.counter(
    "scheduler_piece_result_total", "Reported piece results", ["result"]
)
PEER_RESULT_TOTAL = _reg.counter(
    "scheduler_peer_result_total", "Reported peer results", ["result"]
)
DOWNLOAD_RECORDS_TOTAL = _reg.counter(
    "scheduler_download_records_total", "Training records written"
)
PROBE_SYNC_TOTAL = _reg.counter(
    "scheduler_probe_sync_total", "SyncProbes rounds", ["phase"]
)
HOSTS_GAUGE = _reg.gauge("scheduler_hosts", "Registered hosts")
PEERS_GAUGE = _reg.gauge("scheduler_peers", "Live peers")
TASKS_GAUGE = _reg.gauge("scheduler_tasks", "Live tasks")

# -- serving engine (DESIGN.md §14: vectorized evaluate path) ----------------
EVAL_SECONDS = _reg.histogram(
    "scheduler_eval_seconds", "evaluate_parents latency", ["algorithm"],
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25),
)
EVAL_CACHE_TOTAL = _reg.counter(
    "scheduler_eval_cache_hits_total",
    "Host-feature cache lookups by outcome", ["result"],
)
EVAL_BATCH_SIZE = _reg.histogram(
    "scheduler_eval_batch_size",
    "Requests coalesced per scorer micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)
EVAL_BATCH_FALLBACK_TOTAL = _reg.counter(
    "scheduler_eval_batch_fallback_total",
    "Coalesced scorer batches degraded to per-request scoring",
)

# -- fleet telemetry plane (DESIGN.md §23: mergeable percentile sketches) ----
# Sketches carry the tail losslessly across processes (fixed-bucket
# histograms cannot): journaled crash-safe (utils/metric_journal.py) and
# merged fleet-wide by tools/fleet_assemble.py.
ANNOUNCE_SECONDS = _reg.sketch(
    "scheduler_announce_seconds",
    "announce_host handling latency (store/refresh + column write)",
)
EVAL_FLUSH_SECONDS = _reg.sketch(
    "scheduler_eval_flush_seconds",
    "Coalesced scorer flush latency per dispatched group "
    "(ScorerBatcher, DESIGN.md §14)",
)

# -- sharded fleet (DESIGN.md §24: ring routing, handoff, shedding) ----------
SHARD_RING_VERSION = _reg.gauge(
    "scheduler_shard_ring_version",
    "Consistent-hash ring version this process has adopted", ["cluster"],
)
SHARD_REDIRECTS_TOTAL = _reg.counter(
    "scheduler_shard_redirects_total",
    "Task-scoped calls answered with a wrong-shard steering redirect",
)
SHARD_HANDOFFS_TOTAL = _reg.counter(
    "scheduler_shard_handoffs_total",
    "Tasks marked for cross-shard migration by membership-change sweeps",
)
SHARD_SHED_TOTAL = _reg.counter(
    "scheduler_shard_shed_total",
    "Requests refused by admission control, by priority class",
    ["priority"],
)

# -- rollout plane (DESIGN.md §15: shadow scoring + canary serving) ----------
SHADOW_ANNOUNCES_TOTAL = _reg.counter(
    "scheduler_shadow_announces_total",
    "Shadow-scoring outcomes per announce", ["result"],  # scored|sampled_out|dropped|error
)
CANARY_ANNOUNCES_TOTAL = _reg.counter(
    "scheduler_canary_announces_total",
    "Announces routed per canary arm", ["arm"],  # candidate|active
)
ROLLOUT_SERVING_STATE = _reg.gauge(
    "scheduler_rollout_state",
    "Local rollout serving state per model name: 0 active-only, "
    "2 shadow, 3 canary (codes match manager rollout_state)", ["name"],
)
