"""Scheduler-side model subscription: registry → MLEvaluator scorer.

The reference intended the scheduler to call Triton over gRPC for every
evaluation (evaluator.go:84 TODO + the unwired KServe client); instead the
scheduler polls the manager registry (via dynconfig cadence) for the
active scorer version and hot-swaps the local MLEvaluator's scorer — a
pointer flip, never an RPC during scheduling.

Hot-swap atomicity (DESIGN.md §14): ``MLEvaluator.set_scorer`` is an
atomic reference flip that also re-targets the attached
``ScorerBatcher``; the evaluate path reads the scorer ONCE per call and
the batcher snapshots it ONCE per flush, so a refresh landing mid-announce
or mid-batch serves every in-flight ranking entirely from one model
version (concurrency drill: tests/test_sched_vectorized.py
refresh-under-load).  ``refresh`` itself is serialized by a lock so two
overlapping polls cannot interleave version bookkeeping.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..manager.registry import ModelRegistry
from .evaluator import MLEvaluator

logger = logging.getLogger(__name__)


class ModelSubscriber:
    def __init__(
        self,
        registry: ModelRegistry,
        evaluator: MLEvaluator,
        *,
        scheduler_id: str,
        model_name: str = "parent-bandwidth-mlp",
        refresh_interval: float = 300.0,
    ) -> None:
        self.registry = registry
        self.evaluator = evaluator
        self.scheduler_id = scheduler_id
        self.model_name = model_name
        self.refresh_interval = refresh_interval
        self._loaded_version: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._refresh_mu = threading.Lock()

    def refresh(self) -> bool:
        """Pull the active version if it changed; returns True on swap.
        Safe against concurrent callers (lock) and against RPC threads
        mid-``score`` (the evaluator/batcher snapshot the scorer)."""
        with self._refresh_mu:
            return self._refresh_locked()

    def _refresh_locked(self) -> bool:
        model = self.registry.active_model(self.scheduler_id, self.model_name)
        if model is None:
            if self._loaded_version is not None:
                self.evaluator.set_scorer(None)  # deactivated → rule fallback
                self._loaded_version = None
                return True
            return False
        if model.version == self._loaded_version:
            return False
        from ..trainer.export import load_scorer

        try:
            scorer = load_scorer(self.registry.load_artifact(model))
        except Exception:  # noqa: BLE001 — a bad artifact must not break scheduling
            logger.exception("loading model %s failed", model.id)
            return False
        self.evaluator.set_scorer(scorer)
        self._loaded_version = model.version
        logger.info("ML evaluator now serving %s v%d", model.name, model.version)
        return True

    def serve(self) -> None:
        if self._thread is not None:
            return
        self.refresh()

        def loop() -> None:
            while not self._stop.wait(self.refresh_interval):
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001
                    logger.exception("model refresh failed")

        self._thread = threading.Thread(target=loop, name="model-subscriber", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
