"""Scheduler-side model subscription: registry → MLEvaluator scorer.

The reference intended the scheduler to call Triton over gRPC for every
evaluation (evaluator.go:84 TODO + the unwired KServe client); instead the
scheduler polls the manager registry (via dynconfig cadence) for the
active scorer version and hot-swaps the local MLEvaluator's scorer — a
pointer flip, never an RPC during scheduling.

Hot-swap atomicity (DESIGN.md §14): ``MLEvaluator.set_scorer`` is an
atomic reference flip that also re-targets the attached
``ScorerBatcher``; the evaluate path reads the scorer ONCE per call and
the batcher snapshots it ONCE per flush, so a refresh landing mid-announce
or mid-batch serves every in-flight ranking entirely from one model
version (concurrency drill: tests/test_sched_vectorized.py
refresh-under-load).  ``refresh`` itself is serialized by a lock so two
overlapping polls cannot interleave version bookkeeping.

Rollout plane (DESIGN.md §15), when a ``rollout_client`` is attached:

- the same poll also fetches the CANDIDATE version (registry state
  SHADOW/CANARY) and installs a ``ShadowScorer`` — and, in the canary
  phase, a ``CanaryRoute`` — on the evaluator;
- **digest refusal**: artifacts are verified against the sha256 the
  registry recorded at create_model (``Registry.load_artifact`` /
  ``RemoteRegistry.load_artifact``); a mismatch logs and KEEPS the
  current scorer — a corrupted blob can demote serving quality, never
  scheduling itself;
- **pin on TOTAL manager loss (last resort only)**: the registry/
  rollout clients sweep the full manager replica list inside every poll
  (rpc/resolver.ManagerEndpoints), so a leader bounce with a standby
  attached fails over mid-poll and never degrades — the PR-4 pin
  engages only when ALL replicas are down.  When it does, a failed poll
  drops canary routing and shadow scoring and keeps serving the last
  ACTIVE scorer.  The pin is sticky until a poll SUCCEEDS (no flapping
  while the managers are down); a re-appearing candidate of the same
  version re-attaches the parked shadow engine with its counters
  intact;
- **poll jitter**: each wait is ``interval · (1 ± jitter)`` drawn from
  an RNG seeded by (scheduler_id, model_name), so a fleet of schedulers
  booted together never synchronizes into a registry thundering herd,
  while any single scheduler's schedule stays reproducible.

Regional model keys (DESIGN.md §29), when an ``idc`` is configured: the
lifecycle plane registers per-region specializations under the composed
name ``model_name@idc`` next to the fleet-wide global arm.  Every poll
asks for the idc-scoped name FIRST and falls back to the global name —
so a region with a promoted specialization serves it, and every other
region keeps serving the global model (no cross-region bleed: a
subscriber only ever requests its own two names).  Versions are
per-(scheduler_id, name) registry keys, so the subscriber tracks the
NAME its loaded/candidate versions belong to and never compares version
numbers across keys; the pin above likewise pins to the last ACTIVE of
whichever key was serving.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import TYPE_CHECKING, Optional, Union

from ..manager.registry import ModelRegistry
from . import metrics
from .evaluator import CanaryRoute, MLEvaluator

if TYPE_CHECKING:  # wiring-time registry/rollout arms (no runtime import cycle)
    from ..rollout.client import LocalRolloutClient, RolloutRESTClient
    from ..rpc.grpc_transport import GRPCRemoteRegistry
    from ..rpc.registry_client import RemoteRegistry

logger = logging.getLogger(__name__)


class ModelSubscriber:
    def __init__(
        self,
        registry: "Union[ModelRegistry, RemoteRegistry, GRPCRemoteRegistry]",
        evaluator: MLEvaluator,
        *,
        scheduler_id: str,
        model_name: str = "parent-bandwidth-mlp",
        idc: Optional[str] = None,
        refresh_interval: float = 300.0,
        jitter: float = 0.1,
        rollout_client: "Optional[Union[LocalRolloutClient, RolloutRESTClient]]" = None,
        shadow_sample_rate: float = 0.1,
        shadow_log_path: Optional[str] = None,
    ) -> None:
        from ..lifecycle.arbiter import regional_model_name

        self.registry = registry
        self.evaluator = evaluator
        self.scheduler_id = scheduler_id
        self.model_name = model_name
        self.idc = idc or None
        # Poll order: idc-scoped specialization first, global fallback.
        self._names = (
            (regional_model_name(model_name, self.idc), model_name)
            if self.idc
            else (model_name,)
        )
        self.refresh_interval = refresh_interval
        self.jitter = max(0.0, float(jitter))
        self.rollout_client = rollout_client
        self.shadow_sample_rate = shadow_sample_rate
        self.shadow_log_path = shadow_log_path
        self._loaded_version: Optional[int] = None
        self._loaded_key: Optional[str] = None
        self._candidate_version: Optional[int] = None
        self._candidate_key: Optional[str] = None
        self._candidate_scorer = None
        self._shadow = None
        self._pinned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards the version bookkeeping + evaluator installs ONLY — it is
        # never held across the registry/rollout RPCs (DF008): refresh
        # snapshots state, polls the network unlocked, then commits under
        # the lock.  `_refresh_gen` makes commits first-poll-wins: an
        # overlapping poll that lost the race discards its fetch instead
        # of installing stale versions out of order.
        self._refresh_mu = threading.Lock()
        self._refresh_gen = 0
        # Seeded per (scheduler, model, idc): deterministic for THIS
        # instance, decorrelated across a fleet (the anti-thundering-herd
        # draw).  The idc-less seed string is unchanged so existing
        # deployments keep their schedules.
        seed = f"{scheduler_id}:{model_name}"
        if self.idc:
            seed += f"@{self.idc}"
        self._rng = random.Random(seed)

    @property
    def candidate_name(self) -> str:
        """Registry name of the candidate currently under evaluation —
        the scoped name when a regional specialization is in flight.
        Reports (rollout/reporter.py) must target THIS key or the
        controller would judge the wrong rollout row."""
        with self._refresh_mu:
            return self._candidate_key or self.model_name

    @property
    def pinned(self) -> bool:
        """True only in the all-replicas-down last resort (the failover
        drills assert this NEVER trips while a standby is reachable)."""
        with self._refresh_mu:
            return self._pinned

    def _next_interval(self) -> float:
        if not self.jitter:
            return self.refresh_interval
        return self.refresh_interval * (
            1.0 + self._rng.uniform(-self.jitter, self.jitter)
        )

    def refresh(self) -> bool:
        """Pull the active (and candidate) version if changed; returns
        True on an active-scorer swap.  Safe against concurrent callers
        and against RPC threads mid-``score`` (the evaluator/batcher
        snapshot the scorer).  The registry/rollout RPCs run with NO lock
        held — state is snapshotted first and the results commit under
        ``_refresh_mu`` only if no other poll committed in between
        (first-poll-wins; the loser's fetch is discarded).  A failed poll
        PINS the evaluator to the last ACTIVE version (canary + shadow
        detached) instead of raising — scheduling never depends on
        manager liveness."""
        with self._refresh_mu:
            gen = self._refresh_gen
            loaded = (self._loaded_key, self._loaded_version)
            candidate = (self._candidate_key, self._candidate_version)
        # ---- network phase: registry + rollout polls, artifact loads ----
        try:
            active = self._fetch_active(loaded)
        except Exception as exc:  # noqa: BLE001 — manager outage → pin
            with self._refresh_mu:
                self._pin_locked(exc)
            return False
        candidate_state = candidate_exc = None
        try:
            candidate_state = self._fetch_candidate(candidate)
        except Exception as exc:  # noqa: BLE001 — candidate poll is best-effort
            candidate_exc = exc
        # ---- commit phase: bookkeeping + evaluator installs, locked ----
        with self._refresh_mu:
            if gen != self._refresh_gen:
                # A concurrent poll committed while we were on the wire;
                # its snapshot is at least as fresh as ours.
                return False
            self._refresh_gen += 1
            changed = self._commit_active_locked(active)
            if candidate_exc is not None:
                self._pin_locked(candidate_exc)
            else:
                self._commit_candidate_locked(candidate_state)
            return changed

    def _fetch_active(self, loaded):
        """Network half of the active-model poll (no lock held): returns
        ``("deactivate"|"unchanged"|"load_failed", model, scorer)``.
        Tries the idc-scoped name first, then the global fallback; the
        first ACTIVE found wins.  A failed scoped poll raises (→ pin);
        ``None`` falls through to the next name."""
        model = None
        for name in self._names:
            model = self.registry.active_model(self.scheduler_id, name)
            if model is not None:
                break
        if model is None:
            return ("deactivate", None, None)
        if (model.name, model.version) == loaded:
            return ("unchanged", model, None)
        from ..trainer.export import load_scorer

        try:
            # load_artifact verifies the recorded sha256 (ArtifactDigestError
            # on mismatch): a corrupted/swapped blob is REFUSED here and the
            # current scorer keeps serving.
            scorer = load_scorer(self.registry.load_artifact(model))
        except Exception:  # noqa: BLE001 — a bad artifact must not break scheduling
            logger.exception("loading model %s failed; keeping current scorer", model.id)
            return ("load_failed", model, None)
        return ("swap", model, scorer)

    def _commit_active_locked(self, active) -> bool:
        kind, model, scorer = active
        if kind == "deactivate":
            if self._loaded_version is not None:
                self.evaluator.set_scorer(None)  # deactivated → rule fallback
                self._loaded_version = None
                self._loaded_key = None
                return True
            return False
        if kind != "swap" or (
            model.name == self._loaded_key and model.version == self._loaded_version
        ):
            return False
        self.evaluator.set_scorer(scorer)
        self._loaded_version = model.version
        self._loaded_key = model.name
        logger.info("ML evaluator now serving %s v%d", model.name, model.version)
        return True

    # -- rollout candidate (shadow / canary) ---------------------------------

    def _fetch_candidate(self, candidate):
        """Network half of the candidate poll (no lock held): returns
        ``None`` (no rollout client) or ``("drop"|"install"|"keep"|"same",
        info, scorer)``.  Raises on a failed poll — the caller pins.
        Same idc-scoped-then-global name order as the active poll, so a
        region shadow-scores its own specialization when one is in
        flight and the global candidate otherwise."""
        if self.rollout_client is None:
            return None
        info = None
        for name in self._names:
            info = self.rollout_client.candidate(self.scheduler_id, name)
            if info is not None:
                break
        if info is None:
            return ("drop", None, None)
        if (info.model.name, info.model.version) != candidate:
            from ..trainer.export import load_scorer

            try:
                scorer = load_scorer(self.registry.load_artifact(info.model))
            except Exception:  # noqa: BLE001 — refuse the candidate, keep serving
                logger.exception(
                    "loading candidate %s failed; rollout state unchanged",
                    info.model.id,
                )
                return ("keep", info, None)
            return ("install", info, scorer)
        return ("same", info, None)

    def _commit_candidate_locked(self, candidate) -> None:
        if candidate is None:
            return
        kind, info, scorer = candidate
        if self._pinned:
            self._pinned = False
            logger.info("manager poll recovered; rollout state unpinned")
        if kind == "drop":
            self._drop_candidate_locked()
            return
        if kind == "keep":
            return
        if kind == "install" and (
            info.model.name != self._candidate_key
            or info.model.version != self._candidate_version
        ):
            from ..rollout.shadow import ShadowScorer

            if self._shadow is not None:
                self._shadow.close()
            self._shadow = ShadowScorer(
                scorer,
                candidate_version=info.model.version,
                active_version=self._loaded_version or 0,
                sample_rate=self.shadow_sample_rate,
                log_path=self.shadow_log_path,
            )
            self._candidate_scorer = scorer
            self._candidate_version = info.model.version
            self._candidate_key = info.model.name
            logger.info(
                "shadow scoring %s v%d against active v%s",
                info.model.name, info.model.version, self._loaded_version,
            )
        elif self._shadow is not None:
            # Same candidate; keep the engine but track active swaps.
            self._shadow.active_version = self._loaded_version or 0
        self.evaluator.set_shadow(self._shadow)
        if info.phase == "canary" and info.canary_percent > 0:
            canary = self.evaluator.canary
            if (
                canary is None
                or canary.version != self._candidate_version
                or canary.percent != info.canary_percent
            ):
                self.evaluator.set_canary(
                    CanaryRoute(
                        self._candidate_scorer,
                        info.canary_percent,
                        self._candidate_version,
                    )
                )
                logger.info(
                    "canary serving %s v%d at %d%%",
                    self.model_name, self._candidate_version, info.canary_percent,
                )
            metrics.ROLLOUT_SERVING_STATE.set(3, name=self.model_name)
        else:
            self.evaluator.set_canary(None)
            metrics.ROLLOUT_SERVING_STATE.set(2, name=self.model_name)

    def _drop_candidate_locked(self) -> None:
        """Candidate gone from the registry (promoted or rolled back):
        detach + dispose the local rollout state."""
        self.evaluator.set_canary(None)
        self.evaluator.set_shadow(None)
        if self._shadow is not None:
            self._shadow.close()
            self._shadow = None
        self._candidate_scorer = None
        self._candidate_version = None
        self._candidate_key = None
        metrics.ROLLOUT_SERVING_STATE.set(0, name=self.model_name)

    def _pin_locked(self, exc: BaseException) -> None:
        """EVERY manager replica unreachable (the client already swept
        the endpoint list): pin serving to the last ACTIVE version.
        Canary routing and shadow scoring DETACH (an unverified candidate
        must not take traffic while its judge is absent) but the shadow
        engine parks — a recovered poll for the same candidate version
        re-attaches it with its counters and replay log intact."""
        had_rollout = (
            self.evaluator.canary is not None or self.evaluator.shadow is not None
        )
        self.evaluator.set_canary(None)
        self.evaluator.set_shadow(None)
        metrics.ROLLOUT_SERVING_STATE.set(0, name=self.model_name)
        if not self._pinned:
            self._pinned = True
            if had_rollout:
                logger.warning(
                    "model poll failed (%s); pinned to last ACTIVE v%s — "
                    "canary/shadow detached until the manager returns",
                    exc, self._loaded_version,
                )
            else:
                logger.warning(
                    "model poll failed (%s); keeping scorer v%s",
                    exc, self._loaded_version,
                )

    def serve(self) -> None:
        if self._thread is not None:
            return
        self.refresh()

        def loop() -> None:
            while not self._stop.wait(self._next_interval()):
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001
                    logger.exception("model refresh failed")

        self._thread = threading.Thread(target=loop, name="model-subscriber", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._shadow is not None:
            self._shadow.close()
