"""Announcer: registers with the manager and ships datasets to the trainer.

Reference (scheduler/announcer/announcer.go): register + keepalive with the
manager (:84-127) and, on ``Trainer.Interval``, stream both record CSVs to
the trainer in 128 MiB chunks over one ``Train`` stream (:144-237).

Here the dataset is already columnar; upload hands the trainer shard
*paths* when co-located (zero-copy — the trainer mmaps the same files) or
chunked bytes when remote, preserving the reference's chunked-stream shape
for the cross-node case.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

from ..records.storage import Storage

if TYPE_CHECKING:
    from ..manager.cluster import ClusterManager, SchedulerInstance
    from ..trainer.service import TrainerService

UPLOAD_CHUNK_BYTES = 128 << 20  # announcer.go:39-41


class Announcer:
    def __init__(
        self,
        scheduler_id: str,
        storage: Storage,
        trainer: "TrainerService",
        *,
        cluster_manager: Optional["ClusterManager"] = None,
        cluster_id: str = "default",
        ip: str = "",
        port: int = 8002,
        hostname: str = "",
        train_interval: float = 7 * 24 * 3600.0,  # constants.go:198 default 7d
    ) -> None:
        self.scheduler_id = scheduler_id
        self.storage = storage
        self.trainer = trainer
        # Any ClusterManager-shaped object: the in-process manager OR the
        # REST wire (rpc/cluster_client.RemoteClusterClient) — one
        # register+keepalive loop implementation either way.
        self.cluster_manager = cluster_manager
        self.cluster_id = cluster_id
        self.ip = ip
        self.port = port
        self.hostname = hostname
        self.train_interval = train_interval
        self.keepalive_interval = 20.0  # < ClusterManager TTL (60 s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._keepalive_thread: Optional[threading.Thread] = None

    def announce_to_manager(self) -> None:
        """Register + keepalive (announcer.go:84-127)."""
        if self.cluster_manager is None:
            return
        from ..manager.cluster import SchedulerInstance

        self.cluster_manager.register_scheduler(
            SchedulerInstance(
                id=self.scheduler_id,
                cluster_id=self.cluster_id,
                hostname=self.hostname,
                ip=self.ip,
                port=self.port,
            )
        )

    def keepalive(self) -> None:
        if self.cluster_manager is not None:
            self.cluster_manager.keepalive(self.scheduler_id)

    def announce_to_trainer(self) -> str:
        """One Train round (announcer.go:144-171): flush buffers, hand both
        datasets to the trainer keyed by this scheduler's host identity, and
        kick training.  Returns the trainer's train-run key."""
        self.storage.flush()
        session = self.trainer.open_train_stream(
            ip=self.ip, hostname=self.hostname, scheduler_id=self.scheduler_id
        )
        for path in self.storage.download_columnar_paths():
            session.send_download_shard(path)
        for path in self.storage.network_topology_columnar_paths():
            session.send_network_topology_shard(path)
        return session.close_and_train()

    def serve(self) -> None:
        if self._thread is not None:
            return
        self.announce_to_manager()

        def train_loop() -> None:
            while not self._stop.wait(self.train_interval):
                try:
                    self.announce_to_trainer()
                except Exception:  # noqa: BLE001 — announce must not kill the scheduler
                    import logging

                    logging.getLogger(__name__).exception("announce_to_trainer failed")

        def keepalive_loop() -> None:
            # The manager marks schedulers inactive past its keepalive TTL
            # (manager/cluster.py); tick well inside it (announcer.go:119-127).
            while not self._stop.wait(self.keepalive_interval):
                self.keepalive()

        self._thread = threading.Thread(target=train_loop, name="announcer", daemon=True)
        self._thread.start()
        if self.cluster_manager is not None:
            self._keepalive_thread = threading.Thread(
                target=keepalive_loop, name="announcer-keepalive", daemon=True
            )
            self._keepalive_thread.start()

    def stop(self) -> None:
        self._stop.set()
