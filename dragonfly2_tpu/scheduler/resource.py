"""In-memory cluster state: Host / Task / Peer resources with FSMs.

Reference parity (scheduler/resource/):
- peer lifecycle FSM: states & events mirror peer.go:52-110 (Pending →
  Received{Empty,Tiny,Small,Normal} → Running / BackToSource →
  Succeeded / Failed → Leave).
- task lifecycle FSM: task.go:57-85 (Pending/Running/Succeeded/Failed/Leave,
  re-download allowed from terminal states).
- per-task peer DAG: task.go:155, edges :276-365 — parents point at
  children; in-degree 0 + not-seed + not-finished means "has no parent yet".
- size scope: task.go:444-470 (EMPTY =0B, TINY ≤128B, SMALL single piece,
  NORMAL else, UNKNOWN when length or piece count is unknown).
- managers: sync.Map stores with TTL-based GC (host_manager.go,
  peer_manager.go, task_manager.go), LoadRandomPeers (task.go:243),
  LoadRandomHosts (host_manager.go:121-140).

Everything here is the *source of the training signal*: piece costs append
into ``Peer.piece_costs`` (bad-node statistics, evaluator features) and
finished downloads are converted into ``records.schema.Download`` rows by
the service layer.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from ..records import schema
from ..utils.dag import DAG, DAGError
from ..utils.fsm import FSM, EventDesc
from ..utils.hostinfo import BuildInfo, CPUStat, DiskStat, MemoryStat, NetworkStat
from ..utils.types import (
    EMPTY_FILE_SIZE,
    TINY_FILE_SIZE,
    HostType,
    Priority,
    SizeScope,
)

# ---------------------------------------------------------------------------
# Peer FSM (peer.go:52-110)
# ---------------------------------------------------------------------------

PEER_PENDING = "Pending"
PEER_RECEIVED_EMPTY = "ReceivedEmpty"
PEER_RECEIVED_TINY = "ReceivedTiny"
PEER_RECEIVED_SMALL = "ReceivedSmall"
PEER_RECEIVED_NORMAL = "ReceivedNormal"
PEER_RUNNING = "Running"
PEER_BACK_TO_SOURCE = "BackToSource"
PEER_SUCCEEDED = "Succeeded"
PEER_FAILED = "Failed"
PEER_LEAVE = "Leave"

_RECEIVED_STATES = (
    PEER_RECEIVED_EMPTY,
    PEER_RECEIVED_TINY,
    PEER_RECEIVED_SMALL,
    PEER_RECEIVED_NORMAL,
)

PEER_EVENTS = (
    EventDesc("RegisterEmpty", (PEER_PENDING,), PEER_RECEIVED_EMPTY),
    EventDesc("RegisterTiny", (PEER_PENDING,), PEER_RECEIVED_TINY),
    EventDesc("RegisterSmall", (PEER_PENDING,), PEER_RECEIVED_SMALL),
    EventDesc("RegisterNormal", (PEER_PENDING,), PEER_RECEIVED_NORMAL),
    EventDesc("Download", _RECEIVED_STATES, PEER_RUNNING),
    EventDesc(
        "DownloadBackToSource",
        _RECEIVED_STATES + (PEER_RUNNING,),
        PEER_BACK_TO_SOURCE,
    ),
    EventDesc(
        "DownloadSucceeded",
        _RECEIVED_STATES + (PEER_RUNNING, PEER_BACK_TO_SOURCE),
        PEER_SUCCEEDED,
    ),
    EventDesc(
        "DownloadFailed",
        (PEER_PENDING,)
        + _RECEIVED_STATES
        + (PEER_RUNNING, PEER_BACK_TO_SOURCE, PEER_SUCCEEDED),
        PEER_FAILED,
    ),
    EventDesc(
        "Leave",
        (PEER_PENDING,)
        + _RECEIVED_STATES
        + (PEER_RUNNING, PEER_BACK_TO_SOURCE, PEER_FAILED, PEER_SUCCEEDED),
        PEER_LEAVE,
    ),
)

# ---------------------------------------------------------------------------
# Task FSM (task.go:57-85)
# ---------------------------------------------------------------------------

TASK_PENDING = "Pending"
TASK_RUNNING = "Running"
TASK_SUCCEEDED = "Succeeded"
TASK_FAILED = "Failed"
TASK_LEAVE = "Leave"

TASK_EVENTS = (
    EventDesc(
        "Download", (TASK_PENDING, TASK_SUCCEEDED, TASK_FAILED, TASK_LEAVE), TASK_RUNNING
    ),
    EventDesc(
        "DownloadSucceeded", (TASK_LEAVE, TASK_RUNNING, TASK_FAILED), TASK_SUCCEEDED
    ),
    EventDesc("DownloadFailed", (TASK_RUNNING,), TASK_FAILED),
    EventDesc(
        "Leave", (TASK_PENDING, TASK_RUNNING, TASK_SUCCEEDED, TASK_FAILED), TASK_LEAVE
    ),
)


def _now() -> float:
    return time.monotonic()


@dataclass
class HostStats:
    """Mutable announce-time stats (host.go:133-347 Host fields)."""

    cpu: CPUStat = field(default_factory=CPUStat)
    memory: MemoryStat = field(default_factory=MemoryStat)
    network: NetworkStat = field(default_factory=NetworkStat)
    disk: DiskStat = field(default_factory=DiskStat)
    build: BuildInfo = field(default_factory=BuildInfo)


class Host:
    """A peer machine (scheduler/resource/host.go).

    Columnar ownership (DESIGN.md §18): when a ``HostFeatureCache`` binds
    this host to a slot (``_cols = (store, slot)``), the store's slot
    columns become the *source of truth* for the hot serving fields —
    upload counters/limit, ``updated_at``, peer count — and the shadow
    attributes here go stale until detach copies the columns back.  The
    property accessors read/write through the binding, so every legacy
    caller (``to_record``, the scalar ``*_reference`` oracles, tests)
    observes exactly the column state; the serving gather never touches
    this object at all.  The binding is flipped only while holding BOTH
    the store lock and this host's lock (store → host order, §16), and
    ``_mut`` is a monotonic mutation stamp bumped by every write so
    non-owning caches can validate their copies.
    """

    def __init__(
        self,
        id: str,
        hostname: str,
        ip: str,
        *,
        port: int = 0,
        download_port: int = 0,
        type: HostType = HostType.NORMAL,
        concurrent_upload_limit: int = 50,
        os: str = "",
        platform: str = "",
        scheduler_cluster_id: int = 0,
    ) -> None:
        self.id = id
        self.hostname = hostname
        self.ip = ip
        self.port = port
        self.download_port = download_port
        self.type = type
        self.os = os
        self.platform = platform
        self.scheduler_cluster_id = scheduler_cluster_id
        self.stats = HostStats()
        self._mu = threading.Lock()
        # Columnar binding + mutation stamp come FIRST: the property
        # setters below consult them.
        self._cols = None  # (HostFeatureCache, slot) when column-owned
        # Slot in the process's PRIMARY store (featcache._primary_ref),
        # -1 otherwise: the lock-free rule gather validates ownership
        # with ONE attribute read per candidate instead of a binding
        # tuple walk (maintained by bind/detach).
        self._pslot = -1
        self._mut = 0
        self._concurrent_upload_limit = concurrent_upload_limit
        self._concurrent_upload_count = 0
        self._upload_count = 0
        self._upload_failed_count = 0
        self.peers: Dict[str, "Peer"] = {}
        self.created_at = time.time()
        self._updated_at = self.created_at
        # Negotiated wire dialect for this host's connections
        # (rpc/version.py; 1 = the legacy unversioned dialect).
        self.protocol_version = 1

    # -- columnar thin-view accessors ---------------------------------------
    #
    # Getters are lock-free: a single column read is as atomic as the old
    # plain attribute read, and the re-check of `_cols` closes the detach/
    # slot-recycle window (a detach copies columns back to the shadows
    # BEFORE clearing the binding, so a raced read falls back to a value
    # at least as fresh).  Setters serialize under the host lock against
    # bind/detach, which also hold it.

    def _col_read(self, col_name: str, shadow_name: str):
        b = self._cols
        if b is None:
            return getattr(self, shadow_name)
        v = getattr(b[0], col_name)[b[1]]
        if self._cols is b:
            return v
        return getattr(self, shadow_name)

    @property
    def upload_count(self) -> int:
        return int(self._col_read("_upload_count_col", "_upload_count"))

    @upload_count.setter
    def upload_count(self, v: int) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._upload_count = int(v)
            else:
                b[0].write_upload_state(b[1], self._mut, upload_count=int(v))

    @property
    def upload_failed_count(self) -> int:
        return int(self._col_read("_upload_failed_col", "_upload_failed_count"))

    @upload_failed_count.setter
    def upload_failed_count(self, v: int) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._upload_failed_count = int(v)
            else:
                b[0].write_upload_state(b[1], self._mut, upload_failed_count=int(v))

    @property
    def concurrent_upload_count(self) -> int:
        return int(self._col_read("_concurrent_upload_col", "_concurrent_upload_count"))

    @concurrent_upload_count.setter
    def concurrent_upload_count(self, v: int) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._concurrent_upload_count = int(v)
            else:
                b[0].write_upload_state(b[1], self._mut, concurrent_upload_count=int(v))

    @property
    def concurrent_upload_limit(self) -> int:
        return int(self._col_read("_upload_limit_col", "_concurrent_upload_limit"))

    @concurrent_upload_limit.setter
    def concurrent_upload_limit(self, v: int) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._concurrent_upload_limit = int(v)
            else:
                b[0].write_upload_state(b[1], self._mut, concurrent_upload_limit=int(v))

    @property
    def updated_at(self) -> float:
        return float(self._col_read("_updated_at_col", "_updated_at"))

    @updated_at.setter
    def updated_at(self, v: float) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._updated_at = float(v)
            else:
                b[0].write_updated_at(b[1], self._mut, float(v))

    def free_upload_count(self) -> int:
        with self._mu:
            b = self._cols
            if b is None:
                return self._concurrent_upload_limit - self._concurrent_upload_count
            store, slot = b
            return int(store._upload_limit_col[slot]) - int(
                store._concurrent_upload_col[slot]
            )

    def acquire_upload(self) -> bool:
        with self._mu:
            b = self._cols
            if b is None:
                if self._concurrent_upload_count >= self._concurrent_upload_limit:
                    return False
                self._mut += 1
                self._concurrent_upload_count += 1
                return True
            store, slot = b
            cur = int(store._concurrent_upload_col[slot])
            if cur >= int(store._upload_limit_col[slot]):
                return False
            self._mut += 1
            store.write_upload_state(slot, self._mut, concurrent_upload_count=cur + 1)
            return True

    def release_upload(self, succeeded: bool = True) -> None:
        with self._mu:
            self._mut += 1
            b = self._cols
            if b is None:
                self._concurrent_upload_count = max(
                    self._concurrent_upload_count - 1, 0
                )
                self._upload_count += 1
                if not succeeded:
                    self._upload_failed_count += 1
                return
            store, slot = b
            failed = int(store._upload_failed_col[slot]) + (0 if succeeded else 1)
            store.write_upload_state(
                slot,
                self._mut,
                concurrent_upload_count=max(
                    int(store._concurrent_upload_col[slot]) - 1, 0
                ),
                upload_count=int(store._upload_count_col[slot]) + 1,
                upload_failed_count=failed,
            )

    def store_peer(self, peer: "Peer") -> None:
        with self._mu:
            self.peers[peer.id] = peer
            b = self._cols
            if b is not None:
                b[0].write_peer_count(b[1], len(self.peers))

    def delete_peer(self, peer_id: str) -> None:
        with self._mu:
            self.peers.pop(peer_id, None)
            b = self._cols
            if b is not None:
                b[0].write_peer_count(b[1], len(self.peers))

    def peer_count(self) -> int:
        with self._mu:
            return len(self.peers)

    def leave_peers(self) -> None:
        """Mark all this host's peers as leaving (host going away)."""
        with self._mu:
            peers = list(self.peers.values())
        for p in peers:
            if p.fsm.can("Leave"):
                p.fsm.event("Leave")

    def touch(self) -> None:
        """Announce-path stats refresh: for a column-owned host this
        recomputes the whole slot row in place (stats may have changed —
        the same contract the PR-3 stamp expressed: every feature-input
        mutation must be accompanied by a ``touch``)."""
        self._mut += 1
        b = self._cols
        if b is None:
            self._updated_at = time.time()
        else:
            b[0].refresh_row(self)

    def touch_stamp(self) -> None:
        """Freshness-only touch for the adopt→announce sequence: the
        bind that just ran computed the row from these very stats, so
        only ``updated_at`` needs writing (the full ``touch`` here was
        a second identical row fill per cold announce).  The mutation
        counter still advances — foreign stamped copies must revalidate
        against the new stamp."""
        self._mut += 1
        b = self._cols
        if b is None:
            self._updated_at = time.time()
        else:
            b[0].stamp_row(self)

    def to_record(self) -> schema.HostRecord:
        return schema.HostRecord(
            id=self.id,
            type=self.type.name_str,
            hostname=self.hostname,
            ip=self.ip,
            port=self.port,
            download_port=self.download_port,
            os=self.os,
            platform=self.platform,
            concurrent_upload_limit=self.concurrent_upload_limit,
            concurrent_upload_count=self.concurrent_upload_count,
            upload_count=self.upload_count,
            upload_failed_count=self.upload_failed_count,
            cpu=self.stats.cpu,
            memory=self.stats.memory,
            network=self.stats.network,
            disk=self.stats.disk,
            build=self.stats.build,
            scheduler_cluster_id=self.scheduler_cluster_id,
            created_at=int(self.created_at * 1e9),
            updated_at=int(self.updated_at * 1e9),
        )


class Piece:
    """Piece metadata cached on the task (task.go StorePiece)."""

    __slots__ = ("number", "parent_id", "offset", "length", "digest", "cost_ns", "created_at")

    def __init__(
        self,
        number: int,
        *,
        parent_id: str = "",
        offset: int = 0,
        length: int = 0,
        digest: str = "",
        cost_ns: int = 0,
    ) -> None:
        self.number = number
        self.parent_id = parent_id
        self.offset = offset
        self.length = length
        self.digest = digest
        self.cost_ns = cost_ns
        self.created_at = time.time()


class Task:
    """A piece of content being distributed; owns the per-task peer DAG
    (scheduler/resource/task.go)."""

    def __init__(
        self,
        id: str,
        url: str,
        *,
        type: str = "standard",
        digest: str = "",
        tag: str = "",
        application: str = "",
        filtered_query_params: tuple = (),
        back_to_source_limit: int = 3,
    ) -> None:
        self.id = id
        self.url = url
        self.type = type
        self.digest = digest
        self.tag = tag
        self.application = application
        self.filtered_query_params = filtered_query_params
        self.content_length = -1
        self.total_piece_count = -1
        self.piece_size = 0
        self.direct_piece = b""  # TINY payload carried inline (task.go DirectPiece)
        self.back_to_source_limit = back_to_source_limit
        self.back_to_source_peers: set[str] = set()
        self.fsm = FSM(TASK_PENDING, TASK_EVENTS)
        self.dag: DAG[Peer] = DAG()
        self.pieces: Dict[int, Piece] = {}
        self._mu = threading.RLock()
        self.created_at = time.time()
        self.updated_at = self.created_at

    # -- peers / DAG --------------------------------------------------------

    def store_peer(self, peer: "Peer") -> None:
        with self._mu:
            if peer.id not in self.dag:
                self.dag.add_vertex(peer.id, peer)

    def load_peer(self, peer_id: str) -> Optional["Peer"]:
        with self._mu:
            if peer_id not in self.dag:
                return None
            return self.dag.get_vertex(peer_id).value

    def delete_peer(self, peer_id: str) -> None:
        with self._mu:
            if peer_id in self.dag:
                self.dag.delete_vertex(peer_id)

    def peer_count(self) -> int:
        with self._mu:
            return len(self.dag)

    def load_random_peers(self, n: int) -> List["Peer"]:
        """Uniform random peer sample (task.go:243 LoadRandomPeers)."""
        with self._mu:
            ids = self.dag.vertex_ids()
            random.shuffle(ids)
            return [self.dag.get_vertex(i).value for i in ids[:n]]

    def can_add_peer_edge(self, parent_id: str, child_id: str) -> bool:
        with self._mu:
            try:
                return self.dag.can_add_edge(parent_id, child_id)
            except DAGError:
                return False

    def add_peer_edge(self, parent: "Peer", child: "Peer") -> bool:
        """parent → child edge; consumes one of parent's upload slots
        (task.go:276-311 AddPeerEdge)."""
        with self._mu:
            try:
                self.dag.add_edge(parent.id, child.id)
            except DAGError:
                return False
        if not parent.host.acquire_upload():
            with self._mu:
                try:
                    self.dag.delete_edge(parent.id, child.id)
                except DAGError:
                    pass
            return False
        return True

    def delete_peer_in_edges(self, peer_id: str) -> None:
        """Detach peer from its parents, releasing their upload slots
        (task.go:313-340 DeletePeerInEdges)."""
        with self._mu:
            if peer_id not in self.dag:
                return
            vertex = self.dag.get_vertex(peer_id)
            parents = list(vertex.parents)
            self.dag.delete_vertex_in_edges(peer_id)
        for pv in parents:
            pv.value.host.release_upload(succeeded=True)

    def delete_peer_edge(self, parent: "Peer", child_id: str) -> bool:
        """Detach ONE parent→child edge, releasing that parent's upload
        slot — the selective form schedule_once needs to swap edge sets
        attach-first (old parents detach only after replacements hold)."""
        with self._mu:
            try:
                self.dag.delete_edge(parent.id, child_id)
            except DAGError:
                return False
        parent.host.release_upload(succeeded=True)
        return True

    def delete_peer_out_edges(self, peer_id: str) -> None:
        with self._mu:
            if peer_id not in self.dag:
                return
            vertex = self.dag.get_vertex(peer_id)
            n_children = len(vertex.children)
            self.dag.delete_vertex_out_edges(peer_id)
            peer = vertex.value
        for _ in range(n_children):
            peer.host.release_upload(succeeded=True)

    def peer_in_degree(self, peer_id: str) -> int:
        with self._mu:
            return self.dag.get_vertex(peer_id).in_degree()

    def peer_out_degree(self, peer_id: str) -> int:
        with self._mu:
            return self.dag.get_vertex(peer_id).out_degree()

    def load_parents(self, peer_id: str) -> List["Peer"]:
        with self._mu:
            v = self.dag.get_vertex(peer_id)
            return [p.value for p in v.parents]

    def load_children(self, peer_id: str) -> List["Peer"]:
        with self._mu:
            v = self.dag.get_vertex(peer_id)
            return [c.value for c in v.children]

    # -- pieces -------------------------------------------------------------

    def store_piece(self, piece: Piece) -> None:
        with self._mu:
            self.pieces[piece.number] = piece

    def load_piece(self, number: int) -> Optional[Piece]:
        with self._mu:
            return self.pieces.get(number)

    # -- scope / state ------------------------------------------------------

    def size_scope(self) -> SizeScope:
        if self.content_length < 0 or self.total_piece_count < 0:
            return SizeScope.UNKNOWN
        if self.content_length == EMPTY_FILE_SIZE:
            return SizeScope.EMPTY
        if self.content_length <= TINY_FILE_SIZE:
            return SizeScope.TINY
        if self.total_piece_count == 1:
            return SizeScope.SMALL
        return SizeScope.NORMAL

    def can_back_to_source(self) -> bool:
        return len(self.back_to_source_peers) <= self.back_to_source_limit

    def can_reuse_direct_piece(self) -> bool:
        return len(self.direct_piece) > 0 and len(self.direct_piece) == self.content_length

    def has_available_peer(self, blocklist: Optional[set] = None) -> bool:
        """Any peer that could serve as a parent (task.go HasAvailablePeer)."""
        blocklist = blocklist or set()
        with self._mu:
            peers = [self.dag.get_vertex(i).value for i in self.dag.vertex_ids()]
        for p in peers:
            if p.id in blocklist:
                continue
            if p.fsm.current in (PEER_SUCCEEDED, PEER_RUNNING, PEER_BACK_TO_SOURCE):
                return True
        return False

    def touch(self) -> None:
        self.updated_at = time.time()

    def to_record(self) -> schema.TaskRecord:
        return schema.TaskRecord(
            id=self.id,
            url=self.url,
            type=self.type,
            content_length=self.content_length,
            total_piece_count=max(self.total_piece_count, 0),
            back_to_source_limit=self.back_to_source_limit,
            back_to_source_peer_count=len(self.back_to_source_peers),
            state=self.fsm.current,
            created_at=int(self.created_at * 1e9),
            updated_at=int(self.updated_at * 1e9),
        )


class Peer:
    """One download of one task by one host (scheduler/resource/peer.go:137-201)."""

    def __init__(
        self,
        id: str,
        task: Task,
        host: Host,
        *,
        priority: Priority = Priority.LEVEL0,
        tag: str = "",
        application: str = "",
        tenant: str = "",
    ) -> None:
        self.id = id
        self.task = task
        self.host = host
        self.priority = priority
        self.tag = tag
        self.application = application
        # Tenant identity (DESIGN.md §26): stamped from the daemon's
        # declared/derived tenant at registration; "" = default tenant.
        self.tenant = tenant
        self.range: Optional[tuple] = None
        # Lock-free FSM-state mirrors for the vectorized serving gather:
        # `fsm.current` takes the FSM's RLock per read, which the rule
        # evaluator paid once per candidate per announce.  The mirrors
        # are written by the FSM's own enter_state callback (after the
        # transition commits) and read GIL-atomically — the same
        # different-instants snapshot consistency the scalar path's
        # per-candidate locked reads already had.  ``fsm_elevated``
        # pre-computes the host_type_score state test.
        self.fsm_state = PEER_PENDING
        self.fsm_elevated = False
        # Packed serving encoding (finished_piece_count << 1 | elevated),
        # maintained by finish_piece and the FSM mirror — the rule
        # gather reads ONE attribute per candidate (featcache.rule_serve).
        self._enc = 0
        self.fsm = FSM(
            PEER_PENDING, PEER_EVENTS, callbacks={"enter_state": self._mirror_fsm}
        )
        self._mu = threading.Lock()
        self.finished_pieces: set[int] = set()
        self.piece_costs_ns: List[int] = []
        # Pieces THIS peer downloaded, keyed by number, each attributed to the
        # parent that served it (the reference keeps peer.Pieces with ParentID,
        # service_v1.go:1505-1519 — the Download record's per-parent piece
        # costs come from the child's pieces, not the parent's own downloads).
        self.pieces: Dict[int, Piece] = {}
        self.block_parents: set[str] = set()
        self.need_back_to_source = False
        self.cost_ns = 0
        self.created_at = time.time()
        self.updated_at = self.created_at

    def _mirror_fsm(self, fsm, event: str, src: str, dst: str) -> None:
        self.fsm_state = dst
        elevated = dst in (PEER_RECEIVED_NORMAL, PEER_RUNNING)
        self.fsm_elevated = elevated
        self._enc = (len(self.finished_pieces) << 1) | elevated

    def append_piece_cost(self, cost_ns: int) -> None:
        with self._mu:
            self.piece_costs_ns.append(cost_ns)

    def piece_costs(self) -> List[int]:
        with self._mu:
            return list(self.piece_costs_ns)

    def finish_piece(
        self,
        number: int,
        cost_ns: int,
        *,
        parent_id: str = "",
        length: int = 0,
    ) -> bool:
        """Record a finished piece; False for a duplicate report.

        Idempotent: a retried report (wire client re-sending after a
        timeout) must not double-count the piece cost — callers use the
        return value to gate THEIR side effects (parent serve-cost
        evidence) on the first delivery only.
        """
        with self._mu:
            if number in self.finished_pieces:
                return False
            self.finished_pieces.add(number)
            self.piece_costs_ns.append(cost_ns)
            self.pieces[number] = Piece(
                number, parent_id=parent_id, length=length, cost_ns=cost_ns
            )
            self._enc = (len(self.finished_pieces) << 1) | self.fsm_elevated
        self.updated_at = time.time()
        return True

    def finished_piece_count(self) -> int:
        with self._mu:
            return len(self.finished_pieces)

    def snapshot_pieces(self) -> List[Piece]:
        """Consistent copy of this peer's downloaded pieces (insertion
        order) — the serving-path featurizer groups them by serving
        parent in one pass (evaluator.MLEvaluator._served_stats)."""
        with self._mu:
            return list(self.pieces.values())

    def is_done(self) -> bool:
        return self.fsm.current in (PEER_SUCCEEDED, PEER_FAILED, PEER_LEAVE)

    def touch(self) -> None:
        self.updated_at = time.time()

    def to_parent_record(self, child: Optional["Peer"] = None) -> schema.Parent:
        """Snapshot as a Download.parents[] entry (storage/types.go Parent).

        ``child`` is the downloading peer whose record this parent entry
        belongs to: the per-piece costs are the CHILD's pieces attributed to
        this parent (service_v1.go:1505-1519), so
        ``Parent.observed_bandwidth()`` measures the parent→child transfer.
        ``upload_piece_count`` is likewise the count of child pieces this
        parent served.
        """
        piece_size = self.task.piece_size or (4 << 20)
        pieces: List[schema.Piece] = []
        upload_piece_count = 0
        if child is not None:
            with child._mu:
                served = [p for p in child.pieces.values() if p.parent_id == self.id]
            upload_piece_count = len(served)
            pieces = [
                schema.Piece(
                    length=p.length or piece_size,
                    cost=p.cost_ns,
                    created_at=int(p.created_at * 1e9),
                )
                for p in served[: schema.MAX_PIECES_PER_PARENT]
            ]
        with self._mu:
            finished = len(self.finished_pieces)
        return schema.Parent(
            id=self.id,
            tag=self.tag,
            application=self.application,
            state=self.fsm.current,
            cost=self.cost_ns,
            upload_piece_count=upload_piece_count,
            finished_piece_count=finished,
            host=self.host.to_record(),
            pieces=pieces,
            created_at=int(self.created_at * 1e9),
            updated_at=int(self.updated_at * 1e9),
        )


# ---------------------------------------------------------------------------
# Managers (sync.Map + TTL GC in the reference)
# ---------------------------------------------------------------------------


class _TTLManager:
    def __init__(self, ttl: float) -> None:
        self._mu = threading.Lock()
        self._items: Dict[str, object] = {}
        self.ttl = ttl

    def load(self, key: str):
        with self._mu:
            return self._items.get(key)

    def store(self, key: str, value) -> None:
        with self._mu:
            self._items[key] = value

    def load_or_store(self, key: str, value):
        """Returns (existing_or_new, loaded)."""
        with self._mu:
            if key in self._items:
                return self._items[key], True
            self._items[key] = value
            return value, False

    def delete(self, key: str) -> None:
        with self._mu:
            self._items.pop(key, None)

    def items(self) -> list:
        with self._mu:
            return list(self._items.values())

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)


class HostManager(_TTLManager):
    """host_manager.go — reaps hosts idle past TTL (no announce)."""

    def __init__(self, ttl: float = 6 * 3600) -> None:
        super().__init__(ttl)

    def load_random_hosts(self, n: int, blocklist: Optional[set] = None) -> List[Host]:
        blocklist = blocklist or set()
        hosts = [h for h in self.items() if h.id not in blocklist]
        random.shuffle(hosts)
        return hosts[:n]

    def run_gc(self) -> int:
        now = time.time()
        reaped = 0
        for host in self.items():
            if now - host.updated_at > self.ttl and host.peer_count() == 0:
                self.delete(host.id)
                reaped += 1
            elif now - host.updated_at > self.ttl:
                host.leave_peers()
        return reaped


class TaskManager(_TTLManager):
    """task_manager.go — reaps tasks with no peers past TTL."""

    def __init__(self, ttl: float = 2 * 3600) -> None:
        super().__init__(ttl)

    def run_gc(self) -> int:
        now = time.time()
        reaped = 0
        for task in self.items():
            if task.peer_count() == 0 and now - task.updated_at > self.ttl:
                if task.fsm.can("Leave"):
                    task.fsm.event("Leave")
                self.delete(task.id)
                reaped += 1
        return reaped


class PeerManager(_TTLManager):
    """peer_manager.go — reaps finished/idle peers past TTL."""

    def __init__(self, ttl: float = 24 * 3600) -> None:
        super().__init__(ttl)

    def run_gc(self) -> int:
        now = time.time()
        reaped = 0
        for peer in self.items():
            idle = now - peer.updated_at
            if peer.fsm.current == PEER_LEAVE or (peer.is_done() and idle > self.ttl):
                peer.task.delete_peer_in_edges(peer.id)
                peer.task.delete_peer_out_edges(peer.id)
                peer.task.delete_peer(peer.id)
                peer.host.delete_peer(peer.id)
                self.delete(peer.id)
                reaped += 1
        return reaped


class Resource:
    """Composition of the three managers (scheduler/resource/resource.go:32-47)."""

    def __init__(
        self,
        *,
        host_ttl: float = 6 * 3600,
        task_ttl: float = 2 * 3600,
        peer_ttl: float = 24 * 3600,
    ) -> None:
        self.host_manager = HostManager(host_ttl)
        self.task_manager = TaskManager(task_ttl)
        self.peer_manager = PeerManager(peer_ttl)

    def store_host(self, host: Host) -> Host:
        existing, loaded = self.host_manager.load_or_store(host.id, host)
        return existing

    def store_task(self, task: Task) -> Task:
        existing, loaded = self.task_manager.load_or_store(task.id, task)
        return existing

    def store_peer(self, peer: Peer) -> Peer:
        existing, loaded = self.peer_manager.load_or_store(peer.id, peer)
        if not loaded:
            peer.task.store_peer(peer)
            peer.host.store_peer(peer)
        return existing

    def run_gc(self) -> dict:
        return {
            "peers": self.peer_manager.run_gc(),
            "tasks": self.task_manager.run_gc(),
            "hosts": self.host_manager.run_gc(),
        }
