"""Parent-peer evaluators: rule-based, network-topology, and ML.

Reference parity (scheduler/scheduling/evaluator/):
- algorithm dispatch by name default/nt/ml/plugin (evaluator.go:28-46,
  :76-90).  In the reference, ``ml`` is a TODO that falls back to the base
  evaluator (evaluator.go:84-86); here it is real.
- base scoring: 6 weighted features summing to 1.0 — finished-piece 0.2,
  upload-success 0.2, free-upload 0.15, host-type 0.15, IDC 0.15,
  location 0.15 (evaluator_base.go:28-45, evaluate :71-84).
- nt scoring: adds probe-RTT weight 0.12 and lowers host-type/IDC/location
  to 0.11 each; RTT is normalized against the 1 s ping timeout
  (evaluator_network_topology.go:30-56, :215-224).
- bad-node test: needs ≥2 piece-cost samples; <30 samples → last cost >
  20× mean of the rest; ≥30 → last cost > mean + 3σ (evaluator.go:92-129).

ML evaluator (the TPU-native design): instead of a Triton RPC per
scheduling decision (the reference's planned KServe client,
pkg/rpc/inference/client/client_v1.go:86-100), the trainer exports a
**local scorer** — model weights applied host-side via numpy (microsecond
cost, no RPC on the hot path).  See ``trainer/export.py`` for the scorer
artifact.  When no model is loaded the ML evaluator degrades to the base
rules, exactly like the reference's fallback.

Serving engine (DESIGN.md §14): ``evaluate_parents`` is the announce hot
path, so ranking runs **vectorized** — per-parent inputs are gathered
into arrays once and the weighted sum / featurization is numpy over all
candidates, with per-host feature rows served from ``HostFeatureCache``
and scorer calls optionally coalesced across concurrent announces by
``ScorerBatcher``.  The pre-vectorization scalar implementations are
kept verbatim as ``*_reference`` ordering oracles: the vectorized paths
are required (tests/test_sched_vectorized.py) to reproduce their
orderings byte-for-byte, including argsort tie-break stability.
"""

from __future__ import annotations

import functools
import logging
import statistics
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, List, Optional, Protocol, Sequence

import numpy as np

from ..records.features import EDGE_FEATURE_DIM as _EDGE_DIM
from ..records.features import edge_features as _edge_features
from ..records.features import edge_features_batch as _edge_features_batch
from ..records.features import host_features as _host_features
from ..records.schema import MAX_PIECES_PER_PARENT, Download
from ..utils.types import HostType
from . import metrics
from .featcache import HostFeatureCache
from .resource import (
    PEER_BACK_TO_SOURCE,
    PEER_FAILED,
    PEER_LEAVE,
    PEER_PENDING,
    PEER_RECEIVED_EMPTY,
    PEER_RECEIVED_NORMAL,
    PEER_RECEIVED_SMALL,
    PEER_RECEIVED_TINY,
    PEER_RUNNING,
    Peer,
)

if TYPE_CHECKING:
    from .microbatch import ScorerBatcher
    from .networktopology import NetworkTopology

logger = logging.getLogger(__name__)

DEFAULT_ALGORITHM = "default"
NETWORK_TOPOLOGY_ALGORITHM = "nt"
ML_ALGORITHM = "ml"

MAX_SCORE = 1.0
MIN_SCORE = 0.0

# Location affinity looks at up to 5 '|'-separated elements (evaluator.go maxElementLen).
MAX_ELEMENT_LEN = 5
# ≥30 cost samples ⇒ treat as normal distribution (evaluator.go normalDistributionLen).
NORMAL_DISTRIBUTION_LEN = 30
MIN_AVAILABLE_COST_LEN = 2

PING_TIMEOUT_NS = 1_000_000_000  # 1 s (evaluator_network_topology.go defaultPingTimeout)

_BAD_STATES = (
    PEER_FAILED,
    PEER_LEAVE,
    PEER_PENDING,
    PEER_RECEIVED_EMPTY,
    PEER_RECEIVED_TINY,
    PEER_RECEIVED_SMALL,
    PEER_RECEIVED_NORMAL,
)


def piece_score(parent: Peer, child: Peer, total_piece_count: int) -> float:
    if total_piece_count > 0:
        return parent.finished_piece_count() / total_piece_count
    return float(parent.finished_piece_count() - child.finished_piece_count())


def upload_success_score(parent: Peer) -> float:
    uploads = parent.host.upload_count
    failed = parent.host.upload_failed_count
    if uploads < failed:
        return MIN_SCORE
    if uploads == 0 and failed == 0:
        return MAX_SCORE  # never scheduled → try it first
    return (uploads - failed) / uploads


def free_upload_score(parent: Peer) -> float:
    limit = parent.host.concurrent_upload_limit
    free = parent.host.free_upload_count()
    if limit > 0 and free > 0:
        return free / limit
    return MIN_SCORE


def host_type_score(parent: Peer) -> float:
    """Seed peers win on first download (still fetching), dfdaemon peers
    otherwise (evaluator_base.go:126-143)."""
    if parent.host.type is not HostType.NORMAL:
        if parent.fsm.current in (PEER_RECEIVED_NORMAL, PEER_RUNNING):
            return MAX_SCORE
        return MIN_SCORE
    return MAX_SCORE * 0.5


def idc_affinity_score(dst: str, src: str) -> float:
    if not dst or not src:
        return MIN_SCORE
    return MAX_SCORE if dst.lower() == src.lower() else MIN_SCORE


@functools.lru_cache(maxsize=65536)
def location_affinity_score(dst: str, src: str) -> float:
    # lru_cache: the location vocabulary is small and recurs on every
    # announce; the split/lower loop showed up in the serving profile.
    if not dst or not src:
        return MIN_SCORE
    if dst.lower() == src.lower():
        return MAX_SCORE
    de, se = dst.split("|"), src.split("|")
    n = min(len(de), len(se), MAX_ELEMENT_LEN)
    score = 0
    for i in range(n):
        if de[i].lower() != se[i].lower():
            break
        score += 1
    return score / MAX_ELEMENT_LEN


# Label-bound histogram children per algorithm: label resolution paid
# once, not per announce (utils.metrics._HistogramChild).
_EVAL_SECONDS_CHILDREN: dict = {}


def _eval_seconds(algorithm: str):
    child = _EVAL_SECONDS_CHILDREN.get(algorithm)
    if child is None:
        child = _EVAL_SECONDS_CHILDREN[algorithm] = metrics.EVAL_SECONDS.labels(
            algorithm=algorithm
        )
    return child


# Piece-score weight for the columnar rule path (the host-side term
# weights are baked into the store's pre-scaled columns, featcache.py).
_W_PIECE = 0.2


class Evaluator:
    """Base (rule-based) evaluator + shared bad-node detection.

    ``evaluate`` (scalar, per-parent) is the semantic source of truth;
    ``evaluate_all`` computes the same weighted sum for ALL parents in
    one set of numpy expressions — identical operation order per
    element, so scores (and therefore orderings) match bit-for-bit.

    With a columnar host store attached (``feature_cache``, DESIGN.md
    §18), the host-side score terms come pre-scaled straight off the
    slot columns (one locked gather), and the only per-parent Python
    work left is one fromiter over the peers — the attribute gathers
    that kept ``vector_rule`` at ~1× are gone.  Without a store the
    PR-3 fromiter path is kept verbatim (NetworkTopologyEvaluator and
    storeless constructions still use it).
    """

    ALGORITHM = DEFAULT_ALGORITHM
    _feature_cache: Optional[HostFeatureCache] = None

    def __init__(self, feature_cache: Optional[HostFeatureCache] = None) -> None:
        self._feature_cache = feature_cache

    @property
    def feature_cache(self) -> Optional[HostFeatureCache]:
        return self._feature_cache

    def evaluate(self, parent: Peer, child: Peer, total_piece_count: int) -> float:
        return (
            0.2 * piece_score(parent, child, total_piece_count)
            + 0.2 * upload_success_score(parent)
            + 0.15 * free_upload_score(parent)
            + 0.15 * host_type_score(parent)
            + 0.15 * idc_affinity_score(parent.host.stats.network.idc, child.host.stats.network.idc)
            + 0.15
            * location_affinity_score(
                parent.host.stats.network.location, child.host.stats.network.location
            )
        )

    # -- vectorized scoring (the serving path) -------------------------------

    def _component_arrays(
        self, parents: Sequence[Peer], child: Peer, total_piece_count: int
    ):
        """The 6 base score components as float64 arrays, one entry per
        parent, each computed exactly like its scalar counterpart."""
        n = len(parents)
        # Direct field reads, not the locked accessors: a GIL-atomic
        # snapshot of an int is exactly as consistent as the scalar
        # path's lock-per-parent reads taken at 50 different instants,
        # and the lock round-trips dominated this gather's profile.
        # TWO gather passes total (one numeric, one for the python-scored
        # terms) — eight separate fromiter loops dominated the old one.
        child_idc = child.host.stats.network.idc
        child_loc = child.host.stats.network.location
        nums = np.fromiter(
            (
                (
                    len(p.finished_pieces),
                    p.host.upload_count,
                    p.host.upload_failed_count,
                    p.host.concurrent_upload_limit,
                    p.host.concurrent_upload_count,
                )
                for p in parents
            ),
            dtype=np.dtype((np.float64, 5)),
            count=n,
        )
        scored = np.fromiter(
            (
                (
                    host_type_score(p),
                    idc_affinity_score(p.host.stats.network.idc, child_idc),
                    location_affinity_score(
                        p.host.stats.network.location, child_loc
                    ),
                )
                for p in parents
            ),
            dtype=np.dtype((np.float64, 3)),
            count=n,
        )
        finished = nums[:, 0]
        uploads = nums[:, 1]
        failed = nums[:, 2]
        limit = nums[:, 3]
        free = limit - nums[:, 4]

        if total_piece_count > 0:
            ps = finished / total_piece_count
        else:
            ps = finished - float(child.finished_piece_count())

        us = np.where(
            uploads < failed,
            MIN_SCORE,
            np.where(
                (uploads == 0.0) & (failed == 0.0),
                MAX_SCORE,
                (uploads - failed) / np.maximum(uploads, 1.0),
            ),
        )
        fs = np.where(
            (limit > 0) & (free > 0), free / np.maximum(limit, 1.0), MIN_SCORE
        )
        return ps, us, fs, scored[:, 0], scored[:, 1], scored[:, 2]

    def evaluate_all(  # dflint: hotpath
        self, parents: Sequence[Peer], child: Peer, total_piece_count: int
    ) -> np.ndarray:
        """[n] float64 scores — one numpy expression over all parents,
        term order matching ``evaluate`` so every element is bit-equal.
        With a columnar host store attached the host-side terms are
        pre-scaled column gathers; fromiter fallback otherwise."""
        cache = self._feature_cache
        if cache is None:
            ps, us, fs, hts, idcs, locs = self._component_arrays(
                parents, child, total_piece_count
            )
            return (
                0.2 * ps + 0.2 * us + 0.15 * fs + 0.15 * hts + 0.15 * idcs + 0.15 * locs
            )
        return self._evaluate_all_columnar(cache, parents, child, total_piece_count)

    def _evaluate_all_columnar(  # dflint: hotpath
        self, cache: HostFeatureCache, parents, child: Peer, total_piece_count: int
    ) -> np.ndarray:
        """Columnar rule scoring: host terms come pre-scaled off the slot
        columns (``RuleGather``); the only per-parent Python pass reads
        the two PEER-side inputs (finished-piece count, FSM-state
        mirror).  Term order and every float product match ``evaluate``
        bit-for-bit: the pre-scaled columns are written with the exact
        per-host math the scalar path runs per call (featcache
        write-through), and multiplication/addition order is preserved
        below."""
        n = len(parents)
        if n == 0:
            return np.zeros(0, dtype=np.float64)
        # Steady state: one lock-free featcache call computes the whole
        # score vector (slot gather + pre-scaled adds) — see
        # HostFeatureCache.rule_scores for the seqlock discipline.
        score = cache.rule_scores(child, parents, total_piece_count)
        if score is not None:
            return score
        sv = cache.rule_serve(child.host, parents)
        enc = sv.peer_enc
        counts = enc >> 1
        if total_piece_count > 0:
            score = _W_PIECE * (counts / total_piece_count)
        else:
            score = _W_PIECE * (counts - child.finished_piece_count())
        # In-place adds: bitwise identical to out-of-place, half the
        # allocation churn on a path measured in numpy dispatches.  The
        # host-type term is a pairwise gather — column 2 + elevated bit
        # holds the exact scalar 0.15 * host_type_score product for that
        # (host type, peer state) combination (featcache fill).
        w = sv.w_host
        np.add(score, w[:, 0], out=score)
        np.add(score, w[:, 1], out=score)
        np.add(score, sv.w_ht, out=score)
        aff = sv.w_aff
        np.add(score, aff[:, 0], out=score)
        np.add(score, aff[:, 1], out=score)
        return score

    def evaluate_parents(  # dflint: hotpath
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        if len(parents) <= 1:
            return list(parents)
        t0 = time.perf_counter()
        # Steady-state shortcut: one lock-free featcache call computes
        # the whole score vector (rule_scores); evaluate_all covers every
        # other condition with identical bit-level results.
        cache = self._feature_cache
        scores = (
            cache.rule_scores(child, parents, total_piece_count)
            if cache is not None
            else None
        )
        if scores is None:
            scores = self.evaluate_all(parents, child, total_piece_count)
        # Stable descending sort == sorted(reverse=True): ties keep their
        # candidate-sample order on both paths.  The negation runs in
        # place (scores is this announce's private array) and the order
        # iterates as python ints — both measured on the announce path.
        np.negative(scores, out=scores)
        order = scores.argsort(kind="stable")
        _eval_seconds(self.ALGORITHM).observe(time.perf_counter() - t0)
        # order is a host-side numpy array (no device transfer): tolist
        # only converts to python ints for the C-level map/getitem.
        return list(map(parents.__getitem__, order.tolist()))  # dflint: disable=DF011

    def evaluate_parents_reference(
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        """Pre-vectorization scalar path, kept verbatim: the ordering
        oracle for the property tests and bench_sched's baseline."""
        return sorted(
            parents,
            key=lambda p: self.evaluate(p, child, total_piece_count),
            reverse=True,
        )

    # -- bad-node detection ---------------------------------------------------

    def is_bad_node(self, peer: Peer) -> bool:
        if peer.fsm.current in _BAD_STATES:
            return True
        costs = peer.piece_costs()
        n = len(costs)
        if n < MIN_AVAILABLE_COST_LEN:
            return False
        last = costs[-1]
        mean = statistics.fmean(costs[:-1])
        if n < NORMAL_DISTRIBUTION_LEN:
            return last > mean * 20
        stdev = statistics.pstdev(costs[:-1])
        return last > mean + 3 * stdev

    def is_bad_nodes(self, peers: Sequence[Peer]) -> np.ndarray:
        """[n] bool — ``is_bad_node`` for a whole candidate set with the
        cost statistics vectorized (segment reductions over one flat
        array instead of ``statistics`` per peer).  Equivalent to the
        scalar test; the 3σ threshold is computed with the numerically
        stable two-pass formula, so verdicts can differ from the scalar
        oracle only for a sample sitting within float rounding of the
        exact threshold (asserted equal over random populations in
        tests/test_sched_vectorized.py)."""
        n = len(peers)
        bad = np.zeros(n, dtype=bool)
        rows: List[int] = []
        lens: List[int] = []
        flat: List[int] = []
        for i, p in enumerate(peers):
            if p.fsm.current in _BAD_STATES:
                bad[i] = True
                continue
            costs = p.piece_costs()
            if len(costs) < MIN_AVAILABLE_COST_LEN:
                continue
            rows.append(i)
            lens.append(len(costs))
            flat.extend(costs)
        if not rows:
            return bad
        lens_a = np.asarray(lens, dtype=np.int64)
        flat_a = np.asarray(flat, dtype=np.float64)
        ends = np.cumsum(lens_a)
        starts = ends - lens_a
        last = flat_a[ends - 1]
        m = (lens_a - 1).astype(np.float64)
        head_sum = np.add.reduceat(flat_a, starts) - last
        mean = head_sum / m
        verdict = last > mean * 20
        big = lens_a >= NORMAL_DISTRIBUTION_LEN
        if np.any(big):
            centered = flat_a - np.repeat(mean, lens_a)
            centered[ends - 1] = 0.0  # the probe sample is not in the window
            sq = np.add.reduceat(centered * centered, starts)
            std = np.sqrt(sq / m)
            verdict = np.where(big, last > mean + 3 * std, verdict)
        bad[np.asarray(rows, dtype=np.int64)] = verdict
        return bad


class NetworkTopologyEvaluator(Evaluator):
    """Adds probe-RTT affinity (evaluator_network_topology.go)."""

    ALGORITHM = NETWORK_TOPOLOGY_ALGORITHM

    def __init__(self, networktopology: "NetworkTopology") -> None:
        self._nt = networktopology

    def _rtt_score(self, parent_host_id: str, child_host_id: str) -> float:
        rtt_ns = self._nt.average_rtt(parent_host_id, child_host_id)
        if rtt_ns is None:
            return MIN_SCORE
        return (PING_TIMEOUT_NS - rtt_ns) / PING_TIMEOUT_NS

    def evaluate(self, parent: Peer, child: Peer, total_piece_count: int) -> float:
        return (
            0.2 * piece_score(parent, child, total_piece_count)
            + 0.2 * upload_success_score(parent)
            + 0.15 * free_upload_score(parent)
            + 0.11 * host_type_score(parent)
            + 0.11 * idc_affinity_score(parent.host.stats.network.idc, child.host.stats.network.idc)
            + 0.11
            * location_affinity_score(
                parent.host.stats.network.location, child.host.stats.network.location
            )
            + 0.12 * self._rtt_score(parent.host.id, child.host.id)
        )

    def evaluate_all(  # dflint: hotpath
        self, parents: Sequence[Peer], child: Peer, total_piece_count: int
    ) -> np.ndarray:
        ps, us, fs, hts, idcs, locs = self._component_arrays(
            parents, child, total_piece_count
        )
        child_id = child.host.id
        rtts = np.fromiter(
            (self._rtt_score(p.host.id, child_id) for p in parents),
            np.float64,
            count=len(parents),
        )
        return (
            0.2 * ps
            + 0.2 * us
            + 0.15 * fs
            + 0.11 * hts
            + 0.11 * idcs
            + 0.11 * locs
            + 0.12 * rtts
        )


class EdgeScorer(Protocol):
    """What the trainer exports for the scheduler (trainer/export.py).

    Scores [n] candidate edges given featurized inputs; higher = better
    parent.  Implementations must be cheap (numpy, no device transfer) —
    this sits on the scheduling hot path — and must score each row
    independently of its batch-mates (the batched-score contract:
    ``ScorerBatcher`` pads and coalesces rows from concurrent announces
    into one call)."""

    def score(
        self,
        features: np.ndarray,
        *,
        src_buckets: Optional[np.ndarray] = None,
        dst_buckets: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """[n, DOWNLOAD_FEATURE_DIM] features (+ parent/child host hash
        buckets) → [n] scores. Feature-based scorers may ignore the
        buckets; identity-based scorers (GNN) may ignore the features and
        set ``wants_features = False`` to skip featurization entirely."""
        ...


class CanaryRoute:
    """Atomic canary routing state: one immutable object per (candidate
    scorer, percent, version), swapped whole by ``MLEvaluator.set_canary``
    — the same single-reference-read discipline as the scorer hot-swap,
    so an announce can never see half a canary config.

    Bucketing is deterministic per child host: ``crc32(host_id) % 100 <
    percent`` — a child stays on one arm for the whole canary (outcome
    attribution stays clean) and drills can predict the split."""

    __slots__ = ("scorer", "percent", "version")

    def __init__(self, scorer, percent: int, version: int) -> None:
        self.scorer = scorer
        self.percent = int(percent)
        self.version = int(version)

    def routes_to_candidate(self, host_id: str) -> bool:
        import zlib

        return (zlib.crc32(host_id.encode("utf-8")) % 100) < self.percent


class MLEvaluator(Evaluator):
    """Learned evaluator: ranks parents with the trainer's exported scorer.

    The reference reserved this slot (evaluator.go:84 `case MLAlgorithm:
    // TODO`) and planned a Triton round-trip; we featurize the candidate
    edges exactly like training rows (records/features.py) and apply the
    exported model locally.  No model → base-rule fallback, mirroring the
    reference's fallback behavior.

    Serving engine wiring: host feature rows come from a
    ``HostFeatureCache`` gather, edge features are computed in one
    vectorized pass, and — when a ``ScorerBatcher`` is attached —
    concurrent announces coalesce into one padded scorer call.  The
    scorer reference is read ONCE per evaluate (immutable snapshot), so
    ``ModelSubscriber.refresh`` hot-swapping mid-call can never fault the
    ranking; any scorer-path failure degrades to rule ranking instead of
    failing the announce.
    """

    ALGORITHM = ML_ALGORITHM
    _SERVED_CACHE_MAX = 4096

    def __init__(
        self,
        scorer: Optional[EdgeScorer] = None,
        *,
        feature_cache: Optional[HostFeatureCache] = None,
        batcher: Optional["ScorerBatcher"] = None,
    ) -> None:
        self._scorer = scorer
        # child peer id -> (piece count, served-piece groups); see
        # _served_groups.  Only touched from evaluate (GIL-serialized
        # dict ops on a private map).
        self._served_cache: "OrderedDict[str, tuple]" = OrderedDict()
        # `is None`, not `or`: an empty cache is len()==0 and falsy.
        self._feature_cache = (
            feature_cache if feature_cache is not None else HostFeatureCache()
        )
        self._batcher = batcher
        if batcher is not None:
            batcher.set_scorer(scorer)
        # Rollout plane (DESIGN.md §15): both references are read ONCE
        # per evaluate (atomic snapshot, like the scorer) and cost a
        # None-check when no rollout is in flight.
        self._shadow = None            # rollout.shadow.ShadowScorer
        self._canary: Optional[CanaryRoute] = None

    def set_scorer(self, scorer: Optional[EdgeScorer]) -> None:
        self._scorer = scorer
        if self._batcher is not None:
            self._batcher.set_scorer(scorer)

    # -- rollout plane (ModelSubscriber candidate poll) ----------------------

    def set_shadow(self, shadow) -> None:
        """Attach/detach the shadow comparison engine (None = off)."""
        self._shadow = shadow

    @property
    def shadow(self):
        return self._shadow

    def set_canary(self, route: Optional[CanaryRoute]) -> None:
        """Install/clear canary routing; the batcher gets the candidate
        scorer so canaried announces keep coalescing (per-arm groups)."""
        self._canary = route
        if self._batcher is not None:
            self._batcher.set_candidate(route.scorer if route else None)

    @property
    def canary(self) -> Optional[CanaryRoute]:
        return self._canary

    @property
    def has_model(self) -> bool:
        return self._scorer is not None

    @property
    def feature_cache(self) -> HostFeatureCache:
        return self._feature_cache

    @property
    def batcher(self) -> Optional["ScorerBatcher"]:
        return self._batcher

    # -- featurization --------------------------------------------------------

    def _served_groups(self, child: Peer, piece_size: int) -> dict:
        """parent-id → (truncated count, truncated length sum, full count)
        of the child's pieces attributed to that parent — ONE pass over
        the child's pieces instead of ``to_parent_record``'s scan per
        parent, mirroring the record's ``MAX_PIECES_PER_PARENT`` split.
        Memoized per child against its piece count: pieces only accrue
        during a download, so an unchanged count means unchanged groups
        (re-announces between piece finishes are the common case)."""
        n_pieces = len(child.pieces)  # GIL-atomic len read
        cached = self._served_cache.get(child.id)
        if cached is not None and cached[0] == n_pieces:
            # No move_to_end on hits: eviction order is least-recently-
            # REBUILT, which keeps active downloaders (their piece count
            # moves) and is race-free for concurrent announce threads.
            return cached[1]
        raw: dict = {}
        for pc in child.snapshot_pieces():
            raw.setdefault(pc.parent_id, []).append(pc.length or piece_size)
        groups = {}
        for parent_id, lens in raw.items():
            kept = lens[:MAX_PIECES_PER_PARENT]
            groups[parent_id] = (len(kept), sum(kept), len(lens))
        self._served_cache[child.id] = (n_pieces, groups)
        self._served_cache.move_to_end(child.id)
        while len(self._served_cache) > self._SERVED_CACHE_MAX:
            self._served_cache.popitem(last=False)
        return groups

    def _served_stats(self, child: Peer, parents: Sequence[Peer], piece_size: int):
        """Per-parent arrays of ``_served_groups`` for a candidate set."""
        groups = self._served_groups(child, piece_size)
        n = len(parents)
        trunc_counts = np.zeros(n, dtype=np.int64)
        trunc_lens = np.zeros(n, dtype=np.int64)
        full_counts = np.zeros(n, dtype=np.int64)
        if groups:
            for i, p in enumerate(parents):
                g = groups.get(p.id)
                if g is not None:
                    trunc_counts[i] = g[0]
                    trunc_lens[i] = g[1]
                    full_counts[i] = g[2]
        return trunc_counts, trunc_lens, full_counts

    def _featurize(  # dflint: hotpath
        self, parents: Sequence[Peer], child: Peer
    ) -> np.ndarray:
        """[n, DOWNLOAD_FEATURE_DIM] rows matching features.py layout
        (child host feats ++ parent host feats ++ edge feats): a cache
        serve (one fancy-index gather + vectorized affinity terms) + one
        vectorized edge-feature pass.  Byte-identical to
        ``_featurize_reference``."""
        return self._featurize_batch(parents, child)[0]

    def _edge_inputs(self, sv, parents: Sequence[Peer], child: Peer, n: int) -> dict:
        """The ``edge_features_batch`` kwargs for one candidate set —
        shared by the assembled-matrix featurizer and the fused
        slot-path featurizer.  ONE python pass for both per-peer reads
        (direct len() read — GIL-atomic, see _component_arrays)."""
        task = child.task
        piece_size = task.piece_size or (4 << 20)
        trunc_counts, trunc_lens, full_counts = self._served_stats(
            child, parents, piece_size
        )
        fin_cost = np.fromiter(
            ((len(p.finished_pieces), p.cost_ns) for p in parents),
            dtype=np.dtype((np.int64, 2)),
            count=n,
        )
        return dict(
            same_idc=sv.same_idc,
            location_affinity=sv.location_affinity,
            served_counts=trunc_counts,
            served_len_sums=trunc_lens,
            content_length=task.content_length,
            finished_piece_counts=fin_cost[:, 0],
            total_piece_count=max(task.total_piece_count, 0),
            cost_ns=fin_cost[:, 1],
            upload_piece_counts=full_counts,
        )

    def _featurize_batch(  # dflint: hotpath
        self, parents: Sequence[Peer], child: Peer
    ):
        """(_featurize rows, src hash buckets [n], child hash bucket) —
        buckets and the idc/location affinity terms all ride the cache's
        single-lock serve sweep (featcache.ServingGather)."""
        n = len(parents)
        sv = self._feature_cache.serve(child.host, [p.host for p in parents])
        kw = self._edge_inputs(sv, parents, child, n)
        h = sv.child_row.shape[0]
        out = np.empty((n, 2 * h + _EDGE_DIM), dtype=np.float32)
        out[:, :h] = sv.child_row
        out[:, h : 2 * h] = sv.rows
        # written in place, no temp + copy
        _edge_features_batch(out=out[:, 2 * h :], **kw)
        return out, sv.src_buckets, sv.dst_bucket

    def _featurize_slots(  # dflint: hotpath
        self, parents: Sequence[Peer], child: Peer
    ):
        """(edge block [n, E], parent slot ids, child slot id, buckets)
        for a fused gather+score scorer (ops/pallas_score.py): the host
        feature rows are NOT assembled host-side — the kernel gathers
        them from its device mirror of the slot matrix by slot id, so
        the per-announce host cost is the edge block alone.  Slot ids
        are None when the store served uncached (oversized set)."""
        n = len(parents)
        sv = self._feature_cache.serve(child.host, [p.host for p in parents])
        kw = self._edge_inputs(sv, parents, child, n)
        edge = _edge_features_batch(**kw)
        return edge, sv.src_slots, sv.child_slot, sv.src_buckets, sv.dst_bucket

    def _featurize_reference(self, parents: Sequence[Peer], child: Peer) -> np.ndarray:
        """Pre-vectorization featurizer, kept verbatim: one
        ``to_parent_record`` + ``np.concatenate`` per parent.  The
        byte-equality oracle for ``_featurize`` (property tests) and
        bench_sched's scalar baseline."""
        child_rec = child.host.to_record()
        child_f = _host_features(child_rec)
        # A lightweight Download shell so edge_features sees task context.
        dl = Download(task=child.task.to_record(), host=child_rec)
        rows = []
        for p in parents:
            parent_rec = p.to_parent_record(child)
            rows.append(
                np.concatenate(
                    [child_f, _host_features(parent_rec.host), _edge_features(dl, parent_rec)]
                )
            )
        # Raw features; the scorer artifact applies its own post-hoc mask
        # (MLPScorer.score) so the train/serve contract travels with it.
        return np.stack(rows).astype(np.float32)

    # -- ranking --------------------------------------------------------------

    def evaluate_parents(  # dflint: hotpath
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        scorer = self._scorer  # ONE snapshot: refresh() swaps can't race us
        if scorer is None or not parents:
            return super().evaluate_parents(parents, child, total_piece_count)
        if len(parents) == 1:
            return list(parents)
        t0 = time.perf_counter()
        # Canary routing: one snapshot read; with no rollout in flight
        # this is a None-compare and the path below is unchanged.  The
        # scorer that will score THIS announce (``engine``) is resolved
        # HERE, atomically with the route decision, and — for candidate
        # arms — carried into the batcher flush as a pinned snapshot: a
        # rollout transition mid-linger (e.g. float → quantized
        # candidate swap) can therefore never mix scorer snapshots
        # inside one coalesced call (tests/test_rollout.py).
        canary = self._canary
        use_candidate = False
        if canary is not None:
            use_candidate = canary.routes_to_candidate(child.host.id)
            metrics.CANARY_ANNOUNCES_TOTAL.inc(
                arm="candidate" if use_candidate else "active"
            )
        engine = canary.scorer if use_candidate else scorer
        shadow = self._shadow
        try:
            cache = self._feature_cache
            feats = None
            n = len(parents)
            if getattr(engine, "wants_slots", False) and shadow is None:
                # Fused gather+score: the scorer gathers host rows from
                # its device mirror of the slot matrix by slot id — only
                # the edge block is built host-side.  (With a shadow
                # engine attached the assembled path below runs instead:
                # the shadow comparison needs the full feature matrix.)
                edge, src_slots, child_slot, src_buckets, dst_bucket = (
                    self._featurize_slots(parents, child)
                )
                if src_slots is not None:
                    dst_slots = np.broadcast_to(np.int64(child_slot), (n,))
                    if self._batcher is not None:
                        # Slot-path requests ALWAYS pin their snapshot:
                        # the payload shape is scorer-specific, so a
                        # flush snapshot swap must not re-route them.
                        scores = np.asarray(
                            self._batcher.score(
                                edge,
                                src_buckets=src_slots,
                                dst_buckets=dst_slots,
                                candidate=use_candidate,
                                scorer=engine,
                                tenant=getattr(child, "tenant", ""),
                            )
                        )
                    else:
                        scores = np.asarray(
                            engine.score(
                                edge, src_buckets=src_slots, dst_buckets=dst_slots
                            )
                        )
                else:
                    # Store served uncached (oversized candidate set) —
                    # no slots exist; score the assembled rows with the
                    # scorer's reference path.
                    feats, src_buckets, dst_bucket = self._featurize_batch(
                        parents, child
                    )
                    scores = np.asarray(engine.score_rows(feats))
            else:
                # Identity-only scorers (GNN embedding lookup) skip
                # featurization — building the feature matrix is the
                # expensive part of this path.
                fused = getattr(engine, "wants_slots", False)
                if getattr(engine, "wants_features", True):
                    feats, src_buckets, dst_bucket = self._featurize_batch(
                        parents, child
                    )
                else:
                    feats = np.zeros((n, 0), dtype=np.float32)
                    src_buckets = np.fromiter(
                        (cache.bucket(p.host) for p in parents),
                        np.int64,
                        count=n,
                    )
                    dst_bucket = cache.bucket(child.host)
                # broadcast_to: the scorer only reads the buckets — no
                # per-announce materialized array.
                dst_buckets = np.broadcast_to(np.int64(dst_bucket), (n,))
                if fused:
                    # Fused scorer forced onto the assembled path (the
                    # shadow engine needs the full feature matrix):
                    # score via its reference path, off the batcher.
                    scores = np.asarray(engine.score_rows(feats))
                elif self._batcher is not None:
                    scores = np.asarray(
                        self._batcher.score(
                            feats,
                            src_buckets=src_buckets,
                            dst_buckets=dst_buckets,
                            candidate=use_candidate,
                            # Candidate arms pin the snapshot resolved
                            # with the route decision; active arms keep
                            # the flush-snapshot coalescing economics.
                            scorer=engine if use_candidate else None,
                            # Weighted-fair lane key (DESIGN.md §26).
                            tenant=getattr(child, "tenant", ""),
                        )
                    )
                else:
                    scores = np.asarray(
                        engine.score(
                            feats, src_buckets=src_buckets, dst_buckets=dst_buckets
                        )
                    )
        except Exception as exc:  # noqa: BLE001 — degrade to rules, never fail the announce
            logger.warning("ML scorer path failed (%s); ranking with rules", exc)
            return super().evaluate_parents(parents, child, total_piece_count)
        # Shadow comparison rides the arrays this announce already built
        # (zero extra featurization); only active-armed announces offer —
        # the comparison needs the ACTIVE scores as its baseline.  The
        # fused fast path never offers (feats is None) — it only engages
        # with no shadow attached.
        if shadow is not None and not use_candidate and feats is not None:
            dst_buckets = np.broadcast_to(np.int64(dst_bucket), (len(parents),))
            shadow.offer(child.host.id, feats, src_buckets, dst_buckets, scores)
        order = np.argsort(-scores, kind="stable")
        _eval_seconds(self.ALGORITHM).observe(time.perf_counter() - t0)
        return [parents[i] for i in order]

    def _evaluate_parents_reference(
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        """Pre-vectorization ML path (scalar featurize + direct scorer):
        the ordering oracle and bench_sched's scalar-ML baseline."""
        scorer = self._scorer
        if scorer is None or not parents:
            return self.evaluate_parents_reference(parents, child, total_piece_count)
        from ..records.features import host_bucket

        if getattr(scorer, "wants_features", True):
            feats = self._featurize_reference(parents, child)
        else:
            feats = np.zeros((len(parents), 0), dtype=np.float32)
        src_buckets = np.asarray([host_bucket(p.host.id) for p in parents], np.int64)
        dst_buckets = np.full(
            len(parents), host_bucket(child.host.id), dtype=np.int64
        )
        scores = np.asarray(
            scorer.score(feats, src_buckets=src_buckets, dst_buckets=dst_buckets)
        )
        order = np.argsort(-scores, kind="stable")
        return [parents[i] for i in order]


def new_evaluator(
    algorithm: str = DEFAULT_ALGORITHM,
    *,
    networktopology: Optional["NetworkTopology"] = None,
    scorer: Optional[EdgeScorer] = None,
    feature_cache: Optional[HostFeatureCache] = None,
    batcher: Optional["ScorerBatcher"] = None,
) -> Evaluator:
    """Algorithm dispatch (evaluator.go:76-90)."""
    if algorithm == NETWORK_TOPOLOGY_ALGORITHM and networktopology is not None:
        return NetworkTopologyEvaluator(networktopology)
    if algorithm == ML_ALGORITHM:
        return MLEvaluator(scorer, feature_cache=feature_cache, batcher=batcher)
    # The rule evaluator gets the columnar host store too (DESIGN.md
    # §18): with one attached, host-side score terms gather pre-scaled
    # off the slot columns instead of per-parent attribute reads.
    return Evaluator(feature_cache=feature_cache)
