"""Parent-peer evaluators: rule-based, network-topology, and ML.

Reference parity (scheduler/scheduling/evaluator/):
- algorithm dispatch by name default/nt/ml/plugin (evaluator.go:28-46,
  :76-90).  In the reference, ``ml`` is a TODO that falls back to the base
  evaluator (evaluator.go:84-86); here it is real.
- base scoring: 6 weighted features summing to 1.0 — finished-piece 0.2,
  upload-success 0.2, free-upload 0.15, host-type 0.15, IDC 0.15,
  location 0.15 (evaluator_base.go:28-45, evaluate :71-84).
- nt scoring: adds probe-RTT weight 0.12 and lowers host-type/IDC/location
  to 0.11 each; RTT is normalized against the 1 s ping timeout
  (evaluator_network_topology.go:30-56, :215-224).
- bad-node test: needs ≥2 piece-cost samples; <30 samples → last cost >
  20× mean of the rest; ≥30 → last cost > mean + 3σ (evaluator.go:92-129).

ML evaluator (the TPU-native design): instead of a Triton RPC per
scheduling decision (the reference's planned KServe client,
pkg/rpc/inference/client/client_v1.go:86-100), the trainer exports a
**local scorer** — model weights applied host-side via numpy (microsecond
cost, no RPC on the hot path).  See ``trainer/export.py`` for the scorer
artifact.  When no model is loaded the ML evaluator degrades to the base
rules, exactly like the reference's fallback.
"""

from __future__ import annotations

import statistics
from typing import TYPE_CHECKING, List, Optional, Protocol, Sequence

import numpy as np

from ..records.features import edge_features as _edge_features
from ..records.features import host_features as _host_features
from ..records.schema import Download
from ..utils.types import HostType
from .resource import (
    PEER_BACK_TO_SOURCE,
    PEER_FAILED,
    PEER_LEAVE,
    PEER_PENDING,
    PEER_RECEIVED_EMPTY,
    PEER_RECEIVED_NORMAL,
    PEER_RECEIVED_SMALL,
    PEER_RECEIVED_TINY,
    PEER_RUNNING,
    Peer,
)

if TYPE_CHECKING:
    from .networktopology import NetworkTopology

DEFAULT_ALGORITHM = "default"
NETWORK_TOPOLOGY_ALGORITHM = "nt"
ML_ALGORITHM = "ml"

MAX_SCORE = 1.0
MIN_SCORE = 0.0

# Location affinity looks at up to 5 '|'-separated elements (evaluator.go maxElementLen).
MAX_ELEMENT_LEN = 5
# ≥30 cost samples ⇒ treat as normal distribution (evaluator.go normalDistributionLen).
NORMAL_DISTRIBUTION_LEN = 30
MIN_AVAILABLE_COST_LEN = 2

PING_TIMEOUT_NS = 1_000_000_000  # 1 s (evaluator_network_topology.go defaultPingTimeout)

_BAD_STATES = (
    PEER_FAILED,
    PEER_LEAVE,
    PEER_PENDING,
    PEER_RECEIVED_EMPTY,
    PEER_RECEIVED_TINY,
    PEER_RECEIVED_SMALL,
    PEER_RECEIVED_NORMAL,
)


def piece_score(parent: Peer, child: Peer, total_piece_count: int) -> float:
    if total_piece_count > 0:
        return parent.finished_piece_count() / total_piece_count
    return float(parent.finished_piece_count() - child.finished_piece_count())


def upload_success_score(parent: Peer) -> float:
    uploads = parent.host.upload_count
    failed = parent.host.upload_failed_count
    if uploads < failed:
        return MIN_SCORE
    if uploads == 0 and failed == 0:
        return MAX_SCORE  # never scheduled → try it first
    return (uploads - failed) / uploads


def free_upload_score(parent: Peer) -> float:
    limit = parent.host.concurrent_upload_limit
    free = parent.host.free_upload_count()
    if limit > 0 and free > 0:
        return free / limit
    return MIN_SCORE


def host_type_score(parent: Peer) -> float:
    """Seed peers win on first download (still fetching), dfdaemon peers
    otherwise (evaluator_base.go:126-143)."""
    if parent.host.type is not HostType.NORMAL:
        if parent.fsm.current in (PEER_RECEIVED_NORMAL, PEER_RUNNING):
            return MAX_SCORE
        return MIN_SCORE
    return MAX_SCORE * 0.5


def idc_affinity_score(dst: str, src: str) -> float:
    if not dst or not src:
        return MIN_SCORE
    return MAX_SCORE if dst.lower() == src.lower() else MIN_SCORE


def location_affinity_score(dst: str, src: str) -> float:
    if not dst or not src:
        return MIN_SCORE
    if dst.lower() == src.lower():
        return MAX_SCORE
    de, se = dst.split("|"), src.split("|")
    n = min(len(de), len(se), MAX_ELEMENT_LEN)
    score = 0
    for i in range(n):
        if de[i].lower() != se[i].lower():
            break
        score += 1
    return score / MAX_ELEMENT_LEN


class Evaluator:
    """Base (rule-based) evaluator + shared bad-node detection."""

    def evaluate(self, parent: Peer, child: Peer, total_piece_count: int) -> float:
        return (
            0.2 * piece_score(parent, child, total_piece_count)
            + 0.2 * upload_success_score(parent)
            + 0.15 * free_upload_score(parent)
            + 0.15 * host_type_score(parent)
            + 0.15 * idc_affinity_score(parent.host.stats.network.idc, child.host.stats.network.idc)
            + 0.15
            * location_affinity_score(
                parent.host.stats.network.location, child.host.stats.network.location
            )
        )

    def evaluate_parents(
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        return sorted(
            parents,
            key=lambda p: self.evaluate(p, child, total_piece_count),
            reverse=True,
        )

    def is_bad_node(self, peer: Peer) -> bool:
        if peer.fsm.current in _BAD_STATES:
            return True
        costs = peer.piece_costs()
        n = len(costs)
        if n < MIN_AVAILABLE_COST_LEN:
            return False
        last = costs[-1]
        mean = statistics.fmean(costs[:-1])
        if n < NORMAL_DISTRIBUTION_LEN:
            return last > mean * 20
        stdev = statistics.pstdev(costs[:-1])
        return last > mean + 3 * stdev


class NetworkTopologyEvaluator(Evaluator):
    """Adds probe-RTT affinity (evaluator_network_topology.go)."""

    def __init__(self, networktopology: "NetworkTopology") -> None:
        self._nt = networktopology

    def _rtt_score(self, parent_host_id: str, child_host_id: str) -> float:
        rtt_ns = self._nt.average_rtt(parent_host_id, child_host_id)
        if rtt_ns is None:
            return MIN_SCORE
        return (PING_TIMEOUT_NS - rtt_ns) / PING_TIMEOUT_NS

    def evaluate(self, parent: Peer, child: Peer, total_piece_count: int) -> float:
        return (
            0.2 * piece_score(parent, child, total_piece_count)
            + 0.2 * upload_success_score(parent)
            + 0.15 * free_upload_score(parent)
            + 0.11 * host_type_score(parent)
            + 0.11 * idc_affinity_score(parent.host.stats.network.idc, child.host.stats.network.idc)
            + 0.11
            * location_affinity_score(
                parent.host.stats.network.location, child.host.stats.network.location
            )
            + 0.12 * self._rtt_score(parent.host.id, child.host.id)
        )


class EdgeScorer(Protocol):
    """What the trainer exports for the scheduler (trainer/export.py).

    Scores [n] candidate edges given featurized inputs; higher = better
    parent.  Implementations must be cheap (numpy, no device transfer) —
    this sits on the scheduling hot path.
    """

    def score(
        self,
        features: np.ndarray,
        *,
        src_buckets: Optional[np.ndarray] = None,
        dst_buckets: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """[n, DOWNLOAD_FEATURE_DIM] features (+ parent/child host hash
        buckets) → [n] scores. Feature-based scorers may ignore the
        buckets; identity-based scorers (GNN) may ignore the features and
        set ``wants_features = False`` to skip featurization entirely."""
        ...


class MLEvaluator(Evaluator):
    """Learned evaluator: ranks parents with the trainer's exported scorer.

    The reference reserved this slot (evaluator.go:84 `case MLAlgorithm:
    // TODO`) and planned a Triton round-trip; we featurize the candidate
    edges exactly like training rows (records/features.py) and apply the
    exported model locally.  No model → base-rule fallback, mirroring the
    reference's fallback behavior.
    """

    def __init__(self, scorer: Optional[EdgeScorer] = None) -> None:
        self._scorer = scorer

    def set_scorer(self, scorer: Optional[EdgeScorer]) -> None:
        self._scorer = scorer

    @property
    def has_model(self) -> bool:
        return self._scorer is not None

    def _featurize(self, parents: Sequence[Peer], child: Peer) -> np.ndarray:
        """Build [n, DOWNLOAD_FEATURE_DIM] rows matching features.py layout
        (child host feats ++ parent host feats ++ edge feats)."""
        child_rec = child.host.to_record()
        child_f = _host_features(child_rec)
        # A lightweight Download shell so edge_features sees task context.
        dl = Download(task=child.task.to_record(), host=child_rec)
        rows = []
        for p in parents:
            parent_rec = p.to_parent_record(child)
            rows.append(
                np.concatenate(
                    [child_f, _host_features(parent_rec.host), _edge_features(dl, parent_rec)]
                )
            )
        # Raw features; the scorer artifact applies its own post-hoc mask
        # (MLPScorer.score) so the train/serve contract travels with it.
        return np.stack(rows).astype(np.float32)

    def evaluate_parents(
        self, parents: List[Peer], child: Peer, total_piece_count: int
    ) -> List[Peer]:
        if self._scorer is None or not parents:
            return super().evaluate_parents(parents, child, total_piece_count)
        from ..records.features import host_bucket

        # Identity-only scorers (GNN embedding lookup) skip featurization —
        # building the feature matrix is the expensive part of this path.
        if getattr(self._scorer, "wants_features", True):
            feats = self._featurize(parents, child)
        else:
            feats = np.zeros((len(parents), 0), dtype=np.float32)
        src_buckets = np.asarray([host_bucket(p.host.id) for p in parents], np.int64)
        dst_buckets = np.full(
            len(parents), host_bucket(child.host.id), dtype=np.int64
        )
        scores = np.asarray(
            self._scorer.score(feats, src_buckets=src_buckets, dst_buckets=dst_buckets)
        )
        order = np.argsort(-scores, kind="stable")
        return [parents[i] for i in order]


def new_evaluator(
    algorithm: str = DEFAULT_ALGORITHM,
    *,
    networktopology: Optional["NetworkTopology"] = None,
    scorer: Optional[EdgeScorer] = None,
) -> Evaluator:
    """Algorithm dispatch (evaluator.go:76-90)."""
    if algorithm == NETWORK_TOPOLOGY_ALGORITHM and networktopology is not None:
        return NetworkTopologyEvaluator(networktopology)
    if algorithm == ML_ALGORITHM:
        return MLEvaluator(scorer)
    return Evaluator()
