"""Network-topology probe store + snapshotter (reference: scheduler/networktopology/).

The reference keeps the probe graph in Redis (adjacency hashes
``networktopology:<src>:<dst>``, capped probe lists, probed-count keys) with
a read-through TTL cache.  Here the store is an embedded, thread-safe
in-process KV with identical semantics — the scheduler is the only writer
in both designs, and dropping the Redis round-trips removes the hot-path
latency — plus a **columnar export** (src/dst/rtt arrays) that feeds the
GNN trainer directly.

Semantics preserved:
- per-edge probe queue capped at ``queue_length`` (probes.go:145-222),
  oldest dropped on overflow;
- moving-average RTT recomputed over the queue on enqueue with weight 0.1
  on the running average: ``avg = 0.1*avg + 0.9*rtt`` folded left-to-right
  (probes.go:38-39, :188-197) — heavily favoring fresh probes;
- per-destination probed-count incremented on enqueue (probes.go:216-219);
- ``find_probed_hosts``: sample 50 random hosts, return the
  ``probe_count`` least-probed (network_topology.go:47-48, :190-256);
- ``snapshot``: serialize the whole graph into NetworkTopologyRecord rows
  (capped dest hosts per record) written to record storage
  (network_topology.go:386-497).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..records import schema
from .resource import Host, HostManager

MOVING_AVERAGE_WEIGHT = 0.1  # probes.go defaultMovingAverageWeight
FIND_PROBED_CANDIDATE_HOSTS_LIMIT = 50  # network_topology.go:47-48
DEFAULT_PROBE_QUEUE_LENGTH = 5  # config/constants.go:112-115
DEFAULT_PROBE_COUNT = 5


@dataclass
class Probe:
    """One ICMP probe result (probes.go Probe)."""

    host_id: str  # destination host
    rtt_ns: int
    created_at: float = field(default_factory=time.time)


class _Edge:
    __slots__ = ("probes", "average_rtt_ns", "created_at", "updated_at")

    def __init__(self, queue_length: int) -> None:
        self.probes: Deque[Probe] = deque(maxlen=queue_length)
        self.average_rtt_ns: Optional[int] = None
        self.created_at = time.time()
        self.updated_at = self.created_at


@dataclass
class TopologyConfig:
    probe_queue_length: int = DEFAULT_PROBE_QUEUE_LENGTH
    probe_count: int = DEFAULT_PROBE_COUNT
    collect_interval: float = 2 * 3600.0  # snapshot cadence


class NetworkTopology:
    """The probe-graph store (network_topology.go NetworkTopology iface :55-88)."""

    def __init__(
        self,
        host_manager: Optional[HostManager] = None,
        config: Optional[TopologyConfig] = None,
    ) -> None:
        self.config = config or TopologyConfig()
        self._host_manager = host_manager
        self._mu = threading.RLock()
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self._probed_count: Dict[str, int] = {}

    # -- writes -------------------------------------------------------------

    def store(self, src_host_id: str, dest_host_id: str) -> None:
        """Ensure the edge exists (network_topology.go:172-186 Store)."""
        with self._mu:
            key = (src_host_id, dest_host_id)
            if key not in self._edges:
                self._edges[key] = _Edge(self.config.probe_queue_length)

    def enqueue_probe(self, src_host_id: str, dest_host_id: str, probe: Probe) -> None:
        """probes.go:145-222 Enqueue: capped queue + EMA + probed count."""
        with self._mu:
            key = (src_host_id, dest_host_id)
            edge = self._edges.get(key)
            if edge is None:
                edge = _Edge(self.config.probe_queue_length)
                self._edges[key] = edge
            edge.probes.append(probe)  # deque(maxlen) drops the oldest
            avg: Optional[float] = None
            for p in edge.probes:
                if avg is None:
                    avg = float(p.rtt_ns)
                else:
                    avg = avg * MOVING_AVERAGE_WEIGHT + p.rtt_ns * (1 - MOVING_AVERAGE_WEIGHT)
            edge.average_rtt_ns = int(avg) if avg is not None else None
            edge.updated_at = probe.created_at
            self._probed_count[dest_host_id] = self._probed_count.get(dest_host_id, 0) + 1

    def delete_host(self, host_id: str) -> None:
        """Drop all edges touching the host (network_topology.go DeleteHost)."""
        with self._mu:
            self._edges = {
                k: v for k, v in self._edges.items() if host_id not in k
            }
            self._probed_count.pop(host_id, None)

    # -- reads --------------------------------------------------------------

    def has(self, src_host_id: str, dest_host_id: str) -> bool:
        with self._mu:
            return (src_host_id, dest_host_id) in self._edges

    def average_rtt(self, src_host_id: str, dest_host_id: str) -> Optional[int]:
        with self._mu:
            edge = self._edges.get((src_host_id, dest_host_id))
            return edge.average_rtt_ns if edge else None

    def probes(self, src_host_id: str, dest_host_id: str) -> List[Probe]:
        with self._mu:
            edge = self._edges.get((src_host_id, dest_host_id))
            return list(edge.probes) if edge else []

    def probed_count(self, host_id: str) -> int:
        with self._mu:
            return self._probed_count.get(host_id, 0)

    def neighbours(self, src_host_id: str) -> List[str]:
        with self._mu:
            return [dst for (src, dst) in self._edges if src == src_host_id]

    def edge_count(self) -> int:
        with self._mu:
            return len(self._edges)

    def find_probed_hosts(self, host_id: str) -> List[Host]:
        """Least-probed of 50 random candidates (network_topology.go:190-256)."""
        if self._host_manager is None:
            return []
        candidates = self._host_manager.load_random_hosts(
            FIND_PROBED_CANDIDATE_HOSTS_LIMIT, blocklist={host_id}
        )
        if not candidates:
            return []
        if len(candidates) <= self.config.probe_count:
            return candidates
        with self._mu:
            counts = {h.id: self._probed_count.get(h.id, 0) for h in candidates}
            # First selection initializes the count (network_topology.go:228-234).
            for h in candidates:
                self._probed_count.setdefault(h.id, 0)
        candidates.sort(key=lambda h: counts[h.id])
        return candidates[: self.config.probe_count]

    # -- snapshot / export --------------------------------------------------

    def snapshot(self, max_dest_hosts: int = schema.MAX_DEST_HOSTS) -> List[schema.NetworkTopologyRecord]:
        """Whole-graph serialization to records (network_topology.go:386-497).

        Host metadata comes from the host manager when available; edges to
        unknown hosts still snapshot with bare IDs so no signal is lost.
        """
        with self._mu:
            by_src: Dict[str, List[Tuple[str, _Edge]]] = {}
            for (src, dst), edge in self._edges.items():
                if edge.average_rtt_ns is None:
                    continue
                by_src.setdefault(src, []).append((dst, edge))

        def topo_host(host_id: str, edge: Optional[_Edge] = None) -> schema.TopoHost:
            host = self._host_manager.load(host_id) if self._host_manager else None
            th = schema.TopoHost(id=host_id)
            if host is not None:
                th.type = host.type.name_str
                th.hostname = host.hostname
                th.ip = host.ip
                th.port = host.port
                th.network = host.stats.network
            if edge is not None:
                th.probes = schema.ProbeStats(
                    average_rtt=edge.average_rtt_ns or 0,
                    created_at=int(edge.created_at * 1e9),
                    updated_at=int(edge.updated_at * 1e9),
                )
            return th

        now = time.time_ns()
        records: List[schema.NetworkTopologyRecord] = []
        for src, dests in by_src.items():
            for i in range(0, len(dests), max_dest_hosts):
                chunk = dests[i : i + max_dest_hosts]
                records.append(
                    schema.NetworkTopologyRecord(
                        id=f"networktopology-{src[:16]}-{now}-{i}",
                        host=topo_host(src),
                        dest_hosts=[topo_host(d, e) for d, e in chunk],
                        created_at=now,
                    )
                )
        return records

    # -- durability + cross-replica sharing (the Redis analog) ---------------
    #
    # The reference's probe graph lives in Redis (network_topology.go:55-88,
    # pkg/redis): it survives scheduler restarts and is readable by every
    # replica.  Here durability is a JSON state file per scheduler
    # (save/load below) and sharing rides the manager: each scheduler
    # pushes its edge summaries and pulls the other replicas' (scheduler/
    # topology_sync.py), merged newest-wins into the live store.

    def export_state(self) -> dict:
        """Full-fidelity state (probe queues + counts) for save/load."""
        with self._mu:
            return {
                "edges": [
                    {
                        "src": src, "dst": dst,
                        "average_rtt_ns": e.average_rtt_ns,
                        "created_at": e.created_at,
                        "updated_at": e.updated_at,
                        "probes": [
                            {"host_id": p.host_id, "rtt_ns": p.rtt_ns,
                             "created_at": p.created_at}
                            for p in e.probes
                        ],
                    }
                    for (src, dst), e in self._edges.items()
                ],
                "probed_count": dict(self._probed_count),
            }

    def import_state(self, state: dict) -> int:
        """Restore a saved state (restart reload); returns edges loaded."""
        edges = state.get("edges", [])
        with self._mu:
            for rec in edges:
                edge = _Edge(self.config.probe_queue_length)
                for p in rec.get("probes", []):
                    edge.probes.append(Probe(
                        host_id=p["host_id"], rtt_ns=int(p["rtt_ns"]),
                        created_at=float(p.get("created_at", 0.0)),
                    ))
                edge.average_rtt_ns = rec.get("average_rtt_ns")
                edge.created_at = float(rec.get("created_at", time.time()))
                edge.updated_at = float(rec.get("updated_at", edge.created_at))
                self._edges[(rec["src"], rec["dst"])] = edge
            for host_id, count in state.get("probed_count", {}).items():
                self._probed_count[host_id] = max(
                    self._probed_count.get(host_id, 0), int(count)
                )
        return len(edges)

    def save(self, path: str) -> None:
        import json
        import os
        import threading as _threading

        # Per-writer tmp name: even if two savers ever coexist, each
        # os.replace installs a COMPLETE document (no interleaved writes).
        tmp = f"{path}.{os.getpid()}.{_threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.export_state(), f)
        os.replace(tmp, path)

    def load(self, path: str) -> int:
        """Reload a persisted probe graph; 0 when absent/corrupt — a bad
        state file must degrade to an empty graph, never a boot crash."""
        import json

        try:
            with open(path) as f:
                state = json.load(f)
            return self.import_state(state)
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            return 0

    def export_edges(self) -> List[dict]:
        """Edge summaries for cross-replica sharing (no probe queues —
        replicas need the averaged signal, not the raw samples)."""
        with self._mu:
            return [
                {
                    "src": src, "dst": dst,
                    "average_rtt_ns": e.average_rtt_ns,
                    "updated_at": e.updated_at,
                }
                for (src, dst), e in self._edges.items()
                if e.average_rtt_ns is not None
            ]

    def merge_remote_edges(self, edges: List[dict]) -> int:
        """Adopt another replica's edge summaries, newest-wins; local
        probe queues and probed counts stay untouched (remote knowledge
        must not skew THIS scheduler's probe-target selection).  Returns
        the number of edges adopted."""
        adopted = 0
        with self._mu:
            for rec in edges:
                avg = rec.get("average_rtt_ns")
                src, dst = rec.get("src"), rec.get("dst")
                # Skip malformed records — one bad replica's push must not
                # kill sharing for the whole cluster.
                if avg is None or not src or not dst:
                    continue
                key = (src, dst)
                updated = float(rec.get("updated_at", 0.0))
                edge = self._edges.get(key)
                if edge is None:
                    edge = _Edge(self.config.probe_queue_length)
                    self._edges[key] = edge
                elif edge.updated_at >= updated:
                    continue  # local knowledge is fresher
                edge.average_rtt_ns = int(avg)
                edge.updated_at = updated
                adopted += 1
        return adopted

    def to_edge_arrays(self) -> Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]:
        """Columnar export for the GNN: (host_ids, src_idx, dst_idx, rtt_ns).

        This is the TPU-side replacement for the reference's CSV snapshot →
        trainer path: the probe graph leaves the scheduler already in
        index/array form, ready for static-shape batching.
        """
        with self._mu:
            edges = [
                (src, dst, e.average_rtt_ns)
                for (src, dst), e in self._edges.items()
                if e.average_rtt_ns is not None
            ]
        ids: Dict[str, int] = {}
        for src, dst, _ in edges:
            for h in (src, dst):
                if h not in ids:
                    ids[h] = len(ids)
        src_idx = np.array([ids[s] for s, _, _ in edges], dtype=np.int32)
        dst_idx = np.array([ids[d] for _, d, _ in edges], dtype=np.int32)
        rtt = np.array([r for _, _, r in edges], dtype=np.float32)
        return list(ids.keys()), src_idx, dst_idx, rtt


class ProbeAgent:
    """Daemon-side probe loop (reference: client/daemon/networktopology/).

    The reference daemon syncs with the scheduler over a ``SyncProbes``
    stream, pings the returned candidates with ICMP in parallel, and
    reports RTTs (network_topology.go:72-210).  In-process, the agent asks
    the store for candidates and reports simulated/measured RTTs via a
    pluggable ping function — the e2e swarm simulator injects ground-truth
    RTT; a real deployment injects pkg/net/ping-style ICMP.
    """

    def __init__(
        self,
        host: Host,
        topology: NetworkTopology,
        ping,  # Callable[[Host], Optional[int]] → rtt_ns or None on timeout
    ) -> None:
        self.host = host
        self.topology = topology
        self._ping = ping

    def sync_probes(self) -> int:
        """One probe round; returns the number of successful probes."""
        targets = self.topology.find_probed_hosts(self.host.id)
        ok = 0
        for target in targets:
            rtt_ns = self._ping(target)
            if rtt_ns is None:
                continue
            self.topology.store(self.host.id, target.id)
            self.topology.enqueue_probe(
                self.host.id, target.id, Probe(host_id=target.id, rtt_ns=int(rtt_ns))
            )
            ok += 1
        return ok
