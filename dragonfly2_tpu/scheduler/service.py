"""Scheduler service layer: peer lifecycle handling + training-record birth.

Transport-neutral port of the reference's gRPC handler logic
(scheduler/service/service_v1.go, service_v2.go).  The daemon (or the
in-process swarm simulator) calls these methods where the reference
demuxes stream messages:

- ``register_peer``       — service_v2.go:866 handleRegisterPeerRequest /
  service_v1.go:95 RegisterPeerTask: load-or-create host/task/peer, FSM
  register event by size scope, schedule.
- ``report_piece_finished`` — service_v2.go:1157: piece cost bookkeeping
  on the child peer (parent-attributed — the training signal).
- ``report_peer_finished``  — service_v1.go:1284 handlePeerSuccess →
  :1418 createDownloadRecord: FSM success + **Download record written to
  storage** (the row the trainer trains on; v1 is the only record-writing
  path in the reference too).
- ``report_peer_failed``   — FSM failure + reschedule bookkeeping.
- ``leave_peer`` / ``leave_host`` — teardown.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Set

from ..records import schema
from ..records.storage import Storage
from ..utils import idgen
from ..utils.fsm import FSM, InvalidEventError
from ..utils.types import TINY_FILE_SIZE, Priority, SizeScope
from . import metrics
from .networktopology import NetworkTopology, Probe
from .resource import Host, Peer, Piece, Resource, Task
from .scheduling import ScheduleResult, ScheduleResultKind, Scheduling

logger = logging.getLogger(__name__)


def _try_event(fsm: FSM, name: str) -> bool:
    """Fire an event if currently legal, atomically.

    ``if fsm.can(x): fsm.event(x)`` is check-then-act — under the wire
    binding two handler threads race it and the loser crashes the RPC with
    InvalidEventError.  The FSM's own event() is atomic; losing the race
    is a legal no-op here (the state the event wanted is already reached
    or superseded).
    """
    try:
        fsm.event(name)
        return True
    except InvalidEventError:
        return False


@dataclass
class RegisterResult:
    peer: Peer
    size_scope: SizeScope
    schedule: Optional[ScheduleResult] = None
    direct_piece: bytes = b""


class SchedulerService:
    """The composition the rpcserver binds (scheduler/scheduler.go:69-301)."""

    def __init__(
        self,
        resource: Resource,
        scheduling: Scheduling,
        storage: Optional[Storage] = None,
        networktopology: Optional[NetworkTopology] = None,
        *,
        seed_peer_trigger=None,
        hub=None,
        shard_guard=None,
    ) -> None:
        self.resource = resource
        self.scheduling = scheduling
        self.storage = storage
        self.networktopology = networktopology
        # Optional sharding.ShardGuard: ownership + admission checks at
        # the task-scoped entry points (DESIGN.md §24).  The guard needs
        # the resource to sweep live tasks on a membership change.
        self.shard_guard = shard_guard
        if shard_guard is not None:
            shard_guard.resource = resource
        # Optional callable(url, task_id) -> bool: asks a seed peer to warm
        # the task (resource/seed_peer.go:93-229 TriggerDownloadTask; wired
        # to a seed daemon's conductor in-process, an RPC in deployments).
        self.seed_peer_trigger = seed_peer_trigger
        # Optional PeerStreamHub (push.py): when a peer is connected over
        # the bidi wire, scheduling decisions made OUTSIDE its own request
        # cycle (bad-parent ejection, parent death, stalls) are pushed down
        # its stream (service_v2.go:89-207 stream.Send semantics).
        self.hub = hub
        self._mu = threading.Lock()
        self._seed_triggered: set = set()  # task ids already warmed
        self._gauges_refreshed_at = float("-inf")
        # Columnar host store (DESIGN.md §18): when the evaluator carries
        # one, announce decode binds hosts on arrival so their serving
        # state lives in slot columns from birth and the evaluate path
        # never marshals objects into the matrix.
        self._host_store = getattr(scheduling.evaluator, "feature_cache", None)
        # Tenant QoS policy (DESIGN.md §26): installed via dynconfig
        # (set_qos_policy) and re-published on announce answers so
        # daemons converge on it without their own manager dependency
        # (the §24 ring re-publication discipline).
        self.qos_policy = None

    # -- tenant QoS (DESIGN.md §26) ------------------------------------------

    def set_qos_policy(self, policy) -> None:
        """Install a ``qos.QoSPolicy`` across this scheduler's
        enforcement points: admission accounting (per-tenant caps +
        over-quota shedding) and the scorer batcher's DRR weights."""
        self.qos_policy = policy
        guard = self.shard_guard
        if guard is not None and guard.admission is not None:
            acct = guard.admission.accounting
            if acct is None:
                from ..qos.accounting import TenantAccounting

                guard.admission.accounting = TenantAccounting(policy)
            else:
                acct.set_policy(policy)
        batcher = getattr(self.scheduling.evaluator, "batcher", None)
        if batcher is not None:
            batcher.set_qos_policy(policy)

    def on_qos_config(self, config: dict) -> None:
        """Dynconfig observer: adopt the manager-published ``tenant_qos``
        blob.  Malformed payloads are skipped (an observer exception
        would take down the dynconfig refresh for every observer)."""
        payload = config.get("tenant_qos")
        if not isinstance(payload, dict) or not payload:
            return
        from ..qos.policy import QoSPolicy

        try:
            self.set_qos_policy(QoSPolicy.from_payload(payload))
        except (KeyError, TypeError, ValueError):
            logger.warning("ignoring malformed tenant_qos payload")

    # -- registration -------------------------------------------------------

    def register_peer(
        self,
        *,
        host: Host,
        url: str,
        peer_id: Optional[str] = None,
        task_id: Optional[str] = None,
        priority: Priority = Priority.LEVEL0,
        tag: str = "",
        application: str = "",
        tenant: str = "",
        blocklist: Optional[Set[str]] = None,
    ) -> RegisterResult:
        if self.shard_guard is not None:
            # Ownership before any state is created: a mis-routed
            # register must steer to the owner, not seed a split-brain
            # swarm here.  Admission next — the noisy tenant's lowest
            # priority band sheds first (DESIGN.md §26).
            self.shard_guard.check_task(task_id or idgen.task_id(url))
            self.shard_guard.admit(priority, tenant=tenant)
        host = self.resource.store_host(host)
        freshly_bound = False
        if self._host_store is not None:
            # Columnar from birth: registration is an announce — the
            # host's serving state moves into the slot columns NOW, so
            # the evaluate path finds a bound host (pure gather, no
            # object→matrix marshalling).
            freshly_bound = self._host_store.adopt(host)
        # A fresh bind just filled the row from these stats; stamp
        # freshness instead of paying a second identical fill.
        if freshly_bound:
            host.touch_stamp()
        else:
            host.touch()
        tid = task_id or idgen.task_id(url)
        task = self.resource.store_task(Task(tid, url, tag=tag, application=application))
        task.touch()
        peer = Peer(
            peer_id or idgen.peer_id(host.ip, host.hostname),
            task,
            host,
            priority=priority,
            tag=tag,
            application=application,
            tenant=tenant,
        )
        # Resource.store_peer inserts into the task DAG and host peer map
        # for newly created peers — single insertion point.
        peer = self.resource.store_peer(peer)

        _try_event(task.fsm, "Download")

        scope = task.size_scope()
        # _try_event: a retried registration (same client-generated peer_id
        # re-sent after a wire timeout) finds the peer already registered —
        # the event is then a legal no-op, not an error.
        if scope is SizeScope.EMPTY:
            _try_event(peer.fsm, "RegisterEmpty")
            metrics.REGISTER_PEER_TOTAL.inc(result="ok")
            self._refresh_gauges()
            return RegisterResult(peer=peer, size_scope=scope)
        if scope is SizeScope.TINY and task.can_reuse_direct_piece():
            _try_event(peer.fsm, "RegisterTiny")
            metrics.REGISTER_PEER_TOTAL.inc(result="ok")
            self._refresh_gauges()
            return RegisterResult(
                peer=peer, size_scope=scope, direct_piece=task.direct_piece
            )
        if scope is SizeScope.SMALL:
            _try_event(peer.fsm, "RegisterSmall")
        else:
            _try_event(peer.fsm, "RegisterNormal")
        schedule = self.scheduling.schedule_candidate_parents(peer, blocklist)
        if (
            schedule.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE
            and self.seed_peer_trigger is not None
            and not task.has_available_peer()
            # A SEED registering a cold task IS the warm-up — triggering
            # for it would call back into the very daemon that is mid-
            # register (its conductor dedups same-task downloads, so the
            # nested obtain would join the blocked run: a trigger↔register
            # deadlock until both sides' timeouts unwind).  Seeds go
            # straight to source; only normal peers get a seed warmed.
            and not host.type.is_seed
        ):
            # Cold task: warm a seed peer first, then reschedule once —
            # the child gets a parent instead of hitting the origin
            # (service_v2.go:1370 downloadTaskBySeedPeer).  Once per task,
            # claimed under the lock: the seed's OWN registration re-enters
            # this path (observed: unbounded recursive triggering without
            # the claim), and concurrent cold registrations must not launch
            # duplicate seed downloads.  The trigger is synchronous here
            # (in-process seed); the wire deployment should pass an async
            # trigger and rely on the client's reschedule-on-piece-failure.
            with self._mu:
                first = task.id not in self._seed_triggered
                if first:
                    self._seed_triggered.add(task.id)
            triggered = False
            if first:
                try:
                    triggered = self.seed_peer_trigger(task.url, task.id)
                except Exception as exc:  # noqa: BLE001 — trigger failure → back-to-source
                    logger.warning("seed trigger for %s failed: %s", task.id, exc)
                    triggered = False
            if triggered:
                schedule = self.scheduling.schedule_candidate_parents(peer, blocklist)
        metrics.SCHEDULE_TOTAL.inc(outcome=schedule.kind.name.lower())
        metrics.SCHEDULE_RETRIES.observe(schedule.retries)
        metrics.REGISTER_PEER_TOTAL.inc(result="ok")
        self._refresh_gauges()
        if schedule.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
            task.back_to_source_peers.add(peer.id)
            _try_event(peer.fsm, "DownloadBackToSource")
        elif schedule.kind is ScheduleResultKind.PARENTS:
            _try_event(peer.fsm, "Download")
        return RegisterResult(peer=peer, size_scope=scope, schedule=schedule)

    def announce_host(self, host: Host, *, tenant: str = "") -> Host:
        """Host stats announce (service_v2 AnnounceHost): store-or-refresh
        the host record and WRITE ITS COLUMNS on arrival (DESIGN.md §18)
        — the announce decode is the marshalling point, not the evaluate
        path.  Both wire adapters and the in-process
        ``daemon.host_announcer`` land here.  ``tenant`` feeds the
        per-tenant accounting + announce-rate caps (DESIGN.md §26)."""
        t0 = time.monotonic()
        if self.shard_guard is not None:
            # Host-scoped: every shard accepts announces (each keeps its
            # own host inventory) — only the shed gate applies (tenant
            # announce caps included), and the handling latency feeds
            # the shard's windowed burn signal.
            self.shard_guard.admit(Priority.LEVEL0, tenant=tenant)
        stored = self.resource.store_host(host)
        if stored is not host:
            # Refresh announce-time stats AND addresses on the existing
            # record — a restarted daemon announces a fresh download_port
            # and children must not be handed the dead one.
            stored.stats = host.stats
            stored.concurrent_upload_limit = host.concurrent_upload_limit
            stored.ip = host.ip
            stored.port = host.port
            stored.download_port = host.download_port
        freshly_bound = False
        if self._host_store is not None:
            freshly_bound = self._host_store.adopt(stored)
        # touch() on a bound host recomputes the whole slot row in place
        # (the stats just changed) — the announce pays the marshalling
        # once so every subsequent serve is a pure fancy-index.  When
        # the adopt above BOUND the host, the bind already computed the
        # row from these stats: only the freshness stamp remains (the
        # double fill cost cold announces ~2× at fleet scale).
        if freshly_bound:
            stored.touch_stamp()
        else:
            stored.touch()
        # Announce-handling latency into the mergeable sketch (DESIGN.md
        # §23) — the fleet-scale scheduler's announces/sec signal rides
        # the crash-safe journal, not the per-process scrape.
        metrics.ANNOUNCE_SECONDS.observe(time.monotonic() - t0)
        if self.shard_guard is not None and self.shard_guard.admission is not None:
            self.shard_guard.admission.observe(time.monotonic() - t0)
        return stored

    # Lifecycle gauges refresh at most this often: every register/leave
    # used to take all three resource-manager locks just to re-publish
    # sizes — pure overhead at 100k-peer announce rates.
    _GAUGE_REFRESH_S = 0.5

    def _refresh_gauges(self) -> None:  # dflint: hotpath
        now = time.monotonic()
        if now - self._gauges_refreshed_at < self._GAUGE_REFRESH_S:
            return
        # Benign race: two concurrent refreshes both publish CURRENT
        # sizes; the stamp write is a plain store either way.
        self._gauges_refreshed_at = now
        metrics.HOSTS_GAUGE.set(len(self.resource.host_manager))
        metrics.PEERS_GAUGE.set(len(self.resource.peer_manager))
        metrics.TASKS_GAUGE.set(len(self.resource.task_manager))

    def set_task_info(
        self,
        peer: Peer,
        content_length: int,
        total_piece_count: int,
        piece_size: int,
    ) -> None:
        """First peer reports origin metadata (the reference carries this on
        RegisterPeerTask / piece results)."""
        task = peer.task
        with self._mu:
            if task.content_length < 0:
                task.content_length = content_length
                task.total_piece_count = total_piece_count
                task.piece_size = piece_size

    def set_task_direct_piece(self, peer: Peer, data: bytes) -> None:
        """First peer of a TINY task publishes the content inline; later
        registrations get the bytes in the response instead of scheduling
        (task.go DirectPiece / service_v1 tiny shortcut)."""
        task = peer.task
        with self._mu:
            if (
                not task.direct_piece
                and 0 < len(data) <= TINY_FILE_SIZE
                and len(data) == task.content_length
            ):
                # Must cover the WHOLE content (can_reuse_direct_piece
                # compares lengths) — a short read would poison the slot.
                task.direct_piece = data

    def mark_back_to_source(self, peer: Peer) -> None:
        """Peer fell back to origin download (conductor's source path)."""
        _try_event(peer.fsm, "DownloadBackToSource")
        peer.task.back_to_source_peers.add(peer.id)
        # peer.go:270-279 (PeerEventDownloadBackToSource callback): the
        # abandoned parent assignments release their upload slots.
        peer.task.delete_peer_in_edges(peer.id)

    # -- piece / peer results ----------------------------------------------

    def report_piece_finished(
        self,
        peer: Peer,
        piece_number: int,
        *,
        parent_id: str = "",
        length: int = 0,
        cost_ns: int = 0,
    ) -> None:
        """DownloadPieceFinishedRequest (service_v2.go:1157)."""
        if self.shard_guard is not None:
            # A handed-off task's in-flight reports steer to the new
            # owner instead of mutating a swarm this shard gave away.
            self.shard_guard.check_task(peer.task.id)
        metrics.PIECE_RESULT_TOTAL.inc(result="finished")
        is_new = peer.finish_piece(
            piece_number, cost_ns, parent_id=parent_id, length=length
        )
        peer.task.store_piece(
            Piece(piece_number, parent_id=parent_id, length=length, cost_ns=cost_ns)
        )
        if not is_new or not parent_id:
            # Retried report (wire client re-sent after a timeout): the
            # child side already deduped; the parent-side serve evidence
            # must not double-count either.
            return
        # Serve-side evidence: the observed piece cost describes the PARENT
        # as a server; it feeds the same 3σ/20×-mean bad-node test the
        # evaluator runs on candidates (evaluator.go:92-129).  Appended on
        # every transport so is_bad_node sees identical inputs whether or
        # not a push hub is attached.
        parent = self.resource.peer_manager.load(parent_id)
        if parent is None:
            return
        parent.append_piece_cost(cost_ns)
        # Bad-parent ejection push: the cost just appended may tip the
        # parent over the test — if so, every *connected* child gets fresh
        # candidates pushed, before any of them fails a piece.
        if self.hub is not None and self.scheduling.evaluator.is_bad_node(parent):
            self._push_reschedule_children(parent)

    def report_pieces_finished(self, peer: Peer, pieces) -> None:
        """Batched piece results (the daemon's report batcher coalesces a
        linger window of finished pieces into ONE call).  Each entry is a
        dict with number/parent_id/length/cost_ns; semantics are exactly
        N report_piece_finished calls — per-piece dedup (Peer.finish_piece)
        and the bad-parent ejection check run for every entry, so a
        retried batch is as blind-retry-safe as retried singles."""
        for p in pieces:
            self.report_piece_finished(
                peer,
                int(p["number"]),
                parent_id=p.get("parent_id", ""),
                length=int(p.get("length", 0)),
                cost_ns=int(p.get("cost_ns", 0)),
            )

    def report_piece_failed(self, peer: Peer, parent_id: str) -> ScheduleResult:
        """Piece failure → blocklist the parent and reschedule
        (service handleDownloadPieceFailedRequest)."""
        metrics.PIECE_RESULT_TOTAL.inc(result="failed")
        peer.block_parents.add(parent_id)
        result = self.scheduling.schedule_candidate_parents(peer)
        metrics.SCHEDULE_TOTAL.inc(outcome=result.kind.name.lower())
        metrics.SCHEDULE_RETRIES.observe(result.retries)
        return result

    def report_peer_finished(self, peer: Peer) -> None:
        """handlePeerSuccess (:1284) + createDownloadRecord (:1418-1629)."""
        if self.shard_guard is not None:
            self.shard_guard.check_task(peer.task.id)
        metrics.PEER_RESULT_TOTAL.inc(result="succeeded")
        _try_event(peer.fsm, "DownloadSucceeded")
        peer.cost_ns = int((time.time() - peer.created_at) * 1e9)
        task = peer.task
        _try_event(task.fsm, "DownloadSucceeded")
        # The record must capture parent attribution BEFORE the DAG edges
        # are dropped (createDownloadRecord at service_v1.go:1418 runs with
        # the graph intact; the FSM callback releases slots afterwards).
        record = (
            self._build_download_record(peer) if self.storage is not None else None
        )
        # Reference peer.go:280-292 (PeerEventDownloadSucceeded callback):
        # a finished child detaches from its parents, RELEASING their
        # upload slots — without this, every completed download holds a
        # slot forever and the seed saturates at concurrent_upload_limit
        # (observed: exactly 50 parent-attributed records, then 100%
        # back-to-source).
        peer.task.delete_peer_in_edges(peer.id)
        if self.storage is not None:
            self.storage.create_download(record)
            metrics.DOWNLOAD_RECORDS_TOTAL.inc()

    def report_peer_failed(self, peer: Peer) -> None:
        metrics.PEER_RESULT_TOTAL.inc(result="failed")
        _try_event(peer.fsm, "DownloadFailed")
        record = (
            self._build_download_record(peer, state="Failed")
            if self.storage is not None
            else None
        )
        # A failed peer can no longer serve: its connected children get
        # fresh candidates pushed (with it blocklisted) instead of burning
        # piece retries against it.
        self._push_reschedule_children(peer)
        # peer.go:293-305 (PeerEventDownloadFailed callback).
        peer.task.delete_peer_in_edges(peer.id)
        if self.storage is not None:
            self.storage.create_download(record)
            metrics.DOWNLOAD_RECORDS_TOTAL.inc()

    def leave_peer(self, peer: Peer) -> None:
        _try_event(peer.fsm, "Leave")
        # A leaving parent strands its children: push them fresh candidates
        # BEFORE the edges disappear (v2 semantics — the child never has to
        # fail a piece against the dead parent first).
        self._push_reschedule_children(peer)
        peer.task.delete_peer_in_edges(peer.id)
        peer.task.delete_peer_out_edges(peer.id)
        self._refresh_gauges()

    def leave_host(self, host: Host) -> None:
        host.leave_peers()
        if self.networktopology is not None:
            self.networktopology.delete_host(host.id)
        # A departed host frees its feature-cache slot immediately instead
        # of aging out of the LRU (featcache invalidation rule, DESIGN §14).
        cache = getattr(self.scheduling.evaluator, "feature_cache", None)
        if cache is not None:
            cache.invalidate(host.id)
        self._refresh_gauges()

    # -- server push (service_v2.go stream.Send semantics) -------------------

    def _push_reschedule_children(self, parent: Peer) -> None:
        """Reschedule every *connected* child of ``parent`` away from it and
        push the fresh candidates down their streams.

        Only hub-subscribed children are touched: rescheduling moves DAG
        edges, and a child that cannot hear about it must keep its current
        assignment (it will recover through the report_piece_failed path
        like the unary wire always did).
        """
        if self.hub is None:
            return
        try:
            children = parent.task.load_children(parent.id)
        except Exception as exc:  # noqa: BLE001 — parent may already be off the DAG
            logger.debug("load_children(%s): %s", parent.id, exc)
            return
        for child in children or []:
            if child.id == parent.id or child.is_done():
                continue
            # Claim the push slot BEFORE touching the DAG; schedule_once
            # only detaches the child's edges when replacements exist and
            # never sleeps (this runs on stream handler threads).
            if not self.hub.claim(child.id):
                continue
            result = self.scheduling.schedule_once(child, {parent.id})
            if result.kind is not ScheduleResultKind.PARENTS:
                continue
            if self.hub.push(child.id, result):
                metrics.SCHEDULE_TOTAL.inc(outcome=f"push_{result.kind.name.lower()}")

    def reschedule_stalled(self, max_idle_s: float) -> int:
        """Server-initiated stall sweep: running peers with parents that
        have not finished a piece within ``max_idle_s`` get fresh
        candidates (current parents blocklisted) pushed.  Returns pushes.

        The unary wire cannot express this — the child would have to fail
        first.  Driven by push.StallMonitor (or tests) on an interval.
        """
        if self.hub is None:
            return 0
        now = time.time()
        pushed = 0
        for peer in self.resource.peer_manager.items():
            if peer.is_done() or now - peer.updated_at <= max_idle_s:
                continue
            if not self.hub.subscribed(peer.id):
                continue
            try:
                current = peer.task.load_parents(peer.id)
            except Exception as exc:  # noqa: BLE001 — raced with GC
                logger.debug("load_parents(%s): %s", peer.id, exc)
                continue
            if not current:
                continue
            if not self.hub.claim(peer.id):
                continue
            result = self.scheduling.schedule_once(
                peer, {p.id for p in current}
            )
            if result.kind is not ScheduleResultKind.PARENTS:
                continue
            if self.hub.push(peer.id, result):
                peer.touch()  # restart the idle clock for the new parents
                pushed += 1
                metrics.SCHEDULE_TOTAL.inc(
                    outcome=f"push_{result.kind.name.lower()}"
                )
        return pushed

    # -- probes (service_v2.go:721-866 SyncProbes) ---------------------------

    def sync_probes_start(self, host: Host) -> List[Host]:
        if self.networktopology is None:
            return []
        metrics.PROBE_SYNC_TOTAL.inc(phase="start")
        return self.networktopology.find_probed_hosts(host.id)

    def sync_probes_finished(
        self, host: Host, results: List[tuple]
    ) -> None:
        """results: [(dest_host_id, rtt_ns)]"""
        if self.networktopology is None:
            return
        metrics.PROBE_SYNC_TOTAL.inc(phase="finished")
        for dest_id, rtt_ns in results:
            self.networktopology.store(host.id, dest_id)
            self.networktopology.enqueue_probe(
                host.id, dest_id, Probe(host_id=dest_id, rtt_ns=int(rtt_ns))
            )

    # -- record construction (service_v1.go:1418-1629) -----------------------

    def _build_download_record(
        self, peer: Peer, state: Optional[str] = None
    ) -> schema.Download:
        parents = [
            parent.to_parent_record(peer)
            for parent in peer.task.load_parents(peer.id)
        ][: schema.MAX_PARENTS_PER_DOWNLOAD]
        return schema.Download(
            id=peer.id,
            tag=peer.tag,
            application=peer.application,
            state=state or peer.fsm.current,
            cost=peer.cost_ns,
            finished_piece_count=peer.finished_piece_count(),
            task=peer.task.to_record(),
            host=peer.host.to_record(),
            parents=parents,
            created_at=int(peer.created_at * 1e9),
            updated_at=int(peer.updated_at * 1e9),
        )
