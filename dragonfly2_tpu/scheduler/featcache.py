"""Per-host feature cache for the scheduler serving path.

``MLEvaluator._featurize`` used to rebuild every host's 12-dim feature
vector — including a full ``Host.to_record()`` dataclass construction —
once per candidate per announce.  Host state changes on announce cadence
(seconds), not evaluate cadence (sub-millisecond under load), so the
vectors are overwhelmingly reusable: this cache keys them by host id and
validates each entry against a cheap *stamp* of every mutable input the
feature function reads.

Layout: an entry is ``(stamp, slot)`` and everything derived from the
host lives in preallocated per-slot arrays — the ``[max_hosts, H]``
float32 feature matrix plus int64 columns for the hash bucket and the
interned idc/location ids.  The per-announce sweep therefore only
collects slot indices in Python; rows, buckets and affinity inputs all
come out as fancy-index gathers.  Interning the idc/location strings
turns the per-announce affinity terms into one vectorized id-compare
(``same_idc``) and one table lookup (``location_affinity`` against a
per-child-location affinity row, built lazily over the location
vocabulary) — the two per-parent Python loops that dominated the
serving featurize profile (BENCHMARKS.md).

Invalidation rules (DESIGN.md §14):

- **announce / host-update** — any path that mutates feature inputs also
  moves the stamp (``Host.touch()`` on announce, upload-slot accounting
  on edge churn), so a stale entry can never be served: the stamp
  mismatch recomputes in place.  Correctness never depends on an
  explicit invalidate call.
- **eviction** — least-recently-REFRESHED past ``max_hosts`` (bounded
  memory on million-host managers; the freed row slot is recycled):
  every recompute moves a host to the back of the order, so live hosts
  keep re-queueing on announce cadence and the front of the order is the
  hosts that have gone quiet longest.  Plus explicit
  ``invalidate(host_id)`` from ``SchedulerService.leave_host`` so
  departed hosts free their slot immediately instead of aging out.

The cached row is produced by the *same* ``records.features.host_features``
code the scalar path used, so cache-path features are byte-identical to
reference-path features (asserted in tests/test_sched_vectorized.py).

Lock ordering: the cache lock is taken before any per-host lock
(``Host.to_record`` on the miss path); no caller may enter the cache
while holding a host lock.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, namedtuple
from typing import Dict, List, Tuple

import numpy as np

from ..records.features import HOST_FEATURE_DIM, _location_affinity, host_bucket
from ..records.features import host_features as _host_features
from . import metrics

_Stamp = Tuple[float, int, int, int, int]

# One announce's cache product: everything the ML featurizer needs that
# is a function of host identity/state alone, gathered in one locked
# sweep.  ``rows``/``child_row`` are private copies (fancy-indexed out
# of the slot matrix), never views into it.
ServingGather = namedtuple(
    "ServingGather",
    (
        "child_row",      # [H] float32
        "rows",           # [n, H] float32, one per parent host
        "src_buckets",    # [n] int64 hash buckets (parents)
        "dst_bucket",     # int hash bucket (child)
        "same_idc",       # [n] float64 — 1.0 iff non-empty idc match
        "location_affinity",  # [n] float64 — shared '|'-prefix fraction
        "n_hits",
        "n_misses",
    ),
)


class HostFeatureCache:
    """host-id → (stamp, row slot) + per-slot feature/bucket/id columns."""

    def __init__(self, max_hosts: int = 65536) -> None:
        self.max_hosts = max_hosts
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[_Stamp, int]]" = OrderedDict()
        # Per-slot columns, indexed by an entry's slot.
        self._matrix = np.empty((max_hosts, HOST_FEATURE_DIM), dtype=np.float32)
        self._bucket_col = np.empty(max_hosts, dtype=np.int64)
        self._idc_col = np.empty(max_hosts, dtype=np.int64)
        self._loc_col = np.empty(max_hosts, dtype=np.int64)
        # Stack of recyclable row slots; pop() hands out high slots first.
        self._free: List[int] = list(range(max_hosts))
        # Interning tables.  The idc/location vocabulary is the fleet's
        # topology labels — bounded by deployment shape, not host count.
        self._idcs: List[str] = []
        self._idc_ids: Dict[str, int] = {}
        self._locs: List[str] = []
        self._loc_ids: Dict[str, int] = {}
        # child loc id -> affinity row over the loc vocabulary (float64),
        # extended lazily as the vocabulary grows; at most vocab² floats.
        self._aff_rows: Dict[int, np.ndarray] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _stamp(host) -> _Stamp:
        # Every mutable field host_features() reads, cheap attribute reads
        # only.  stats.* writers go through Host.touch() (announce paths),
        # which moves updated_at; the upload counters move on their own.
        return (
            host.updated_at,
            host.concurrent_upload_count,
            host.upload_count,
            host.upload_failed_count,
            host.concurrent_upload_limit,
        )

    # -- locked internals ----------------------------------------------------

    def _intern_locked(self, s: str, strings: List[str], ids: Dict[str, int]) -> int:
        i = ids.get(s)
        if i is None:
            i = len(strings)
            strings.append(s)
            ids[s] = i
        return i

    def _miss_locked(self, h) -> int:
        """(Re)compute one host's entry; returns its row slot.  Stamp is
        read BEFORE featurizing: a host mutating mid-computation leaves an
        old stamp behind, so the next lookup recomputes — the cache can
        never serve a row fresher than its stamp."""
        stamp = self._stamp(h)
        # Same code path as the scalar reference (to_record() +
        # host_features()), so rows are byte-identical to it.
        row = _host_features(h.to_record())
        old = self._entries.get(h.id)
        if old is not None:
            slot = old[1]
        elif self._free:
            slot = self._free.pop()
        else:
            _, evicted = self._entries.popitem(last=False)
            slot = evicted[1]
            self.evictions += 1
        self._matrix[slot] = row
        self._bucket_col[slot] = host_bucket(h.id)
        self._idc_col[slot] = self._intern_locked(
            h.stats.network.idc, self._idcs, self._idc_ids
        )
        self._loc_col[slot] = self._intern_locked(
            h.stats.network.location, self._locs, self._loc_ids
        )
        self._entries[h.id] = (stamp, slot)
        self._entries.move_to_end(h.id)
        return slot

    def _slot_locked(self, h) -> int:
        entry = self._entries.get(h.id)
        # _stamp() inlined: a method call + tuple per host showed in the
        # gather profile at 50 candidates/announce.
        if entry is not None and entry[0] == (
            h.updated_at,
            h.concurrent_upload_count,
            h.upload_count,
            h.upload_failed_count,
            h.concurrent_upload_limit,
        ):
            # No move_to_end on hits: eviction order is least-recently-
            # REFRESHED — hosts re-announce on a cadence, so live hosts
            # keep moving to the back via the miss path, and the hit
            # sweep saves an OrderedDict relink per candidate.
            self.hits += 1
            return entry[1]
        self.misses += 1
        return self._miss_locked(h)

    def _aff_row_locked(self, loc_id: int) -> np.ndarray:
        """Affinity of ``loc_id``'s location string against every interned
        location — each cell is the SAME ``_location_affinity`` the scalar
        path calls per pair, so table lookups are byte-identical to it."""
        row = self._aff_rows.get(loc_id)
        if row is None or len(row) < len(self._locs):
            src = self._locs[loc_id]
            row = np.fromiter(
                (_location_affinity(src, dst) for dst in self._locs),
                np.float64,
                count=len(self._locs),
            )
            self._aff_rows[loc_id] = row
        return row

    # -- the serving surface -------------------------------------------------

    def serve(self, child_host, hosts) -> ServingGather:
        """ONE locked sweep per announce: the Python loop only resolves
        slot indices; rows, hash buckets and the vectorized idc/location
        affinity terms all come out as fancy-index gathers over the
        per-slot columns (the per-host numpy scalar stores and affinity
        genexprs dominated the old gather profile)."""
        n = len(hosts)
        if n + 1 > self.max_hosts:
            # A candidate set larger than the cache would evict-and-reuse
            # slots mid-sweep; serve it uncached (never hit in practice —
            # filter_parent_limit is orders below max_hosts).
            return self._serve_uncached(child_host, hosts)
        slots: List[int] = []
        append = slots.append
        with self._mu:
            hits0 = self.hits  # inside the lock: counters are shared
            cslot = self._slot_locked(child_host)
            entries = self._entries
            n_hit = 0
            for h in hosts:
                e = entries.get(h.id)
                # Hit path fully inlined (stamp tuple + method call per
                # host showed in the serve profile at 50 candidates).
                if e is not None and e[0] == (
                    h.updated_at,
                    h.concurrent_upload_count,
                    h.upload_count,
                    h.upload_failed_count,
                    h.concurrent_upload_limit,
                ):
                    # No move_to_end on hits — see _slot_locked.
                    n_hit += 1
                    append(e[1])
                else:
                    append(self._miss_locked(h))
            self.hits += n_hit
            self.misses += n - n_hit
            idx = np.asarray(slots, dtype=np.intp)
            rows = self._matrix[idx]             # fancy index == copy
            child_row = self._matrix[cslot].copy()
            src_buckets = self._bucket_col[idx]
            dst_bucket = int(self._bucket_col[cslot])
            child_idc = self._idc_col[cslot]
            if self._idcs[child_idc]:
                same_idc = (self._idc_col[idx] == child_idc).astype(np.float64)
            else:
                same_idc = np.zeros(n, dtype=np.float64)
            location_affinity = self._aff_row_locked(
                int(self._loc_col[cslot])
            )[self._loc_col[idx]]
            n_hits = self.hits - hits0
        n_misses = (n + 1) - n_hits
        metrics.EVAL_CACHE_TOTAL.inc(n_hits, result="hit")
        metrics.EVAL_CACHE_TOTAL.inc(n_misses, result="miss")
        return ServingGather(
            child_row, rows, src_buckets, dst_bucket, same_idc,
            location_affinity, n_hits, n_misses,
        )

    def _serve_uncached(self, child_host, hosts) -> ServingGather:
        child_row = _host_features(child_host.to_record())
        rows = np.stack([_host_features(h.to_record()) for h in hosts])
        src_buckets = np.asarray([host_bucket(h.id) for h in hosts], np.int64)
        child_idc = child_host.stats.network.idc
        same_idc = np.asarray(
            [
                1.0 if (child_idc and child_idc == h.stats.network.idc) else 0.0
                for h in hosts
            ],
            np.float64,
        )
        child_loc = child_host.stats.network.location
        location_affinity = np.asarray(
            [_location_affinity(child_loc, h.stats.network.location) for h in hosts],
            np.float64,
        )
        n = len(hosts)
        metrics.EVAL_CACHE_TOTAL.inc(n + 1, result="miss")
        with self._mu:
            self.misses += n + 1
        return ServingGather(
            child_row, rows, src_buckets, host_bucket(child_host.id),
            same_idc, location_affinity, 0, n + 1,
        )

    def features(self, host) -> np.ndarray:
        with self._mu:
            hit = self.hits
            slot = self._slot_locked(host)
            row = self._matrix[slot].copy()  # copy: slots get recycled
            hit = self.hits - hit
        metrics.EVAL_CACHE_TOTAL.inc(result="hit" if hit else "miss")
        return row

    def gather(self, hosts) -> np.ndarray:  # dflint: hotpath
        """[n, HOST_FEATURE_DIM] float32 — one cached row per host, one
        fancy-index copy; metrics batched into two counter bumps."""
        return self.gather_with_buckets(hosts)[0]

    def gather_with_buckets(self, hosts) -> Tuple[np.ndarray, np.ndarray]:
        """(features [n, H] float32, hash buckets [n] int64) in one
        locked sweep."""
        n = len(hosts)
        if not n:
            return (
                np.zeros((0, HOST_FEATURE_DIM), dtype=np.float32),
                np.zeros(0, dtype=np.int64),
            )
        if n > self.max_hosts:
            sv = self._serve_uncached(hosts[0], hosts)
            return sv.rows, sv.src_buckets
        with self._mu:
            hits0 = self.hits  # inside the lock: counters are shared
            idx = np.fromiter(
                (self._slot_locked(h) for h in hosts), np.intp, count=n
            )
            rows = self._matrix[idx]
            buckets = self._bucket_col[idx]
            n_hits = self.hits - hits0
        metrics.EVAL_CACHE_TOTAL.inc(n_hits, result="hit")
        metrics.EVAL_CACHE_TOTAL.inc(n - n_hits, result="miss")
        return rows, buckets

    def bucket(self, host) -> int:
        """Memoized ``host_bucket(host.id)`` (crc32 skipped on hits)."""
        with self._mu:
            entry = self._entries.get(host.id)
            if entry is not None:
                return int(self._bucket_col[entry[1]])
        return host_bucket(host.id)

    # -- maintenance ---------------------------------------------------------

    def invalidate(self, host_id: str) -> None:
        with self._mu:
            entry = self._entries.pop(host_id, None)
            if entry is not None:
                self._free.append(entry[1])

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._free = list(range(self.max_hosts))

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
