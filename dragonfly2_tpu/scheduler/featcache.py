"""Columnar host store: the slot matrix is the SOURCE OF TRUTH.

PR 3's ``HostFeatureCache`` was a cache: the ``Host`` object owned the
serving state and the slot matrix held stamp-validated derived rows, so
every serve paid a per-candidate stamp compare and every stamp miss paid
an object→matrix marshalling hop (``to_record()`` + ``host_features``).
BENCHMARKS.md was honest that this ate the whole ``vector_rule`` win.

This module inverts the ownership (DESIGN.md §18, records "columnar from
birth" §2).  The preallocated struct-of-arrays — the ``[max_hosts, H]``
float32 feature matrix plus parallel columns for upload counters/limit,
peer count, ``updated_at`` timestamps, interned idc/location ids,
pre-scaled rule-score terms and per-slot write stamps — is authoritative
for any host *bound* to a slot.  ``scheduler.resource.Host`` becomes a
thin view: its hot-field properties read and write these columns
directly, announce decode (``SchedulerService.announce_host`` /
``register_peer`` → ``adopt``) writes columns on arrival, and the serve
path is a pure fancy-index gather — no attribute walk, no
``to_record()``, and **no stamp-miss refresh on the steady state**.

Ownership & invalidation rules:

- **bind (adopt/first serve)** — an unbound host is claimed: shadow
  state is copied into a slot's columns, the feature row is computed
  once, and the host's accessors flip to column views.  Flipping holds
  the store lock then the host lock (lock order §16).
- **write-through** — every mutator (upload accounting, ``touch``,
  property setters, peer add/remove) writes its column AND the derived
  cells (feature row entries 5-7, the pre-scaled rule upload-success /
  free-upload terms) with the same float math ``host_features`` uses,
  so the matrix row is always current and byte-identical to what the
  scalar oracle computes from the (column-backed) accessors.
- **detach (eviction / ``invalidate``)** — columns are copied back into
  the object's shadow attributes BEFORE the binding clears and the slot
  recycles, so no state is ever lost to churn; a departed host that
  re-announces rebinds from its shadows.
- **foreign entries** — a host already owned by ANOTHER store (two
  evaluators sharing hosts, tests) gets a PR-3-style stamped copy here,
  validated against the host's ``_mut`` mutation counter; correctness is
  identical, only the owner gets the stamp-free fast path.

``_stamp_col`` records each slot's last write generation (the owner's
``_mut`` at write time) — ``validate_consistency`` compares it, plus a
full recompute of every bound row, to detect torn slot state (the chaos
drill's no-torn-rows assertion).

Lock ordering: store lock before any per-host lock; no caller may enter
the store while holding a host lock (mutators write columns under the
host lock only — single-cell writes race a concurrent gather exactly as
benignly as the scalar path's per-field reads at 50 different instants).
"""

from __future__ import annotations

import math
import threading
import time
from collections import OrderedDict, namedtuple
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import numpy as np

from ..records.features import HOST_FEATURE_DIM, _location_affinity, host_bucket
from ..records.features import host_features as _host_features
from ..utils.types import HostType
from . import metrics

if TYPE_CHECKING:  # lock-graph resolver type (§16): Host._mu nests under _mu
    from .resource import Host

# Label-bound metric children: the kwargs-dict label resolution is paid
# once at import, not per announce (utils.metrics._CounterChild).
_CACHE_HIT = metrics.EVAL_CACHE_TOTAL.labels(result="hit")
_CACHE_MISS = metrics.EVAL_CACHE_TOTAL.labels(result="miss")

# rule_serve packs (host slot | peer encoding << 32) into one int per
# parent; slot ids are therefore capped at 2^32 (max_hosts bound).
_SLOT_MASK = np.int64(0xFFFFFFFF)


class _ForeignHost(Exception):
    """Raised inside the lock-free gather's fromiter when a candidate is
    not owner-bound here — aborts the optimistic pass."""


def _foreign():
    raise _ForeignHost

# Rule-evaluator weights (scheduler/evaluator.py base weights), baked into
# the pre-scaled columns/tables so the serve-side weighted sum is pure
# adds.  0.2 * us and 0.15 * fs computed at WRITE time are bit-identical
# to the scalar path computing them at evaluate time from the same ints.
_W_PIECE = 0.2
_W_UPLOAD_SUCCESS = 0.2
_W_FREE_UPLOAD = 0.15
_W_AFFINITY = 0.15
# 0.15 * host_type_score for a NORMAL host (score = MAX_SCORE * 0.5):
# both products are exact-double-identical to the scalar path's.
_W_HT_NORMAL = 0.15 * 0.5

# One announce's ML-path cache product: everything the featurizer needs
# that is a function of host identity/state alone, gathered in one locked
# sweep.  ``rows``/``child_row`` are private copies (fancy-indexed out of
# the slot matrix), never views into it.  ``src_slots``/``child_slot``
# feed the fused gather+score kernel (ops/pallas_score.py); they are None
# on the uncached overflow path.
ServingGather = namedtuple(
    "ServingGather",
    (
        "child_row",      # [H] float32
        "rows",           # [n, H] float32, one per parent host
        "src_buckets",    # [n] int64 hash buckets (parents)
        "dst_bucket",     # int hash bucket (child)
        "same_idc",       # [n] float64 — 1.0 iff non-empty EXACT idc match
        "location_affinity",  # [n] float64 — shared '|'-prefix fraction
        "src_slots",      # [n] intp slot ids (None when served uncached)
        "child_slot",     # int slot id (-1 when served uncached)
        "n_hits",
        "n_misses",
    ),
)

# One announce's RULE-path gather: pre-scaled weighted terms straight off
# the columns — the weighted sum is then ~6 numpy adds (evaluator.py).
# The ONE python pass over the candidates resolves slots AND encodes the
# two peer-side inputs into ``peer_enc`` (finished count << 1 | elevated
# fsm state): a single int per peer, no tuple allocation, one fromiter.
RuleGather = namedtuple(
    "RuleGather",
    (
        # [n, 4] float64 — pre-scaled per-HOST terms, one fancy index:
        # (0.2*upload_success, 0.15*free_upload, host-type base,
        #  host-type elevated multiplier).
        "w_host",
        # [n, 2] float64 — pre-scaled per-(idc, location)-PAIR terms,
        # one gather from the per-child pair table:
        # (0.15*idc_affinity, 0.15*location_affinity).
        "w_aff",
        # [n] float64 — the EXACT 0.15 * host_type_score product for
        # each (host type, peer elevated-state) combination.
        "w_ht",
        "peer_enc",      # [n] int64   — finished_pieces << 1 | elevated
        "slots",         # [n] int64
        "n_hits",
        "n_misses",
    ),
)


class HostFeatureCache:
    """Columnar host store: slot columns are authoritative for bound
    hosts; the class name survives from PR 3 because every consumer
    (config, CLI wiring, tests) addresses it by this name.

    The first store constructed in a process (while no other is alive)
    is the PRIMARY: hosts it binds additionally carry their slot as a
    plain ``Host._pslot`` attribute, which the lock-free rule gather
    validates with one attribute read per candidate.  A scheduler
    process has exactly one store (the composition root builds it), so
    production serving always runs primary; extra stores (tests, tools)
    stay fully correct through the binding-tuple path."""

    _primary_ref = None  # weakref to the process's primary store

    def __init__(self, max_hosts: int = 65536) -> None:
        import weakref

        prim = HostFeatureCache._primary_ref
        self._is_primary = prim is None or prim() is None
        if self._is_primary:
            HostFeatureCache._primary_ref = weakref.ref(self)
        self.max_hosts = max_hosts
        self._mu = threading.Lock()
        # host id -> (slot, stamp); stamp None == owner-bound (stamp-free
        # fast path), else the host's _mut at copy time (foreign entry).
        self._entries: "OrderedDict[str, Tuple[int, Optional[int]]]" = OrderedDict()
        # -- the struct-of-arrays (DF012 contract featcache.hoststate) --
        self._matrix = np.empty((max_hosts, HOST_FEATURE_DIM), dtype=np.float32)
        self._bucket_col = np.empty(max_hosts, dtype=np.int64)
        self._idc_col = np.empty(max_hosts, dtype=np.int64)
        self._idc_ci_col = np.empty(max_hosts, dtype=np.int64)
        self._loc_col = np.empty(max_hosts, dtype=np.int64)
        self._upload_count_col = np.zeros(max_hosts, dtype=np.int64)
        self._upload_failed_col = np.zeros(max_hosts, dtype=np.int64)
        self._concurrent_upload_col = np.zeros(max_hosts, dtype=np.int64)
        self._upload_limit_col = np.zeros(max_hosts, dtype=np.int64)
        self._peer_count_col = np.zeros(max_hosts, dtype=np.int64)
        self._updated_at_col = np.zeros(max_hosts, dtype=np.float64)
        # Pre-scaled rule-score terms, ONE row per slot so the rule
        # gather is a single [n, 4] fancy index: columns are
        # (0.2*upload_success, 0.15*free_upload, host-type base term,
        # host-type elevated multiplier) — see _derive_upload_cells.
        self._rule_w_cols = np.zeros((max_hosts, 4), dtype=np.float64)
        self._type_normal_col = np.zeros(max_hosts, dtype=np.int8)
        # Interned (idc_ci, location) PAIR id per slot: the two affinity
        # terms gather from one per-child-pair [P, 2] table row.
        self._pair_col = np.zeros(max_hosts, dtype=np.int64)
        self._stamp_col = np.zeros(max_hosts, dtype=np.int64)
        # Owner Host object per slot (None for foreign/free slots) — the
        # eviction path needs the object to copy columns back into.
        self._slot_host: List[Optional[object]] = [None] * max_hosts
        # Stack of recyclable row slots; pop() hands out high slots first.
        self._free: List[int] = list(range(max_hosts))
        # Interning tables.  The idc/location vocabulary is the fleet's
        # topology labels — bounded by deployment shape, not host count.
        # The ci (case-insensitive) idc table serves the RULE affinity
        # (evaluator.idc_affinity_score lowercases); the exact table
        # serves the ML feature's exact-match semantics.
        self._idcs: List[str] = []
        self._idc_ids: Dict[str, int] = {}
        self._idcs_ci: List[str] = []
        self._idc_ci_ids: Dict[str, int] = {}
        self._locs: List[str] = []
        self._loc_ids: Dict[str, int] = {}
        # child loc id -> affinity row over the loc vocabulary (float64),
        # extended lazily as the vocabulary grows; at most vocab² floats.
        # _aff_rows: ML semantics (records.features._location_affinity);
        # _pair_rows: rule semantics pre-scaled by 0.15 (per pair id).
        self._aff_rows: Dict[int, np.ndarray] = {}
        # (ci idc id, loc id) pair vocabulary + per-child-pair [P, 2]
        # tables holding (0.15*idc_affinity, 0.15*location_affinity) —
        # both rule affinity terms come out of ONE gather.
        self._pairs: List[Tuple[int, int]] = []
        self._pair_ids: Dict[Tuple[int, int], int] = {}
        self._pair_rows: Dict[int, np.ndarray] = {}
        # Bumped on every row/cell write: the fused scorer's device
        # mirror (ops/pallas_score.py) syncs against it per flush.
        self._row_version = 0
        # Slot-TOPOLOGY seqlock for the lock-free rule fast path: odd
        # while a detach/recycle is in progress, +2 per completed one.
        # Value writes do NOT bump it — single-cell write races are the
        # accepted snapshot envelope; only slot reuse (which would hand a
        # gather another host's row) must be detected.
        self._epoch = 0
        # Slots resolved by the sweep currently holding the lock: the
        # eviction path must not recycle them mid-sweep (a gathered slot
        # changing hosts under the sweep would fancy-index another
        # host's row).  Only ever touched under the store lock.
        self._sweep_slots: Optional[List[int]] = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- interning -----------------------------------------------------------

    def _intern_locked(self, s: str, strings: List[str], ids: Dict[str, int]) -> int:
        i = ids.get(s)
        if i is None:
            i = len(strings)
            strings.append(s)
            ids[s] = i
        return i

    # -- write-through (called by Host mutators, host lock held) -------------

    def write_upload_state(
        self,
        slot: int,
        mut: int,
        *,
        upload_count: Optional[int] = None,
        upload_failed_count: Optional[int] = None,
        concurrent_upload_count: Optional[int] = None,
        concurrent_upload_limit: Optional[int] = None,
    ) -> None:
        """Write upload-counter columns AND every cell derived from them:
        feature-row entries 5-7 (same float math as
        ``records.features.host_features``) and the pre-scaled rule
        upload-success / free-upload terms — so the matrix row and rule
        columns are always current and the serve path never refreshes."""
        if upload_count is not None:
            self._upload_count_col[slot] = upload_count
        if upload_failed_count is not None:
            self._upload_failed_col[slot] = upload_failed_count
        if concurrent_upload_count is not None:
            self._concurrent_upload_col[slot] = concurrent_upload_count
        if concurrent_upload_limit is not None:
            self._upload_limit_col[slot] = concurrent_upload_limit
        self._derive_upload_cells(slot)
        self._stamp_col[slot] = mut
        self._row_version += 1

    def _derive_upload_cells(self, slot: int) -> None:
        uploads = int(self._upload_count_col[slot])
        failed = int(self._upload_failed_col[slot])
        conc = int(self._concurrent_upload_col[slot])
        limit = int(self._upload_limit_col[slot])
        # Feature cells — records.features.host_features lines, verbatim
        # math (python float64, one float32 rounding on assignment).
        lim = max(limit, 1)
        self._matrix[slot, 5] = min(conc / lim, 4.0)
        total = max(uploads, 1)
        self._matrix[slot, 6] = 1.0 - min(failed / total, 1.0)
        self._matrix[slot, 7] = math.log1p(max(uploads, 0))
        # Pre-scaled rule terms — evaluator.upload_success_score /
        # free_upload_score × their evaluate() weights, verbatim math.
        if uploads < failed:
            us = 0.0
        elif uploads == 0 and failed == 0:
            us = 1.0
        else:
            us = (uploads - failed) / uploads
        self._rule_w_cols[slot, 0] = _W_UPLOAD_SUCCESS * us
        free = limit - conc
        if limit > 0 and free > 0:
            self._rule_w_cols[slot, 1] = _W_FREE_UPLOAD * (free / limit)
        else:
            self._rule_w_cols[slot, 1] = 0.0

    def write_updated_at(self, slot: int, mut: int, ts: float) -> None:
        self._updated_at_col[slot] = ts
        self._stamp_col[slot] = mut
        self._row_version += 1

    def write_peer_count(self, slot: int, n: int) -> None:
        self._peer_count_col[slot] = n
        self._row_version += 1

    # -- bind / detach -------------------------------------------------------

    def _fill_slot_locked(self, h: "Host", slot: int, stamp: Optional[int]) -> None:
        """Write EVERY column of ``slot`` from the host's current state.
        For a bind, reads hit the shadows (host still unbound); for a
        foreign copy, reads go through the accessors (and therefore the
        owning store's columns)."""
        rec = h.to_record()
        self._matrix[slot] = _host_features(rec)
        self._bucket_col[slot] = host_bucket(h.id)
        idc = h.stats.network.idc
        loc = h.stats.network.location
        self._idc_col[slot] = self._intern_locked(idc, self._idcs, self._idc_ids)
        self._idc_ci_col[slot] = self._intern_locked(
            idc.lower(), self._idcs_ci, self._idc_ci_ids
        )
        self._loc_col[slot] = self._intern_locked(loc, self._locs, self._loc_ids)
        pair = (int(self._idc_ci_col[slot]), int(self._loc_col[slot]))
        pid = self._pair_ids.get(pair)
        if pid is None:
            pid = len(self._pairs)
            self._pairs.append(pair)
            self._pair_ids[pair] = pid
        self._pair_col[slot] = pid
        self._upload_count_col[slot] = rec.upload_count
        self._upload_failed_col[slot] = rec.upload_failed_count
        self._concurrent_upload_col[slot] = rec.concurrent_upload_count
        self._upload_limit_col[slot] = rec.concurrent_upload_limit
        self._peer_count_col[slot] = len(h.peers)
        self._updated_at_col[slot] = h.updated_at
        normal = h.type is HostType.NORMAL
        self._type_normal_col[slot] = 1 if normal else 0
        # Host-type term indexed by the peer's elevated bit: column
        # 2 + elev holds the EXACT scalar product 0.15*host_type_score —
        # NORMAL scores 0.15*0.5 either way, non-NORMAL 0.0 / 0.15.
        self._rule_w_cols[slot, 2] = _W_HT_NORMAL if normal else 0.0
        self._rule_w_cols[slot, 3] = _W_HT_NORMAL if normal else _W_AFFINITY
        self._derive_upload_cells(slot)
        self._stamp_col[slot] = h._mut if stamp is None else stamp
        self._row_version += 1

    def _alloc_slot_locked(self) -> int:
        if self._free:
            return self._free.pop()
        # Evict the least-recently-ENTERED id; a bound owner is detached
        # (columns copied back) so churn never loses state.  Slots the
        # current sweep already resolved are rotated to the back instead
        # of recycled — guaranteed to terminate because serve() rejects
        # candidate sets larger than the store (n + 1 ≤ max_hosts).
        guard = self._sweep_slots
        for _ in range(len(self._entries)):
            evicted_id, (slot, stamp) = self._entries.popitem(last=False)
            if guard is not None and any(
                (x & 0xFFFFFFFF) == slot for x in guard
            ):
                # Guard entries may be rule_serve's packed ints (slot in
                # the low 32 bits) or raw slots — the mask decodes both.
                self._entries[evicted_id] = (slot, stamp)
                continue
            self._epoch += 1  # seqlock: recycle in progress
            try:
                if stamp is None:
                    owner = self._slot_host[slot]
                    if owner is not None:
                        self._detach_locked(owner, slot)
                self._slot_host[slot] = None
                self.evictions += 1
            finally:
                self._epoch += 1
            return slot
        raise RuntimeError("columnar host store exhausted mid-sweep")

    def _bind_locked(self, h: "Host") -> int:
        """Claim ownership of an unbound host: columns become the source
        of truth; the accessors flip to column views."""
        slot = self._alloc_slot_locked()
        with h._mu:
            bound = h._cols is None
            if bound:
                self._fill_slot_locked(h, slot, None)
                h._cols = (self, slot)
                if self._is_primary:
                    h._pslot = slot
        if not bound:
            # Another store won the bind race between our unbound check
            # and here; serve it as a foreign copy instead (outside the
            # host lock — the foreign path may evict/detach OTHER hosts
            # and must not nest host locks).
            self._free.append(slot)
            return self._foreign_miss_locked(h)
        self._slot_host[slot] = h
        self._entries[h.id] = (slot, None)
        return slot

    def _detach_locked(self, h: "Host", slot: int) -> None:
        """Copy column state back into the object's shadows, then clear
        the binding.  Store lock held; takes the host lock (§16 order)."""
        with h._mu:
            h._upload_count = int(self._upload_count_col[slot])
            h._upload_failed_count = int(self._upload_failed_col[slot])
            h._concurrent_upload_count = int(self._concurrent_upload_col[slot])
            h._concurrent_upload_limit = int(self._upload_limit_col[slot])
            h._updated_at = float(self._updated_at_col[slot])
            h._pslot = -1
            h._cols = None

    def refresh_row(self, h: "Host") -> None:
        """Full row recompute for a bound host (the ``touch`` path —
        announce decode may have replaced stats wholesale).  Re-verifies
        the binding under the store lock: a raced detach falls back to a
        shadow timestamp write."""
        now = time.time()
        with self._mu:
            b = h._cols
            if b is None or b[0] is not self:
                h._updated_at = now
                return
            slot = b[1]
            self._fill_slot_locked(h, slot, None)
            self._updated_at_col[slot] = now

    def adopt(self, h: "Host") -> bool:
        """Announce decode writes columns on arrival: bind an unbound
        host (no-op when already bound here; a host owned elsewhere keeps
        its owner — this store will serve it via stamped copies).

        Returns True when THIS call bound the host — the bind just
        computed the full row from the current stats, so the announce
        path stamps ``updated_at`` instead of paying a second identical
        row fill (the double-fill showed up as ~1.75 fills/announce in
        the fleet-swarm profile)."""
        with self._mu:
            if h._cols is not None:
                return False
            before = self.misses
            self._slot_locked(h)
            # _slot_locked counts a miss exactly when it (re)computed the
            # row on the bind/foreign path; a hit means another store's
            # binding already serves it and the caller must still touch.
            return self.misses > before and h._cols is not None and h._cols[0] is self

    def stamp_row(self, h: "Host") -> None:
        """Freshness stamp for a row filled moments ago (the adopt→touch
        announce sequence): updates ``updated_at`` without recomputing
        feature cells.  Falls back to the shadow write on a raced
        detach, exactly like ``refresh_row``."""
        now = time.time()
        with self._mu:
            b = h._cols
            if b is None or b[0] is not self:
                h._updated_at = now
                return
            self._updated_at_col[b[1]] = now

    # -- slot resolution -----------------------------------------------------

    def _foreign_miss_locked(self, h: "Host") -> int:
        """PR-3-style stamped copy for a host owned by another store.
        Stamp is read BEFORE copying: a host mutating mid-copy leaves a
        newer _mut behind, so the next lookup recomputes — this store can
        never serve a copy fresher than its stamp."""
        stamp = h._mut
        old = self._entries.get(h.id)
        if old is not None:
            slot = old[0]
        else:
            slot = self._alloc_slot_locked()
        self._fill_slot_locked(h, slot, stamp)
        self._slot_host[slot] = None
        self._entries[h.id] = (slot, stamp)
        self._entries.move_to_end(h.id)
        return slot

    def _slot_locked(self, h: "Host") -> int:
        b = h._cols
        if b is not None:
            if b[0] is self:
                # Owner fast path: NO stamp compare, NO dict lookup — the
                # columns are maintained by write-through.
                self.hits += 1
                return b[1]
            e = self._entries.get(h.id)
            if e is not None and e[1] == h._mut:
                self.hits += 1
                return e[0]
            self.misses += 1
            return self._foreign_miss_locked(h)
        # Unbound: claim ownership.
        e = self._entries.get(h.id)
        if e is not None:
            # Stale entry from a previous binding epoch (detached by
            # eviction elsewhere, or a foreign owner released) — rebuild.
            self._entries.pop(h.id, None)
            self._free.append(e[0])
            self._slot_host[e[0]] = None
        self.misses += 1
        return self._bind_locked(h)

    # -- affinity tables -----------------------------------------------------

    def _aff_row_locked(self, loc_id: int) -> np.ndarray:
        """ML semantics: affinity of ``loc_id`` against every interned
        location — each cell is the SAME ``_location_affinity`` the
        featurizer calls per pair, so lookups are byte-identical."""
        row = self._aff_rows.get(loc_id)
        if row is None or len(row) < len(self._locs):
            src = self._locs[loc_id]
            row = np.fromiter(
                (_location_affinity(src, dst) for dst in self._locs),
                np.float64,
                count=len(self._locs),
            )
            self._aff_rows[loc_id] = row
        return row

    def _pair_row_locked(self, child_pair: int) -> np.ndarray:
        """Rule semantics, PRE-SCALED, keyed by the child's interned
        (idc_ci, location) PAIR id: row j holds
        ``(0.15 * idc_affinity_score, 0.15 * location_affinity_score)``
        of pair j against the child — the exact products the scalar
        evaluate computes per parent, so BOTH affinity terms come out of
        one [n, 2] gather.  Rows extend lazily as the pair vocabulary
        grows; at most pairs² × 2 floats."""
        row = self._pair_rows.get(child_pair)
        if row is None or row.shape[0] < len(self._pairs):
            from .evaluator import location_affinity_score  # lazy: no cycle

            cci, cloc = self._pairs[child_pair]
            child_has_idc = self._idcs_ci[cci] != ""
            child_loc = self._locs[cloc]
            n_pairs = len(self._pairs)
            row = np.empty((n_pairs, 2), dtype=np.float64)
            for j, (ci, lj) in enumerate(self._pairs):
                row[j, 0] = _W_AFFINITY * (
                    1.0 if (child_has_idc and ci == cci) else 0.0
                )
                row[j, 1] = _W_AFFINITY * location_affinity_score(
                    self._locs[lj], child_loc
                )
            self._pair_rows[child_pair] = row
        return row

    # -- serving surfaces ----------------------------------------------------

    def serve(self, child_host, hosts) -> ServingGather:
        """ONE locked sweep per announce for the ML featurizer: the
        Python loop only resolves slot indices (binding reads, no stamp
        tuples); rows, hash buckets and the vectorized idc/location
        affinity terms all come out as fancy-index gathers."""
        n = len(hosts)
        if n + 1 > self.max_hosts:
            # A candidate set larger than the store would evict-and-reuse
            # slots mid-sweep; serve it uncached (never hit in practice —
            # filter_parent_limit is orders below max_hosts).
            return self._serve_uncached(child_host, hosts)
        with self._mu:
            hits0 = self.hits  # inside the lock: counters are shared
            sweep: List[int] = []
            self._sweep_slots = sweep
            try:
                cslot = self._slot_locked(child_host)
                sweep.append(cslot)
                slot_of = self._slot_locked
                append = sweep.append
                n_hit = 0
                for h in hosts:
                    # Owner fast path inlined: binding read + identity
                    # check per candidate (the per-candidate stamp-tuple
                    # compare this store no longer needs).
                    b = h._cols
                    if b is not None and b[0] is self:
                        n_hit += 1
                        append(b[1])
                    else:
                        append(slot_of(h))
                self.hits += n_hit
            finally:
                self._sweep_slots = None
            idx = np.asarray(sweep[1:], dtype=np.intp)
            rows = self._matrix[idx]             # fancy index == copy
            child_row = self._matrix[cslot].copy()
            src_buckets = self._bucket_col[idx]
            dst_bucket = int(self._bucket_col[cslot])
            child_idc = self._idc_col[cslot]
            if self._idcs[child_idc]:
                same_idc = (self._idc_col[idx] == child_idc).astype(np.float64)
            else:
                same_idc = np.zeros(n, dtype=np.float64)
            location_affinity = self._aff_row_locked(
                int(self._loc_col[cslot])
            )[self._loc_col[idx]]
            n_hits = self.hits - hits0
        n_misses = (n + 1) - n_hits
        _CACHE_HIT.inc(n_hits)
        _CACHE_MISS.inc(n_misses)
        return ServingGather(
            child_row, rows, src_buckets, dst_bucket, same_idc,
            location_affinity, idx, int(cslot), n_hits, n_misses,
        )

    def rule_serve(self, child_host, parents) -> RuleGather:
        """The RULE evaluator's gather: pre-scaled weighted terms off the
        columns — no per-parent Python scoring calls (the attribute
        gathers that kept ``vector_rule`` at ~1×).  ``parents`` are
        PEERS: the single python pass resolves each parent's host slot
        AND encodes the peer-side inputs.

        Steady state (every host owner-bound here, pair table warm) runs
        LOCK-FREE under a slot-topology seqlock: 32 announcer threads on
        a GIL'd box were losing ~35% to store-lock convoy, and the only
        hazard a lock protects against that value-races don't already
        cover is slot RECYCLING — which ``_epoch`` detects, discarding
        the optimistic gather and retrying under the lock."""
        n = len(parents)
        if n + 1 > self.max_hosts:
            return self._rule_serve_uncached(child_host, parents)
        with self._mu:
            hits0 = self.hits
            # ONE append per parent: low 32 bits = host slot, high bits =
            # the peer encoding (finished << 1 | elevated).  The eviction
            # guard decodes with the same mask (_SLOT_MASK).
            sweep: List[int] = []
            self._sweep_slots = sweep
            try:
                cslot = self._slot_locked(child_host)
                sweep.append(cslot)
                slot_of = self._slot_locked
                append = sweep.append
                n_hit = 0
                for p in parents:
                    b = p.host._cols
                    if b is not None and b[0] is self:
                        n_hit += 1
                        append(b[1] | p._enc << 32)
                    else:
                        append(slot_of(p.host) | p._enc << 32)
                self.hits += n_hit
            finally:
                self._sweep_slots = None
            packed = np.asarray(sweep, dtype=np.int64)[1:]
            idx = packed & _SLOT_MASK
            enc = packed >> 32
            w_host = self._rule_w_cols[idx]
            w_ht = self._rule_w_cols[idx, 2 + (enc & 1)]
            w_aff = self._pair_row_locked(
                int(self._pair_col[cslot])
            )[self._pair_col[idx]]
            n_hits = self.hits - hits0
        n_misses = (n + 1) - n_hits
        _CACHE_HIT.inc(n_hits)
        if n_misses:  # steady state is all-hit: skip the zero inc
            _CACHE_MISS.inc(n_misses)
        return RuleGather(w_host, w_aff, w_ht, enc, idx, n_hits, n_misses)

    def rule_scores(self, child, parents, total_piece_count):  # dflint: hotpath
        """Lock-free steady-state rule scoring (the whole announce in
        one function): valid only when the child and every parent host
        are owner-bound HERE and the child's pair row is already built —
        any other condition, or a slot recycle observed via the seqlock,
        returns None and the caller runs the locked ``rule_serve`` +
        shared math instead.  Value-level races (a counter write landing
        mid-gather) are the same accepted envelope as the scalar path's
        per-instant reads.  The arithmetic sequence is bit-identical to
        ``Evaluator.evaluate``'s term order (asserted per element in
        tests/test_sched_vectorized.py)."""
        n = len(parents)
        if not n or n + 1 > self.max_hosts:
            return None
        epoch0 = self._epoch
        if epoch0 & 1:
            return None
        cslot = child.host._pslot
        if cslot < 0 or not self._is_primary:
            return None
        try:
            # One attribute read validates ownership per candidate:
            # _pslot ≥ 0 ⟺ owner-bound to the (unique) primary store.
            packed = np.fromiter(
                (
                    (s | p._enc << 32)
                    if (s := p.host._pslot) >= 0
                    else _foreign()
                    for p in parents
                ),
                np.int64,
                count=n,
            )
        except _ForeignHost:
            return None
        idx = packed & _SLOT_MASK
        w = self._rule_w_cols[idx]
        w_ht = self._rule_w_cols[idx, 2 + ((packed >> 32) & 1)]
        row = self._pair_rows.get(int(self._pair_col[cslot]))
        if row is None:
            return None
        try:
            w_aff = row[self._pair_col[idx]]
        except IndexError:
            # Pair vocabulary grew past this row build; locked path
            # rebuilds the row.
            return None
        if self._epoch != epoch0:
            return None  # a slot recycled under us: discard, go locked
        # Counter updates race-lossy here by design (stats, not truth).
        self.hits += n + 1
        _CACHE_HIT.inc(n + 1)
        # packed >> 33 == finished-piece count (enc = fin << 1 | elev).
        counts = packed >> 33
        if total_piece_count > 0:
            score = _W_PIECE * (counts / total_piece_count)
        else:
            score = _W_PIECE * (counts - child.finished_piece_count())
        np.add(score, w[:, 0], out=score)
        np.add(score, w[:, 1], out=score)
        np.add(score, w_ht, out=score)
        np.add(score, w_aff[:, 0], out=score)
        np.add(score, w_aff[:, 1], out=score)
        return score

    def _rule_serve_uncached(self, child_host, parents) -> RuleGather:
        """Overflow path: the same pre-scaled terms from accessor reads
        (value-identical — the accessors read the owning columns)."""
        from .evaluator import (  # lazy: no import cycle
            free_upload_score,
            host_type_score,
            idc_affinity_score,
            location_affinity_score,
            upload_success_score,
        )

        n = len(parents)
        child_idc = child_host.stats.network.idc
        child_loc = child_host.stats.network.location
        w_host = np.fromiter(
            (
                (
                    _W_UPLOAD_SUCCESS * upload_success_score(p),
                    _W_FREE_UPLOAD * free_upload_score(p),
                    _W_HT_NORMAL if p.host.type is HostType.NORMAL else 0.0,
                    _W_HT_NORMAL
                    if p.host.type is HostType.NORMAL
                    else _W_AFFINITY,
                )
                for p in parents
            ),
            dtype=np.dtype((np.float64, 4)),
            count=n,
        )
        w_ht = np.fromiter(
            (_W_AFFINITY * host_type_score(p) for p in parents),
            np.float64, count=n,
        )
        w_aff = np.fromiter(
            (
                (
                    _W_AFFINITY
                    * idc_affinity_score(p.host.stats.network.idc, child_idc),
                    _W_AFFINITY
                    * location_affinity_score(
                        p.host.stats.network.location, child_loc
                    ),
                )
                for p in parents
            ),
            dtype=np.dtype((np.float64, 2)),
            count=n,
        )
        peer_enc = np.fromiter((p._enc for p in parents), np.int64, count=n)
        _CACHE_MISS.inc(n + 1)
        with self._mu:
            self.misses += n + 1
        return RuleGather(w_host, w_aff, w_ht, peer_enc, None, 0, n + 1)

    def _serve_uncached(self, child_host, hosts) -> ServingGather:
        child_row = _host_features(child_host.to_record())
        rows = np.stack([_host_features(h.to_record()) for h in hosts])
        src_buckets = np.asarray([host_bucket(h.id) for h in hosts], np.int64)
        child_idc = child_host.stats.network.idc
        same_idc = np.asarray(
            [
                1.0 if (child_idc and child_idc == h.stats.network.idc) else 0.0
                for h in hosts
            ],
            np.float64,
        )
        child_loc = child_host.stats.network.location
        location_affinity = np.asarray(
            [_location_affinity(child_loc, h.stats.network.location) for h in hosts],
            np.float64,
        )
        n = len(hosts)
        _CACHE_MISS.inc(n + 1)
        with self._mu:
            self.misses += n + 1
        return ServingGather(
            child_row, rows, src_buckets, host_bucket(child_host.id),
            same_idc, location_affinity, None, -1, 0, n + 1,
        )

    def features(self, host) -> np.ndarray:
        with self._mu:
            hit = self.hits
            slot = self._slot_locked(host)
            row = self._matrix[slot].copy()  # copy: slots get recycled
            hit = self.hits - hit
        (_CACHE_HIT if hit else _CACHE_MISS).inc()
        return row

    def gather(self, hosts) -> np.ndarray:  # dflint: hotpath
        """[n, HOST_FEATURE_DIM] float32 — one row per host, one
        fancy-index copy; metrics batched into two counter bumps."""
        return self.gather_with_buckets(hosts)[0]

    def gather_with_buckets(self, hosts) -> Tuple[np.ndarray, np.ndarray]:
        """(features [n, H] float32, hash buckets [n] int64) in one
        locked sweep."""
        n = len(hosts)
        if not n:
            return (
                np.zeros((0, HOST_FEATURE_DIM), dtype=np.float32),
                np.zeros(0, dtype=np.int64),
            )
        if n > self.max_hosts:
            sv = self._serve_uncached(hosts[0], hosts)
            return sv.rows, sv.src_buckets
        with self._mu:
            hits0 = self.hits  # inside the lock: counters are shared
            sweep: List[int] = []
            self._sweep_slots = sweep
            try:
                slot_of = self._slot_locked
                for h in hosts:
                    sweep.append(slot_of(h))
            finally:
                self._sweep_slots = None
            idx = np.asarray(sweep, dtype=np.intp)
            rows = self._matrix[idx]
            buckets = self._bucket_col[idx]
            n_hits = self.hits - hits0
        _CACHE_HIT.inc(n_hits)
        _CACHE_MISS.inc(n - n_hits)
        return rows, buckets

    def bucket(self, host) -> int:
        """Memoized ``host_bucket(host.id)`` (crc32 skipped on hits)."""
        with self._mu:
            entry = self._entries.get(host.id)
            if entry is not None:
                return int(self._bucket_col[entry[0]])
        return host_bucket(host.id)

    # -- fused-kernel mirror sync (ops/pallas_score.py) ----------------------

    def matrix_snapshot(self) -> Tuple[int, np.ndarray]:
        """(row_version, coherent copy of the slot matrix) — the fused
        gather+score kernel keeps a device-resident mirror and re-uploads
        when the version moved (one locked copy per stale flush)."""
        with self._mu:
            return self._row_version, self._matrix.copy()

    # -- maintenance ---------------------------------------------------------

    def invalidate(self, host_id: str) -> None:
        """Departure (``SchedulerService.leave_host``): detach the owner
        binding (state copied back to the object) and free the slot."""
        with self._mu:
            entry = self._entries.pop(host_id, None)
            if entry is None:
                return
            slot, stamp = entry
            self._epoch += 1  # seqlock: recycle in progress
            try:
                if stamp is None:
                    owner = self._slot_host[slot]
                    if owner is not None:
                        self._detach_locked(owner, slot)
                self._slot_host[slot] = None
                self._free.append(slot)
            finally:
                self._epoch += 1

    def clear(self) -> None:
        with self._mu:
            self._epoch += 1  # seqlock: recycle in progress
            try:
                for slot, owner in enumerate(self._slot_host):
                    if owner is not None:
                        self._detach_locked(owner, slot)
                        self._slot_host[slot] = None
                self._entries.clear()
                self._free = list(range(self.max_hosts))
            finally:
                self._epoch += 1

    def validate_consistency(self) -> List[str]:
        """Torn-slot-row detector (chaos drills, churn property tests):
        for every owner-bound slot, recompute the feature row and derived
        rule terms from the host's column-backed accessors and compare
        byte-for-byte against the stored columns; verify the write stamp
        matches the host's mutation counter.  Returns human-readable
        mismatch descriptions (empty == consistent)."""
        problems: List[str] = []
        with self._mu:
            checks = [
                (hid, slot)
                for hid, (slot, stamp) in self._entries.items()
                if stamp is None and self._slot_host[slot] is not None
            ]
            for hid, slot in checks:
                h = self._slot_host[slot]
                expect = _host_features(h.to_record())
                got = self._matrix[slot]
                if not np.array_equal(expect, got):
                    bad = [
                        i for i in range(HOST_FEATURE_DIM)
                        if expect[i] != got[i]
                    ]
                    problems.append(
                        f"{hid}: feature row cells {bad} differ from a "
                        f"recompute off the column-backed accessors"
                    )
                if self._stamp_col[slot] != h._mut:
                    problems.append(
                        f"{hid}: slot stamp {int(self._stamp_col[slot])} != "
                        f"host mutation counter {h._mut} (torn write)"
                    )
                us = self._rule_w_cols[slot, 0]
                fs = self._rule_w_cols[slot, 1]
                self._derive_upload_cells(slot)
                if (
                    us != self._rule_w_cols[slot, 0]
                    or fs != self._rule_w_cols[slot, 1]
                ):
                    problems.append(f"{hid}: stale derived rule columns")
        return problems

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
