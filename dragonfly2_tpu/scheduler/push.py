"""Server-push channel registry for the bidi scheduling stream.

Reference: the v2 ``AnnouncePeer`` wire is a long-lived bidirectional
stream per peer — the scheduler does not only answer requests, it PUSHES
responses mid-download (new parent lists after a reschedule, typed
errors) via ``stream.Send`` from any handler
(scheduler/service/service_v2.go:89-207,
scheduler/rpcserver/scheduler_server_v2.go:56).

``PeerStreamHub`` is the transport-neutral seam: stream bindings register
a send callback per connected peer; the service layer calls ``push``
when scheduling decisions happen OUTSIDE the peer's own request cycle
(bad-parent ejection, parent death, stall detection).  Payloads are
``ScheduleResult``s; the transport converts to its wire form.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from .scheduling import ScheduleResult

logger = logging.getLogger(__name__)


class PeerStreamHub:
    """peer_id → push-callback registry (thread-safe).

    Callbacks must be non-blocking (enqueue-and-return): pushes happen on
    scheduler handler threads and on the stall-monitor thread.
    """

    def __init__(self, *, push_cooldown_s: float = 1.0) -> None:
        self._mu = threading.Lock()
        self._channels: Dict[str, Callable[[ScheduleResult], None]] = {}
        # Per-peer cooldown: a bad parent stays 3σ-bad across many piece
        # reports; without damping every report would re-push a reschedule
        # (and churn the DAG edges each time).
        self.push_cooldown_s = push_cooldown_s
        self._last_push: Dict[str, float] = {}

    def register(
        self, peer_id: str, send: Callable[[ScheduleResult], None]
    ) -> None:
        with self._mu:
            self._channels[peer_id] = send

    def unregister(
        self,
        peer_id: str,
        send: Optional[Callable[[ScheduleResult], None]] = None,
    ) -> None:
        """With ``send``, only unregister if that exact callback still owns
        the slot — a dying stream's late teardown must not evict the
        channel a reconnected stream's `resume` just re-registered (the
        old reader can linger in its request iterator for tens of seconds
        after the client reconnects)."""
        with self._mu:
            if send is not None and self._channels.get(peer_id) is not send:
                return
            self._channels.pop(peer_id, None)
            self._last_push.pop(peer_id, None)

    def subscribed(self, peer_id: str) -> bool:
        with self._mu:
            return peer_id in self._channels

    def claim(self, peer_id: str) -> bool:
        """Reserve a push slot BEFORE doing any scheduling work: True iff
        the peer is connected and outside its cooldown window (the slot is
        stamped).  Callers must claim first, then mutate the DAG, then
        ``push`` — checking the cooldown only at push time would move the
        server-side edges and then drop the notification, leaving the
        child downloading from parents the DAG no longer records.
        """
        now = time.monotonic()
        with self._mu:
            if peer_id not in self._channels:
                return False
            last = self._last_push.get(peer_id, 0.0)
            if now - last < self.push_cooldown_s:
                return False
            self._last_push[peer_id] = now
            return True

    def push(self, peer_id: str, result: ScheduleResult) -> bool:
        """Deliver a schedule to a claimed peer; False if the channel died."""
        with self._mu:
            send = self._channels.get(peer_id)
        if send is None:
            return False
        try:
            send(result)
            return True
        except Exception:  # noqa: BLE001 — a dead stream must not kill handlers
            self.unregister(peer_id, send)
            return False


class StallMonitor:
    """Periodic server-side stall sweep (the piece the unary wire cannot
    express: reschedules *initiated by the scheduler*).

    A running peer that has parents but has not finished a piece within
    ``max_idle_s`` gets fresh candidates (current parents blocklisted)
    pushed down its stream — the child never has to fail first.
    """

    def __init__(
        self, service, *, max_idle_s: float = 10.0, interval_s: float = 2.0
    ) -> None:
        self.service = service
        self.max_idle_s = max_idle_s
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="stall-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.service.reschedule_stalled(self.max_idle_s)
            except Exception as exc:  # noqa: BLE001 — sweep must survive races
                logger.warning("stall sweep failed: %s", exc)
