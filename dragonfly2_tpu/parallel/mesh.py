"""Mesh construction and sharding rules.

Design (scaling-book recipe): pick a mesh, annotate shardings on the
arguments, let XLA insert collectives.

- ``data`` axis: batch dimension of download-record batches / edge
  partitions of the probe graph.  Gradient all-reduce rides ICI.
- ``model`` axis: reserved for large embedding tables (node embeddings of
  the 100k+-host graph are sharded here when they outgrow one chip's HBM).

The trainer's standard configs (BASELINE.md):
- 1 chip    → mesh (1, 1): everything local, jit only.
- v5e-16    → mesh (16, 1): pure DP, psum over ICI.
- multi-slice → mesh (slices*chips, 1) with DCN-aware partitioning: JAX
  exposes slice boundaries via device attributes; keeping ``data``
  innermost-major over ICI keeps the heavy gradient traffic off DCN.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshSpec:
    data: int = -1   # -1 → all remaining devices
    model: int = 1

    def resolve(self, n_devices: int) -> tuple:
        model = max(self.model, 1)
        data = self.data if self.data > 0 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} does not tile {n_devices} devices"
            )
        return data, model


def create_mesh(
    spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build the (data, model) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    data, model = spec.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (batch / edges) over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_local_batch(global_batch: int) -> int:
    """Per-host slice of the global batch (multi-host input pipelines feed
    only their addressable shard)."""
    return global_batch // max(jax.process_count(), 1)


def pad_to_multiple(n: int, multiple: int) -> int:
    """Round up so shards are equal-size (static shapes; XLA compiles once)."""
    return ((n + multiple - 1) // multiple) * multiple
