"""Graph-partitioned neighbor aggregation with shard_map collectives.

SURVEY §2.6 / §5.7: the framework's analog of sequence/context parallelism
is partitioning the peer graph's neighbor aggregation across devices.  The
node table shards over the mesh's ``data`` axis; each device owns a
contiguous node block (its rows of the padded neighbor table) but its
nodes' neighbors live anywhere, so each aggregation layer performs one
**boundary exchange** — an all-gather of the node features over ICI (XLA
lowers it as a ring of ppermute hops, the same traffic pattern as ring
attention's K/V rotation) — followed by purely local gather + masked mean.

Cost model (scaling-book style): per layer, all-gather moves N·D·(n-1)/n
floats over ICI while the local gather+reduce does N/n·K·D FLOPs per
device — compute and collective overlap when XLA pipelines the layer, and
the exchange is the *only* cross-device traffic (indices/masks never move).

For graphs whose node features don't fit a chip even sharded, the next
step (round 2+) swaps the full all-gather for a halo exchange of just the
boundary node set per shard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gnn import NeighborTable
from .mesh import DATA_AXIS


def _local_aggregate(h_full: jax.Array, indices, mask, edge_feats) -> jax.Array:
    """Local block of the masked-mean aggregation against the gathered table."""
    nbr = jnp.take(h_full, indices, axis=0)                   # [N/n, K, D]
    nbr = jnp.concatenate([nbr, edge_feats.astype(nbr.dtype)], axis=-1)
    m = mask.astype(nbr.dtype)[..., None]
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return (nbr * m).sum(axis=1) / denom                      # [N/n, D+E]


def sharded_neighbor_aggregate(
    mesh: Mesh,
    h: jax.Array,
    table: NeighborTable,
    *,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Node-sharded masked-mean aggregation: h and table sharded on dim 0.

    h: [N, D] sharded P(axis); table rows sharded the same way (indices are
    GLOBAL node ids).  Returns [N, D+E] with the same sharding.
    """

    def body(h_block, indices, mask, edge_feats):
        # Boundary exchange: assemble the full node table locally (ring
        # all-gather over ICI); everything after is device-local.
        h_full = jax.lax.all_gather(h_block, axis, axis=0, tiled=True)
        return _local_aggregate(h_full, indices, mask, edge_feats)

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(h, table.indices, table.mask, table.edge_feats)


def make_sharded_table(mesh: Mesh, table: NeighborTable, *, axis: str = DATA_AXIS) -> NeighborTable:
    """Place a host-built table with its node dim sharded over the mesh."""
    shard = NamedSharding(mesh, P(axis))
    return NeighborTable(
        indices=jax.device_put(table.indices, shard),
        mask=jax.device_put(table.mask, shard),
        edge_feats=jax.device_put(table.edge_feats, shard),
    )


def pad_nodes_for_mesh(n_nodes: int, mesh: Mesh, *, axis: str = DATA_AXIS) -> int:
    """Node count rounded up so every shard is equal (static shapes)."""
    n = mesh.shape[axis]
    return ((n_nodes + n - 1) // n) * n
