"""Graph-partitioned neighbor aggregation with shard_map collectives.

SURVEY §2.6 / §5.7: the framework's analog of sequence/context parallelism
is partitioning the peer graph's neighbor aggregation across devices.  The
node table shards over the mesh's ``data`` axis; each device owns a
contiguous node block (its rows of the padded neighbor table) but its
nodes' neighbors live anywhere, so each aggregation layer performs one
**boundary exchange** — an all-gather of the node features over ICI (XLA
lowers it as a ring of ppermute hops, the same traffic pattern as ring
attention's K/V rotation) — followed by purely local gather + masked mean.

Cost model (scaling-book style): per layer, all-gather moves N·D·(n-1)/n
floats over ICI while the local gather+reduce does N/n·K·D FLOPs per
device — compute and collective overlap when XLA pipelines the layer, and
the exchange is the *only* cross-device traffic (indices/masks never move).

For graphs whose node features don't fit a chip even sharded, the next
step (round 2+) swaps the full all-gather for a halo exchange of just the
boundary node set per shard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gnn import NeighborTable
from .mesh import DATA_AXIS


def _local_aggregate(h_full: jax.Array, indices, mask, edge_feats) -> jax.Array:
    """Local block of the masked-mean aggregation against the gathered table."""
    nbr = jnp.take(h_full, indices, axis=0)                   # [N/n, K, D]
    nbr = jnp.concatenate([nbr, edge_feats.astype(nbr.dtype)], axis=-1)
    m = mask.astype(nbr.dtype)[..., None]
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return (nbr * m).sum(axis=1) / denom                      # [N/n, D+E]


def sharded_neighbor_aggregate(
    mesh: Mesh,
    h: jax.Array,
    table: NeighborTable,
    *,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Node-sharded masked-mean aggregation: h and table sharded on dim 0.

    h: [N, D] sharded P(axis); table rows sharded the same way (indices are
    GLOBAL node ids).  Returns [N, D+E] with the same sharding.
    """

    def body(h_block, indices, mask, edge_feats):
        # Boundary exchange: assemble the full node table locally (ring
        # all-gather over ICI); everything after is device-local.
        h_full = jax.lax.all_gather(h_block, axis, axis=0, tiled=True)
        return _local_aggregate(h_full, indices, mask, edge_feats)

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(h, table.indices, table.mask, table.edge_feats)


def make_sharded_table(mesh: Mesh, table: NeighborTable, *, axis: str = DATA_AXIS) -> NeighborTable:
    """Place a host-built table with its node dim sharded over the mesh."""
    shard = NamedSharding(mesh, P(axis))
    return NeighborTable(
        indices=jax.device_put(table.indices, shard),
        mask=jax.device_put(table.mask, shard),
        edge_feats=jax.device_put(table.edge_feats, shard),
    )


def pad_nodes_for_mesh(n_nodes: int, mesh: Mesh, *, axis: str = DATA_AXIS) -> int:
    """Node count rounded up so every shard is equal (static shapes)."""
    n = mesh.shape[axis]
    return ((n_nodes + n - 1) // n) * n


# ---------------------------------------------------------------------------
# Halo exchange: ship only the boundary rows, not the whole table
# ---------------------------------------------------------------------------


class HaloPlan:
    """Host-side exchange plan for one graph snapshot.

    The full all-gather moves N·D floats to every device per layer; with a
    locality-partitioned graph each shard's neighbors mostly live on-shard,
    so only the **halo** — the off-shard rows its table references — needs
    to move.  The plan is static-shape (max-halo padded) so XLA compiles
    once; rebuild it when the graph snapshot changes, not per step.

    - send_idx   [n, n, H]  — for src device i: local rows to ship to each
                              dest j (row i used inside shard i).
    - local_idx  [N, K]     — the table's global indices remapped into each
                              shard's local space: [0,S) own rows, then
                              halo slots [S + j·H + p].
    - halo       H          — max off-shard rows needed from any one shard.
    """

    def __init__(
        self, n_shards: int, shard_size: int, send_idx, local_idx, halo: int,
        table_digest: str = "",
    ):
        self.n_shards = n_shards
        self.shard_size = shard_size
        self.send_idx = send_idx
        self.local_idx = local_idx
        self.halo = halo
        # Fingerprint of the table's indices at plan time: the plan remaps
        # THOSE indices, so pairing it with a resampled table would
        # silently misalign features.
        self.table_digest = table_digest


def _table_digest(table: NeighborTable) -> str:
    import hashlib
    import numpy as np

    return hashlib.sha1(np.asarray(table.indices).tobytes()).hexdigest()[:16]


def _check_plan(plan: "HaloPlan", table: NeighborTable) -> None:
    """Refuse a plan built for a different table sampling.  Under jit the
    indices are tracers (no concrete bytes to hash) — the caller owns
    plan/table pairing there; the eager path stays guarded."""
    if not plan.table_digest or isinstance(table.indices, jax.core.Tracer):
        return
    if plan.table_digest != _table_digest(table):
        raise ValueError(
            "HaloPlan was built for a different table sampling — rebuild "
            "the plan whenever build_neighbor_table resamples (per epoch)"
        )


def build_halo_plan(table: NeighborTable, mesh: Mesh, *, axis: str = DATA_AXIS) -> HaloPlan:
    import numpy as np

    n = mesh.shape[axis]
    indices = np.asarray(table.indices)
    N, K = indices.shape
    if N % n:
        raise ValueError(f"node count {N} not divisible by {n} shards")
    S = N // n

    # needed[j][i]: sorted unique global rows shard j needs from shard i.
    # uniq is sorted, so each source shard's rows are one contiguous
    # searchsorted slice — no per-element Python (O(N·K) total, numpy).
    needed = [[None] * n for _ in range(n)]
    halo = 0
    bounds = np.arange(n + 1, dtype=np.int64) * S
    for j in range(n):
        block = indices[j * S : (j + 1) * S]
        uniq = np.unique(block)
        cuts = np.searchsorted(uniq, bounds)
        for i in range(n):
            rows = uniq[cuts[i] : cuts[i + 1]]
            if i == j:
                rows = rows[:0]  # own rows need no exchange
            needed[j][i] = rows
            halo = max(halo, len(rows))
    halo = max(halo, 1)

    # send_idx[i][j]: local offsets shard i ships to shard j (pad with 0).
    send_idx = np.zeros((n, n, halo), dtype=np.int32)
    # slot[g] = shard j's local slot for global id g; only ids that occur
    # in shard j's block are ever read, so stale entries are harmless.
    local_idx = np.empty_like(indices, dtype=np.int32)
    slot = np.empty(N, dtype=np.int32)
    for j in range(n):
        slot[j * S : (j + 1) * S] = np.arange(S, dtype=np.int32)
        for i in range(n):
            rows = needed[j][i]
            send_idx[i, j, : len(rows)] = rows - i * S
            slot[rows] = S + i * halo + np.arange(len(rows), dtype=np.int32)
        local_idx[j * S : (j + 1) * S] = slot[indices[j * S : (j + 1) * S]]
    return HaloPlan(
        n, S, jnp.asarray(send_idx), jnp.asarray(local_idx), halo,
        table_digest=_table_digest(table),
    )


def _halo_assemble(h_block, my_send_idx, axis: str) -> jax.Array:
    """Inside a shard_map body: exchange boundary rows and return the
    shard's LOCAL node table ``[S + n·H, D]`` (own rows first, then halo
    slots laid out as ``S + src_shard·H + p`` — the order
    ``build_halo_plan`` remapped ``local_idx`` against)."""
    send = jnp.take(h_block, my_send_idx[0], axis=0)        # [n, H, D]
    recv = jax.lax.all_to_all(
        send, axis, split_axis=0, concat_axis=0, tiled=False
    )
    # recv [n, H, D]: slice i = rows shipped by shard i to this shard.
    return jnp.concatenate(
        [h_block, recv.reshape(-1, h_block.shape[-1])], axis=0
    )


@partial(jax.jit, static_argnames=("mesh", "hops", "axis"))
def _sharded_precompute_impl(
    node_feats, mask, edge_feats, send_idx, local_idx, *, mesh, hops, axis
):
    from ..models.hop import _hop_parts

    def body(x_block, my_send_idx, li, m, ef):
        # Per hop the aggregate keeps D, so ONE plan serves every hop's
        # exchange; the math itself is models.hop._hop_parts — shared
        # with the replicated oracle so the two cannot drift.
        return _hop_parts(
            x_block.astype(jnp.float32),
            m,
            ef,
            lambda h: jnp.take(_halo_assemble(h, my_send_idx, axis), li, axis=0),
            hops,
        )

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(node_feats, send_idx, local_idx, mask, edge_feats)


def precompute_hop_features_sharded(
    mesh: Mesh,
    node_feats: jax.Array,
    table: NeighborTable,
    plan: HaloPlan,
    *,
    hops: int = 2,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Node-sharded ``models.hop.precompute_hop_features``.

    The replicated precompute holds the FULL [N, F] feature table (and a
    [N, K, D] gather) on every chip — at config[4]'s multi-M-node scale
    that table, not the model, is the memory wall.  Here every chip owns
    S = N/n node rows; per hop the only cross-chip traffic is the halo
    all-to-all of [n·H, D] boundary rows (H = max off-shard rows any
    shard references), after which the gather + both masked means are
    device-local.  Per-chip working set drops from N·D to (S + n·H)·D
    and the output stays sharded P(axis) — it feeds straight into
    ``node_sharding="model"`` training without a host round-trip.

    Jits internally (one fused program; cached on mesh/hops/axis) so
    eager callers get the same footprint the bench measures.  Numerically
    identical to the replicated oracle — the hop math IS the oracle's
    (models.hop._hop_parts); verified in dryrun_multichip and
    tests/test_ops.py.
    """
    _check_plan(plan, table)
    return _sharded_precompute_impl(
        node_feats,
        table.mask,
        table.edge_feats,
        plan.send_idx,
        plan.local_idx,
        mesh=mesh,
        hops=hops,
        axis=axis,
    )


def halo_neighbor_aggregate(
    mesh: Mesh,
    h: jax.Array,
    table: NeighborTable,
    plan: HaloPlan,
    *,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Masked-mean aggregation with boundary-only exchange.

    Per layer, one all-to-all of [n·H, D] rows replaces the [N, D]
    all-gather — with a locality-aware partition H ≪ S and the collective
    traffic drops by ~S/H.  Numerically identical to the full exchange.
    """
    _check_plan(plan, table)

    def body(h_block, my_send_idx, local_idx, mask, edge_feats):
        # h_block [S, D]; my_send_idx [1, n, H] (this device's row of the
        # plan); exchange boundary rows, then gather locally.
        local = _halo_assemble(h_block, my_send_idx, axis)       # [S + n·H, D]
        nbr = jnp.take(local, local_idx, axis=0)                 # [S, K, D]
        nbr = jnp.concatenate([nbr, edge_feats.astype(nbr.dtype)], axis=-1)
        m = mask.astype(nbr.dtype)[..., None]
        denom = jnp.maximum(m.sum(axis=1), 1.0)
        return (nbr * m).sum(axis=1) / denom

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(
        h,
        plan.send_idx,            # dim 0 (src device) sharded
        plan.local_idx,
        table.mask,
        table.edge_feats,
    )
