"""Graph-partitioned neighbor aggregation with shard_map collectives.

SURVEY §2.6 / §5.7: the framework's analog of sequence/context parallelism
is partitioning the peer graph's neighbor aggregation across devices.  The
node table shards over the mesh's ``data`` axis; each device owns a
contiguous node block (its rows of the padded neighbor table) but its
nodes' neighbors live anywhere, so each aggregation layer performs one
**boundary exchange** — an all-gather of the node features over ICI (XLA
lowers it as a ring of ppermute hops, the same traffic pattern as ring
attention's K/V rotation) — followed by purely local gather + masked mean.

Cost model (scaling-book style): per layer, all-gather moves N·D·(n-1)/n
floats over ICI while the local gather+reduce does N/n·K·D FLOPs per
device — compute and collective overlap when XLA pipelines the layer, and
the exchange is the *only* cross-device traffic (indices/masks never move).

For graphs whose node features don't fit a chip even sharded, the next
step (round 2+) swaps the full all-gather for a halo exchange of just the
boundary node set per shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gnn import NeighborTable
from .mesh import DATA_AXIS


def _local_aggregate(h_full: jax.Array, indices, mask, edge_feats) -> jax.Array:
    """Local block of the masked-mean aggregation against the gathered table."""
    nbr = jnp.take(h_full, indices, axis=0)                   # [N/n, K, D]
    nbr = jnp.concatenate([nbr, edge_feats.astype(nbr.dtype)], axis=-1)
    m = mask.astype(nbr.dtype)[..., None]
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return (nbr * m).sum(axis=1) / denom                      # [N/n, D+E]


def sharded_neighbor_aggregate(
    mesh: Mesh,
    h: jax.Array,
    table: NeighborTable,
    *,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Node-sharded masked-mean aggregation: h and table sharded on dim 0.

    h: [N, D] sharded P(axis); table rows sharded the same way (indices are
    GLOBAL node ids).  Returns [N, D+E] with the same sharding.
    """

    def body(h_block, indices, mask, edge_feats):
        # Boundary exchange: assemble the full node table locally (ring
        # all-gather over ICI); everything after is device-local.
        h_full = jax.lax.all_gather(h_block, axis, axis=0, tiled=True)
        return _local_aggregate(h_full, indices, mask, edge_feats)

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(h, table.indices, table.mask, table.edge_feats)


def make_sharded_table(mesh: Mesh, table: NeighborTable, *, axis: str = DATA_AXIS) -> NeighborTable:
    """Place a host-built table with its node dim sharded over the mesh."""
    shard = NamedSharding(mesh, P(axis))
    return NeighborTable(
        indices=jax.device_put(table.indices, shard),
        mask=jax.device_put(table.mask, shard),
        edge_feats=jax.device_put(table.edge_feats, shard),
    )


def pad_nodes_for_mesh(n_nodes: int, mesh: Mesh, *, axis: str = DATA_AXIS) -> int:
    """Node count rounded up so every shard is equal (static shapes)."""
    n = mesh.shape[axis]
    return ((n_nodes + n - 1) // n) * n


# ---------------------------------------------------------------------------
# Halo exchange: ship only the boundary rows, not the whole table
# ---------------------------------------------------------------------------


class HaloPlan:
    """Host-side exchange plan for one graph snapshot.

    The full all-gather moves N·D floats to every device per layer; with a
    locality-partitioned graph each shard's neighbors mostly live on-shard,
    so only the **halo** — the off-shard rows its table references — needs
    to move.  The plan is static-shape (max-halo padded) so XLA compiles
    once; rebuild it when the graph snapshot changes, not per step.

    - send_idx   [n, n, H]  — for src device i: local rows to ship to each
                              dest j (row i used inside shard i).
    - local_idx  [N, K]     — the table's global indices remapped into each
                              shard's local space: [0,S) own rows, then
                              halo slots [S + j·H + p].
    - halo       H          — max off-shard rows needed from any one shard.
    """

    def __init__(
        self, n_shards: int, shard_size: int, send_idx, local_idx, halo: int,
        table_digest: str = "",
    ):
        self.n_shards = n_shards
        self.shard_size = shard_size
        self.send_idx = send_idx
        self.local_idx = local_idx
        self.halo = halo
        # Fingerprint of the table's indices at plan time: the plan remaps
        # THOSE indices, so pairing it with a resampled table would
        # silently misalign features.
        self.table_digest = table_digest


def _table_digest(table: NeighborTable) -> str:
    import hashlib
    import numpy as np

    return hashlib.sha1(np.asarray(table.indices).tobytes()).hexdigest()[:16]


def build_halo_plan(table: NeighborTable, mesh: Mesh, *, axis: str = DATA_AXIS) -> HaloPlan:
    import numpy as np

    n = mesh.shape[axis]
    indices = np.asarray(table.indices)
    N, K = indices.shape
    if N % n:
        raise ValueError(f"node count {N} not divisible by {n} shards")
    S = N // n

    # needed[j][i]: sorted unique global rows shard j needs from shard i.
    needed = [[None] * n for _ in range(n)]
    halo = 0
    for j in range(n):
        block = indices[j * S : (j + 1) * S]
        uniq = np.unique(block)
        for i in range(n):
            rows = uniq[(uniq >= i * S) & (uniq < (i + 1) * S)]
            if i == j:
                rows = rows[:0]  # own rows need no exchange
            needed[j][i] = rows
            halo = max(halo, len(rows))
    halo = max(halo, 1)

    # send_idx[i][j]: local offsets shard i ships to shard j (pad with 0).
    send_idx = np.zeros((n, n, halo), dtype=np.int32)
    # position map for remapping: global id → local slot on shard j.
    local_idx = np.empty_like(indices)
    for j in range(n):
        remap = {}
        for p in range(S):
            remap[j * S + p] = p
        for i in range(n):
            rows = needed[j][i]
            send_idx[i, j, : len(rows)] = rows - i * S
            for p, g in enumerate(rows):
                remap[int(g)] = S + i * halo + p
        block = indices[j * S : (j + 1) * S]
        flat = np.array([remap[int(g)] for g in block.ravel()], dtype=np.int32)
        local_idx[j * S : (j + 1) * S] = flat.reshape(S, K)
    return HaloPlan(
        n, S, jnp.asarray(send_idx), jnp.asarray(local_idx), halo,
        table_digest=_table_digest(table),
    )


def halo_neighbor_aggregate(
    mesh: Mesh,
    h: jax.Array,
    table: NeighborTable,
    plan: HaloPlan,
    *,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Masked-mean aggregation with boundary-only exchange.

    Per layer, one all-to-all of [n·H, D] rows replaces the [N, D]
    all-gather — with a locality-aware partition H ≪ S and the collective
    traffic drops by ~S/H.  Numerically identical to the full exchange.
    """
    if plan.table_digest and plan.table_digest != _table_digest(table):
        raise ValueError(
            "HaloPlan was built for a different table sampling — rebuild "
            "the plan whenever build_neighbor_table resamples (per epoch)"
        )

    def body(h_block, my_send_idx, local_idx, mask, edge_feats):
        # h_block [S, D]; my_send_idx [1, n, H] (this device's row of the
        # plan); gather outgoing halo rows and all-to-all them.
        send = jnp.take(h_block, my_send_idx[0], axis=0)        # [n, H, D]
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=False)
        # recv [n, H, D]: slice i = rows shipped by shard i to this shard.
        local = jnp.concatenate(
            [h_block, recv.reshape(-1, h_block.shape[-1])], axis=0
        )                                                        # [S + n·H, D]
        nbr = jnp.take(local, local_idx, axis=0)                 # [S, K, D]
        nbr = jnp.concatenate([nbr, edge_feats.astype(nbr.dtype)], axis=-1)
        m = mask.astype(nbr.dtype)[..., None]
        denom = jnp.maximum(m.sum(axis=1), 1.0)
        return (nbr * m).sum(axis=1) / denom

    sharded = P(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, sharded),
        out_specs=sharded,
    )(
        h,
        plan.send_idx,            # dim 0 (src device) sharded
        plan.local_idx,
        table.mask,
        table.edge_feats,
    )
