"""Distributed execution over TPU meshes.

The reference's distributed backend is gRPC + Redis + gossip (SURVEY.md
§5.8); its trainer was meant to be a single process.  Here the trainer's
internal communication is JAX collectives over ICI/DCN: a
``jax.sharding.Mesh`` with ``data`` (batch / edge partition) and ``model``
axes, shardings annotated with NamedSharding, XLA inserting the
all-reduce/all-gather traffic.
"""

from .mesh import (  # noqa: F401
    MeshSpec,
    batch_sharding,
    create_mesh,
    host_local_batch,
    replicated,
)
