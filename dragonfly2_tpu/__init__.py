"""dragonfly2_tpu — a TPU-native P2P file-distribution framework with learned scheduling.

A ground-up rebuild of the capabilities of Dragonfly2 (a CNCF P2P
file-distribution / image-acceleration system: manager, scheduler, peer
daemon, trainer), designed TPU-first rather than ported:

- The control plane (scheduler resource state machines, parent-peer
  scheduling, network-topology probe store, manager model registry) is
  implemented as an embeddable runtime with native (C++) storage engines.
- The ML scheduling loop that the reference left as a stub
  (reference: trainer/training/training.go:82-99, and the ML evaluator
  fallback at scheduler/scheduling/evaluator/evaluator.go:84-86) is
  first-class here: schedulers produce download records and probe graphs,
  the trainer trains an MLP bandwidth regressor and a GNN (GraphSAGE/GAT)
  parent ranker with JAX/XLA — data-parallel and graph-partitioned over a
  `jax.sharding.Mesh` — and publishes versioned models back through the
  manager to the scheduler's evaluator.

Package map (mirrors SURVEY.md §2's component inventory):

- ``utils``    — shared kernel: idgen, digest, DAG, TTL cache, GC, hostinfo.
- ``records``  — record schemas (Download / NetworkTopology), columnar
                 storage, synthetic swarm generators.
- ``models``   — MLP regressor, GraphSAGE, GAT ranker (flax, bf16).
- ``ops``      — neighbor gather/aggregation ops (+ pallas kernels).
- ``parallel`` — mesh construction, sharding rules, edge-partitioned
                 aggregation with ring collectives.
- ``trainer``  — ingest pipeline, train loops, checkpointing, eval.
- ``scheduler``— resource FSMs, peer DAG, evaluators (default/nt/ml),
                 scheduling engine, record storage, network topology.
- ``manager``  — model registry (versioned, single-active), searcher.
- ``daemon``   — peer daemon data plane (piece storage, conductor, upload).
- ``native``   — C++ runtime pieces + ctypes bindings.
"""

__version__ = "0.1.0"
