"""dfstore: object-storage CLI through the P2P gateway (reference:
cmd/dfstore + client/dfstore — Get/Put/Copy/Delete/IsExist + metadata)."""

from __future__ import annotations

import os
import sys

from ..daemon import Daemon
from ..daemon.gateway import GatewayConfig, GatewaySourceFetcher, ObjectGateway
from ..objectstorage import FilesystemBackend
from ..scheduler import Evaluator, Resource, SchedulerService, Scheduling, SchedulingConfig
from ..scheduler.resource import Host
from ..utils import idgen
from .common import base_parser, init_debug, init_logging


def _gateway(args):
    # Backend by config (objectstorage.go:179-212 dispatch): local
    # filesystem by default; signed S3/OSS endpoints when pointed at one.
    from ..objectstorage import make_backend

    if args.backend == "fs":
        backend = FilesystemBackend(args.backend_root)
    else:
        backend = make_backend(
            args.backend, endpoint=args.endpoint,
            access_key=args.access_key, secret_key=args.secret_key,
            region=args.region,
        )
    resource = Resource()
    scheduler = SchedulerService(
        resource, Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
    )
    import socket

    hostname = socket.gethostname()
    host = Host(id=idgen.host_id_v2("127.0.0.1", hostname), hostname=hostname, ip="127.0.0.1")
    resource.store_host(host)
    daemon = Daemon(
        host,
        scheduler,
        storage_root=os.path.join(args.work_dir, "pieces"),
        source_fetcher=GatewaySourceFetcher(backend),
    )
    return ObjectGateway(daemon, backend, GatewayConfig(bucket=args.bucket))


def run(argv=None) -> int:
    p = base_parser("dfstore", "Object storage through the P2P gateway")
    p.add_argument("command", choices=["put", "get", "stat", "rm", "ls", "cp"])
    p.add_argument("key", nargs="?", default="")
    p.add_argument("dst_key", nargs="?", default="", help="destination key (cp)")
    p.add_argument("-f", "--file", default=None, help="local file (put/get)")
    p.add_argument("--bucket", default="dragonfly")
    p.add_argument("--backend", choices=["fs", "s3", "oss", "obs"],
                   default="fs",
                   help="object-storage backend (fs=local dir, "
                        "s3/oss/obs=remote)")
    p.add_argument("--endpoint", default="",
                   help="s3/oss endpoint URL (e.g. http://minio:9000)")
    p.add_argument("--access-key", default=os.environ.get("DF_ACCESS_KEY", ""))
    p.add_argument("--secret-key", default=os.environ.get("DF_SECRET_KEY", ""))
    p.add_argument("--region", default="us-east-1")
    p.add_argument("--backend-root", default=os.path.expanduser("~/.dragonfly/objects"))
    p.add_argument("--work-dir", default=os.path.expanduser("~/.dragonfly/dfstore"))
    args = p.parse_args(argv)
    init_logging(args, "dfstore")
    init_debug(args)
    if args.backend != "fs" and not args.endpoint:
        p.error(f"--backend {args.backend} requires --endpoint")
    gw = _gateway(args)

    if args.command == "put":
        if not args.file or not args.key:
            print("dfstore: put needs KEY and -f FILE", file=sys.stderr)
            return 1
        with open(args.file, "rb") as f:
            meta = gw.put_object(args.key, f.read())
        print(f"dfstore: put {args.key} ({meta.content_length} bytes, etag {meta.etag[:12]})")
        return 0
    if args.command == "get":
        if not args.file or not args.key:
            print("dfstore: get needs KEY and -f FILE", file=sys.stderr)
            return 1
        data = gw.get_object(args.key)
        with open(args.file, "wb") as f:
            f.write(data)
        print(f"dfstore: got {args.key} ({len(data)} bytes) -> {args.file}")
        return 0
    if args.command == "stat":
        if not gw.object_exists(args.key):
            print(f"dfstore: {args.key} not found", file=sys.stderr)
            return 1
        m = gw.head_object(args.key)
        print(f"dfstore: {m.key} length={m.content_length} etag={m.etag}")
        return 0
    if args.command == "rm":
        gw.delete_object(args.key)
        print(f"dfstore: removed {args.key}")
        return 0
    if args.command == "cp":
        m = gw.copy_object(args.key, args.dst_key)
        print(f"dfstore: copied {args.key} -> {m.key}")
        return 0
    # ls
    for m in gw.list_objects(args.key):
        print(f"{m.content_length:>12} {m.key}")
    return 0


if __name__ == "__main__":
    sys.exit(run())
