"""dfdaemon: the peer daemon service binary (reference: cmd/dfget daemon
mode + client/daemon/daemon.go).

Boots the full data plane against a remote scheduler: piece storage
(native engine), HTTP piece server, host announcer, probe agent, and an
optional P2P proxy.  ``--download URL`` performs one download through the
running daemon and exits (smoke mode).
"""

from __future__ import annotations

import os
import socket
import sys
import time

from ..config import DaemonConfig, load_config
from ..daemon import DaemonStorage, UploadManager
from ..daemon.conductor import Conductor
from ..daemon.host_announcer import HostAnnouncer
from ..rpc import HTTPPieceFetcher, RemoteScheduler
from ..scheduler.resource import Host
from ..source import PieceSourceFetcher
from ..utils import idgen
from ..utils.ping import make_host_pinger
from .common import (
    base_parser,
    init_debug,
    init_diagnostics,
    init_flight_recorder,
    init_telemetry,
    init_logging,
    init_tracing,
)


def build(cfg: DaemonConfig, scheduler_url: str):
    """Daemon composition against a wire scheduler (daemon.go:118-417)."""
    if cfg.source:
        from ..source import configure_sources

        configure_sources(cfg.source)
    storage = DaemonStorage(cfg.storage.dir, quota_bytes=cfg.storage.quota_bytes)
    upload = UploadManager(storage, concurrent_limit=cfg.concurrent_upload_limit)

    hostname = socket.gethostname()
    from ..utils.hostinfo import local_ip

    # Advertise a routable address — peers on OTHER machines dial it.
    ip = cfg.server.advertise_ip or local_ip()

    # Auto-issued mTLS (certify analog, scheduler.go:186-222): request
    # this daemon's identity from the manager's cluster CA at boot; the
    # piece plane then serves AND fetches over mutual TLS.
    identity = None
    serve_ssl = fetch_ssl = None
    renewer = None
    if cfg.security.auto_issue:
        if not cfg.manager_addr:
            raise SystemExit("dfdaemon: security.auto_issue needs manager_addr")
        from ..security.ca import IdentityRenewer, PeerIdentity
        from ..security.tls import client_context, server_context

        def _issue_identity():
            ident = PeerIdentity.request_from_manager(
                cfg.manager_addr,
                common_name=f"daemon-{hostname}",
                hostnames=[hostname],
                ips=[ip],
                token=cfg.manager_token or None,
                ttl_hours=cfg.security.cert_ttl_hours,
            )
            if cfg.security.identity_dir:
                ident.write(cfg.security.identity_dir)
            return ident

        identity = _issue_identity()
        serve_ssl = server_context(identity)
        fetch_ssl = client_context(identity)
        # Short-TTL certs stay alive: re-issue at half validity and
        # reload both piece-plane contexts in place.
        renewer = IdentityRenewer(
            identity, _issue_identity, [serve_ssl, fetch_ssl]
        ).start()

    # Native-engine stores serve pieces from the C++ server (sendfile hot
    # path); Python HTTP remains the fallback/TLS server.
    from ..rpc.piece_transport import make_piece_server

    # Bind the CONFIGURED piece port (0 = ephemeral): deployments pin it
    # (k8s containerPort / NetworkPolicies key on it) while test
    # clusters pass 0.
    piece_server = make_piece_server(
        upload, host=cfg.server.host, port=cfg.server.port,
        ssl_context=serve_ssl,
    )
    piece_server.serve()
    channel_creds = None
    if identity is not None and cfg.security.scheduler_grpc_tls:
        # The scheduler's gRPC port runs mTLS when the cluster
        # auto-issues — dial with this daemon's issued identity.
        # (security.scheduler_grpc_tls: false covers mixed clusters
        # whose scheduler port is still plaintext.)
        import grpc as _grpc

        channel_creds = _grpc.ssl_channel_credentials(
            root_certificates=identity.ca_pem,
            private_key=identity.key_pem,
            certificate_chain=identity.cert_pem,
        )

    def scheduler_client_cls(url: str):
        if url.startswith("grpc://"):
            # Streaming variant: per-peer calls ride the bidi
            # announce_peer stream so the scheduler can push
            # mid-download reschedules (unary fallback on stream
            # failure).
            from ..rpc.grpc_transport import GRPCStreamingScheduler

            return GRPCStreamingScheduler(
                url[len("grpc://"):], channel_credentials=channel_creds
            )
        return RemoteScheduler(url)

    # Comma-separated scheduler list → consistent-hash steering: each
    # task's swarm state lives on ONE replica (pkg/balancer semantics,
    # rpc/steering.py); probes pin per host and reach the other replicas
    # via the manager's shared-topology sync.
    scheduler_urls = [u.strip() for u in scheduler_url.split(",") if u.strip()]

    host = Host(
        # The piece port joins the identity so multiple daemons on one
        # machine are distinct hosts (reference: hostname-port host ids,
        # pkg/idgen/host_id.go v1).
        id=idgen.host_id_v2(ip, f"{hostname}-{piece_server.port}"),
        hostname=hostname,
        ip=ip,
        port=cfg.server.port,
        download_port=piece_server.port,
        concurrent_upload_limit=cfg.concurrent_upload_limit,
    )
    if len(scheduler_urls) > 1:
        from ..rpc.steering import SteeringSchedulerClient

        client = SteeringSchedulerClient(
            scheduler_urls, factory=scheduler_client_cls
        )
    else:
        client = scheduler_client_cls(scheduler_urls[0])
    # Declared tenant identity (DESIGN.md §26): stamped on announces and
    # registers; the wire client carries it as client state.
    if cfg.tenant and hasattr(client, "tenant"):
        client.tenant = cfg.tenant
    conductor = Conductor(
        host,
        storage,
        client,
        piece_fetcher=HTTPPieceFetcher(
            client.resolve_host, ssl_context=fetch_ssl, tenant=cfg.tenant
        ),
        source_fetcher=PieceSourceFetcher(),
        concurrent_source_groups=cfg.concurrent_source_groups,
        stream_tee_depth=cfg.stream_tee_depth,
        native_fetch=cfg.native_fetch,
        tenant=cfg.tenant,
    )
    announcer = HostAnnouncer(host, client, tenant=cfg.tenant)

    # Tenant QoS adoption (DESIGN.md §26): schedulers re-publish the
    # manager's tenant_qos table on announce answers (the §24 ring
    # discipline); each announce adopts the newest payload into the
    # upload-path bandwidth caps.  Payload-version comparison is cheap
    # (dict equality on a small table) and malformed payloads are
    # skipped — an adoption bug must not kill the announcer loop.
    adopted: list = [None]

    def _adopt_tenant_qos() -> None:
        payload = getattr(client, "tenant_qos", None)
        if not isinstance(payload, dict) or payload == adopted[0]:
            return
        from ..qos.policy import QoSPolicy

        try:
            policy = QoSPolicy.from_payload(payload)
        except (KeyError, TypeError, ValueError):
            return
        adopted[0] = payload
        upload.set_qos_policy(policy)

    announcer.on_announced = _adopt_tenant_qos
    return {
        "storage": storage,
        "upload": upload,
        "piece_server": piece_server,
        "host": host,
        "client": client,
        "conductor": conductor,
        "announcer": announcer,
        "identity": identity,
        "renewer": renewer,
    }


def run(argv=None) -> int:
    p = base_parser("dfdaemon", "Peer daemon service")
    p.add_argument("--scheduler", required=True, help="scheduler RPC URL")
    p.add_argument("--download", default=None, metavar="URL",
                   help="download one URL through the daemon and exit")
    p.add_argument("-O", "--output", default=None, help="output path (--download)")
    p.add_argument("--seed-peer", action="store_true",
                   help="announce as a seed peer and serve the ObtainSeeds "
                        "endpoint the scheduler triggers cold tasks through")
    p.add_argument("--pex-port", type=int, default=-1, metavar="PORT",
                   help="enable networked peer-exchange gossip on this UDP "
                        "port (0 = ephemeral, -1 = disabled)")
    p.add_argument("--pex-join", default="", metavar="HOST:PORT[,...]",
                   help="gossip seed addresses to join")
    args = p.parse_args(argv)
    init_logging(args, "dfdaemon")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(DaemonConfig, args.config)
    init_flight_recorder(args, cfg.tracing, "dfdaemon")
    init_telemetry(args, cfg.telemetry, "dfdaemon")
    init_diagnostics(cfg.metrics, "dfdaemon")
    parts = build(cfg, args.scheduler)

    pex = None
    if args.pex_port >= 0:
        # Networked gossip (pex memberlist analog): piece-holder discovery
        # that keeps serving through scheduler outages.
        from ..daemon.pex import MemberMeta, PeerExchange
        from ..daemon.pex_net import NetworkedGossipBus

        seeds = []
        for part in filter(None, args.pex_join.split(",")):
            h, _, pp = part.rpartition(":")
            seeds.append((h or "127.0.0.1", int(pp)))
        bus = NetworkedGossipBus(
            host=cfg.server.host, port=args.pex_port, seeds=seeds,
            advertise_ip=parts["host"].ip,
        )
        pex = PeerExchange(
            MemberMeta(
                host_id=parts["host"].id,
                ip=parts["host"].ip,
                port=parts["piece_server"].port,
            ),
            bus,
        )
        pex.serve()
        parts["conductor"].pex = pex

        # Resolver chain: scheduler mirror first, gossip metadata second —
        # piece fetches keep resolving when the control plane is down.
        client = parts["client"]

        def resolve(host_id):
            try:
                return client.resolve_host(host_id)
            except KeyError:
                m = pex.member(host_id)
                if m is None:
                    raise
                return m.ip, m.port

        from ..rpc import HTTPPieceFetcher

        # Keep the mTLS client identity (and the requester-pays tenant
        # stamp) through the resolver swap.
        old_fetcher = parts["conductor"].piece_fetcher
        parts["conductor"].piece_fetcher = HTTPPieceFetcher(
            resolve, ssl_context=getattr(old_fetcher, "ssl_context", None),
            tenant=getattr(old_fetcher, "tenant", ""),
        )
        print(f"dfdaemon: pex gossip on udp:{bus.address[1]}", flush=True)

    seeder = None
    if args.seed_peer:
        # Seed mode (seeder.go:41-151): announce as SUPER_SEED and carry
        # the control port in the announce so the scheduler's trigger
        # client (scheduler/seed_client.py) can dial /obtain_seeds.
        from ..daemon.seeder import Seeder
        from ..utils.types import HostType

        parts["host"].type = HostType.SUPER_SEED
        seeder = Seeder(parts["conductor"], parts["storage"])

    # Control API (daemon Download RPC analog): loopback by DEFAULT —
    # /download writes local files on behalf of same-machine dfget.
    # `control_host` may widen the bind for trusted pod/compose networks
    # (deploy/config/daemon.yaml does), which trades that isolation for
    # in-network drivability — never expose it on a routable interface
    # outside such a boundary.
    from ..rpc.daemon_control import DaemonControlServer, write_state

    control = DaemonControlServer(
        parts["conductor"], piece_size=cfg.piece_size,
        host=cfg.control_host, port=cfg.control_port,
        # The seeder rides the loopback server too (not just the public
        # seed endpoint) so the vsock guest surface — which reuses this
        # server's handler — can actually serve /obtain_seeds.
        seeder=seeder,
    )
    control.serve()
    if cfg.control_vsock_port >= 0:
        # VM-guest wire (pkg/rpc/vsock.go): same control handler, vsock
        # listener — guests dial vsock://2:<port> with no network stack.
        from ..rpc.vsock import vsock_available

        try:
            if not vsock_available():
                raise OSError("AF_VSOCK unavailable")
            vport = control.serve_vsock(cfg.control_vsock_port)
            print(f"dfdaemon: control also on vsock:{vport}", flush=True)
        except OSError as exc:
            # socket() succeeding does not guarantee bind() does (module
            # loaded, no transport registered) — degrade to TCP-only
            # rather than crashing the daemon.
            import logging

            logging.getLogger("dragonfly2_tpu.cli.dfdaemon").warning(
                "control_vsock_port set but vsock is unusable: %s", exc
            )
    if args.seed_peer:
        # Separate PUBLIC surface for the scheduler's cross-process
        # trigger: /obtain_seeds (+/healthy) only, bound on the serving
        # address and advertised via the host announce's port.
        seed_endpoint = DaemonControlServer(
            parts["conductor"], piece_size=cfg.piece_size,
            host=cfg.server.host, seeder=seeder, public=True,
        )
        seed_endpoint.serve()
        parts["host"].port = seed_endpoint.address[1]

    parts["announcer"].serve()

    if args.download:
        content_length = parts["conductor"].probe_content_length(args.download)
        if content_length is None or content_length < 0:
            print(f"dfdaemon: cannot size {args.download}", file=sys.stderr)
            return 1
        result = parts["conductor"].download(
            args.download, piece_size=cfg.piece_size, content_length=content_length
        )
        if not result.ok:
            print("dfdaemon: download failed", file=sys.stderr)
            return 1
        if args.output:
            with open(args.output, "wb") as f:
                f.write(parts["storage"].read_task_bytes(result.task_id))
        mode = "back-to-source" if result.back_to_source else "p2p"
        print(f"dfdaemon: {result.pieces} pieces via {mode} in {result.cost_s:.2f}s")
        return 0

    if cfg.proxy.sni_enable:
        from ..daemon.sni import SNIProxy
        from ..security.ca import CertificateAuthority

        class _DaemonShim:
            """SNIProxy's daemon surface over the CLI's parts."""

            def __init__(self, conductor, storage):
                self.conductor = conductor
                self._storage = storage

            def download(self, url, piece_size, content_length=None):
                return self.conductor.download(
                    url, piece_size=piece_size, content_length=content_length
                )

            def read_task_bytes(self, task_id):
                return self._storage.read_task_bytes(task_id)

        # Persistent: restarts keep the same trust anchor, so clients that
        # installed sni-ca.pem don't break on every deploy.
        ca = CertificateAuthority.persistent(
            os.path.join(cfg.storage.dir, "sni-ca")
        )
        ca_path = os.path.join(cfg.storage.dir, "sni-ca.pem")
        os.makedirs(cfg.storage.dir, exist_ok=True)
        with open(ca_path, "wb") as f:
            f.write(ca.cert_pem)
        sni = SNIProxy(
            _DaemonShim(parts["conductor"], parts["storage"]),
            ca=ca,
            hijack=cfg.proxy.sni_hijack_hosts,
            host=cfg.server.host,
            port=cfg.proxy.sni_port,
            piece_size=cfg.piece_size,
        )
        sni.serve()
        print(f"dfdaemon: SNI proxy on :{sni.port}, trust anchor {ca_path}")

    # Discovery state file so dfget finds or spawns this daemon
    # (root.go:234-260).  write_state uses state_path() — the SAME
    # resolution dfget reads, so writer and reader can never disagree.
    state_file = write_state(control.url)

    # Probe loop against the remote scheduler.
    ping = make_host_pinger()
    print(
        f"dfdaemon: serving pieces on :{parts['piece_server'].port}, "
        f"control {control.url} (state {state_file}), "
        f"scheduler {args.scheduler} (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(cfg.probe_interval_s)
            try:
                targets = parts["client"].sync_probes_start(parts["host"])
                results = []
                for t in targets:
                    rtt = ping(t)
                    if rtt is not None:
                        results.append((t.id, rtt))
                if results:
                    parts["client"].sync_probes_finished(parts["host"], results)
            except Exception as exc:  # noqa: BLE001 — probe failures must not kill the daemon
                import logging

                logging.getLogger("dragonfly2_tpu.cli.dfdaemon").debug(
                    "probe sweep failed: %s", exc
                )
    except KeyboardInterrupt:
        parts["piece_server"].stop()
        return 0


if __name__ == "__main__":
    sys.exit(run())
