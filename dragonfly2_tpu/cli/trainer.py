"""trainer service binary (reference: cmd/trainer + trainer/trainer.go).

Boots the trainer composition (registry client, ingest service, training)
on a TPU-VM.  ``--train-once DIR`` ingests columnar shards from DIR and
runs one training round synchronously (the smoke/e2e mode); without it the
process serves and waits for announcer uploads.
"""

from __future__ import annotations

import glob
import os
import sys
import time

from ..config import TrainerConfigFile, load_config
from ..manager.registry import ModelRegistry
from ..trainer.service import TrainerService
from ..trainer.train import TrainConfig
from .common import (
    base_parser,
    init_debug,
    init_flight_recorder,
    init_telemetry,
    init_logging,
    init_tracing,
)


def run(argv=None) -> int:
    p = base_parser("trainer", "Model training service")
    p.add_argument("--train-once", default=None, metavar="DIR",
                   help="ingest DIR's columnar shards, train one round, exit")
    p.add_argument("--scheduler-id", default="scheduler-local")
    p.add_argument("--manager", default=None, metavar="URL",
                   help="remote manager REST URL (models publish there)")
    p.add_argument("--manager-token", default=None, help="bearer token for the manager")
    args = p.parse_args(argv)
    init_logging(args, "trainer")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(TrainerConfigFile, args.config)
    init_flight_recorder(args, cfg.tracing, "trainer")
    init_telemetry(args, cfg.telemetry, "trainer")
    manager_addr = args.manager or cfg.manager_addr
    if manager_addr and manager_addr.startswith("grpc://"):
        from ..rpc.grpc_transport import GRPCRemoteRegistry

        registry = GRPCRemoteRegistry(
            manager_addr[len("grpc://"):], token=args.manager_token or ""
        )
    elif manager_addr:
        from ..rpc import RemoteRegistry

        registry = RemoteRegistry(manager_addr, token=args.manager_token)
    else:
        registry = ModelRegistry()
    service = TrainerService(
        registry,
        # --train-once reads local shards (no staging); serve mode ingests
        # remote uploads into data_dir.
        data_dir=None if args.train_once else cfg.data_dir,
        train_config=TrainConfig(
            epochs=cfg.training.epochs,
            learning_rate=cfg.training.learning_rate,
            warmup_steps=cfg.training.warmup_steps,
        ),
    )

    if args.train_once:
        session = service.open_train_stream(
            ip="127.0.0.1", hostname=os.uname().nodename, scheduler_id=args.scheduler_id
        )
        dl = sorted(glob.glob(os.path.join(args.train_once, "download*.dfc")))
        topo = sorted(glob.glob(os.path.join(args.train_once, "networktopology*.dfc")))
        if not dl:
            print(f"trainer: no download*.dfc shards in {args.train_once}", file=sys.stderr)
            return 1
        for path in dl:
            session.send_download_shard(path)
        for path in topo:
            session.send_network_topology_shard(path)
        key = session.close_and_train()
        run_rec = service.runs[key]
        if run_rec.error:
            print(f"trainer: run failed: {run_rec.error}", file=sys.stderr)
            return 1
        for name, metrics in run_rec.metrics.items():
            print(
                f"trainer: {name}: mae={metrics.mae:.4f} mse={metrics.mse:.4f} "
                f"f1={metrics.f1:.3f} ({run_rec.download_rows} rows)"
            )
        for mid in run_rec.models:
            m = registry.get(mid)
            print(f"trainer: registered {m.name} v{m.version} ({m.type})")
        return 0

    # Serve mode: real ingest servers (trainer/rpcserver analog) — HTTP
    # chunked uploads, plus the gRPC Train client-stream when configured.
    from ..rpc import TrainerHTTPServer

    http_server = TrainerHTTPServer(
        service, host=cfg.server.host, port=cfg.server.port
    )
    http_server.serve()
    # Self-driving lifecycle plane (DESIGN.md §29): with a REST manager
    # attached, every ingested record also streams into the continuous
    # train→export→rollout loop — candidates register and walk
    # SHADOW→CANARY→ACTIVE with zero human steps (schedulers' rollout
    # reporters supply the evaluation evidence).
    lifecycle_daemon = None
    if (
        cfg.lifecycle.enable
        and manager_addr
        and not manager_addr.startswith("grpc://")
    ):
        from ..lifecycle import LifecycleConfig, LifecycleDaemon
        from ..rollout.client import RolloutRESTClient

        lc = cfg.lifecycle
        # No StateBackend here (that is the manager's): lifecycle
        # watermarks/lineage live in the daemon's in-memory store, so
        # the epoch cadence holds for the life of this process; the
        # manager-side rollout rows stay durable either way.
        lifecycle_daemon = LifecycleDaemon(
            registry,
            RolloutRESTClient(manager_addr, token=args.manager_token),
            config=LifecycleConfig(
                scheduler_id=args.scheduler_id,
                model_name=lc.model_name,
                regions=tuple(lc.regions),
                epoch_records=lc.epoch_records,
                max_steps_per_epoch=lc.max_steps_per_epoch,
                min_joined=lc.min_joined,
                arbitration_margin=lc.arbitration_margin,
                canary_percent=lc.canary_percent,
                interval_s=lc.interval_s,
                trainer_batch_size=lc.trainer_batch_size,
            ),
        )
        service.online_sink = lifecycle_daemon
        lifecycle_daemon.serve()
        print(
            f"trainer: lifecycle daemon on (epoch every {lc.epoch_records} "
            f"records, regions={list(lc.regions) or ['global only']})",
            flush=True,
        )
    elif cfg.lifecycle.enable:
        print(
            "trainer: lifecycle.enable set but no REST manager attached; "
            "lifecycle daemon not started",
            flush=True,
        )
    grpc_server = None
    if cfg.server.grpc_port >= 0:
        from ..rpc.grpc_transport import TrainerGRPCServer

        grpc_server = TrainerGRPCServer(
            service, host=cfg.server.host, port=cfg.server.grpc_port
        )
        grpc_server.serve()
    print(
        f"trainer: ingest on {http_server.url}"
        + (f" and grpc on {grpc_server.target}" if grpc_server else "")
        + f", staging in {cfg.data_dir} (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        http_server.stop()
        if grpc_server is not None:
            grpc_server.stop()
        return 0


if __name__ == "__main__":
    sys.exit(run())
