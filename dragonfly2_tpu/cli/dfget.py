"""dfget: one-shot P2P-capable download (reference: cmd/dfget + client/dfget).

Embeds the daemon + scheduler stack in-process (the reference spawns a
daemon sidecar; single-binary embedding is the library-mode equivalent),
downloads the URL piece-by-piece through the conductor — P2P when other
daemons share the process/registry, back-to-source otherwise — and
assembles the output file.
"""

from __future__ import annotations

import os
import sys

from ..daemon import Daemon
from ..scheduler import Evaluator, Resource, SchedulerService, Scheduling, SchedulingConfig
from ..scheduler.resource import Host
from ..source import PieceSourceFetcher
from ..utils import idgen
from .common import base_parser, init_debug, init_logging, init_tracing


def _resolve_recursive_root(url: str):
    """file:// (or bare-path) recursive source → absolute dir, or an
    error string."""
    import urllib.parse

    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme not in ("", "file"):
        return None, "--recursive supports file:// sources only"
    # abspath: a relative bare path must not become a URL netloc when
    # "file://" + path is parsed back (urlsplit would eat the first
    # component as the host).
    src_root = os.path.abspath(
        urllib.parse.unquote(parsed.path) if parsed.scheme == "file" else url
    )
    if not os.path.isdir(src_root):
        return None, "--recursive needs a directory source"
    return src_root, None


def _iter_tree(src_root: str, output: str):
    """Walk the source tree: creates destination dirs (empty ones too),
    reports skipped symlinks/unreadables on stderr, yields
    (file_url, rel, dst, size) for every downloadable file."""
    import urllib.parse

    for dirpath, dirs, files in os.walk(src_root):
        # Preserve empty directories: the restored tree must be
        # structurally identical to the source.
        for d in list(dirs):
            if os.path.islink(os.path.join(dirpath, d)):
                # os.walk(followlinks=False) won't descend — an empty
                # dir here would be a silently incomplete restore.
                print(
                    f"dfget: skipped symlinked dir "
                    f"{os.path.relpath(os.path.join(dirpath, d), src_root)}",
                    file=sys.stderr,
                )
                dirs.remove(d)
                continue
            os.makedirs(
                os.path.join(output, os.path.relpath(os.path.join(dirpath, d), src_root)),
                exist_ok=True,
            )
        for name in files:
            src = os.path.join(dirpath, name)
            rel = os.path.relpath(src, src_root)
            dst = os.path.join(output, rel)
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            try:
                size = os.path.getsize(src)
            except OSError as exc:
                # Dangling symlink etc: report and continue.
                print(f"dfget: skipped {rel}: {exc}", file=sys.stderr)
                continue
            # Percent-encode: '#'/'?' in filenames must survive urlsplit.
            yield "file://" + urllib.parse.quote(src), rel, dst, size


def run(argv=None) -> int:
    p = base_parser("dfget", "Download a file through the P2P stack")
    p.add_argument("url", help="source URL (file://, http://, https://)")
    p.add_argument("-O", "--output", required=True, help="output file path")
    p.add_argument("--piece-size", type=int, default=4 << 20)
    p.add_argument("--work-dir", default=None, help="piece storage dir")
    p.add_argument("--recursive", action="store_true",
                   help="download a directory tree (file:// sources)")
    p.add_argument("--daemon", action="store_true",
                   help="download through a running dfdaemon, spawning one "
                        "if absent (requires --scheduler for the spawn)")
    p.add_argument("--scheduler", default=None,
                   help="scheduler RPC URL (used when spawning a daemon)")
    args = p.parse_args(argv)
    init_logging(args, "dfget")
    init_debug(args)
    init_tracing(args)

    if args.daemon:
        # Reference path: dfget talks to a long-lived daemon, spawning it
        # when absent (cmd/dfget/cmd/root.go:234-260), so downloads share
        # one piece store + upload server across invocations.
        from ..rpc.daemon_control import (
            download_via_daemon,
            ensure_daemon,
            find_healthy_daemon,
        )

        if args.scheduler:
            try:
                daemon_url = ensure_daemon(
                    args.scheduler,
                    extra_args=["--config", args.config] if args.config else None,
                )
            except TimeoutError as exc:
                print(f"dfget: {exc}", file=sys.stderr)
                return 1
        else:
            daemon_url = find_healthy_daemon()
            if daemon_url is None:
                print(
                    "dfget: no running daemon and no --scheduler to spawn one",
                    file=sys.stderr,
                )
                return 1
        if args.recursive:
            # Directory tree through the DAEMON control API (reference:
            # rpcserver.go:407+ recursive downloads go through the
            # long-lived daemon like single files do): every file shares
            # the daemon's piece store and upload server.
            src_root, err = _resolve_recursive_root(args.url)
            if err:
                print(f"dfget: {err}", file=sys.stderr)
                return 1
            count = 0
            for url, rel, dst, _size in _iter_tree(src_root, args.output):
                result = download_via_daemon(
                    url, daemon_url, output=dst, piece_size=args.piece_size
                )
                if not result.get("ok"):
                    print(f"dfget: failed {rel}: {result}", file=sys.stderr)
                    return 1
                count += 1
            print(
                f"dfget: downloaded {count} files through daemon "
                f"-> {args.output}"
            )
            return 0
        result = download_via_daemon(
            args.url, daemon_url, output=args.output,
            piece_size=args.piece_size,
        )
        if not result.get("ok"):
            print(f"dfget: daemon download failed: {result}", file=sys.stderr)
            return 1
        mode = "back-to-source" if result.get("back_to_source") else "p2p"
        print(
            f"dfget: {result['pieces']} pieces via {mode} through daemon "
            f"in {result['cost_s']:.2f}s -> {args.output}"
        )
        return 0

    import socket
    import tempfile

    hostname = socket.gethostname()
    ip = "127.0.0.1"
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="dfget-")

    resource = Resource()
    scheduler = SchedulerService(
        resource, Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
    )
    host = Host(
        id=idgen.host_id_v2(ip, hostname), hostname=hostname, ip=ip
    )
    resource.store_host(host)
    source = PieceSourceFetcher()
    daemon = Daemon(
        host,
        scheduler,
        storage_root=os.path.join(work_dir, "storage"),
        source_fetcher=source,
    )

    if args.recursive:
        # Directory tree (reference: recursive dir download,
        # rpcserver.go:407+): each file goes through the same P2P path.
        src_root, err = _resolve_recursive_root(args.url)
        if err:
            print(f"dfget: {err}", file=sys.stderr)
            return 1
        count = 0
        for url, rel, dst, size in _iter_tree(src_root, args.output):
            result = daemon.download(
                url, piece_size=args.piece_size, content_length=size
            )
            if not result.ok:
                print(f"dfget: failed {rel}", file=sys.stderr)
                return 1
            with open(dst, "wb") as out:
                out.write(daemon.read_task_bytes(result.task_id))
            count += 1
        print(f"dfget: downloaded {count} files -> {args.output}")
        return 0

    content_length = source.content_length(args.url)
    if content_length < 0:
        print(f"dfget: cannot determine content length of {args.url}", file=sys.stderr)
        return 1

    result = daemon.download(
        args.url, piece_size=args.piece_size, content_length=content_length
    )
    if not result.ok:
        print("dfget: download failed", file=sys.stderr)
        return 1

    with open(args.output, "wb") as out:
        out.write(daemon.read_task_bytes(result.task_id))
    mode = "back-to-source" if result.back_to_source else "p2p"
    print(
        f"dfget: {content_length} bytes in {result.cost_s:.2f}s "
        f"({result.pieces} pieces, {mode}) -> {args.output}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(run())
