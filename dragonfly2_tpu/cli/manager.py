"""manager service binary (reference: cmd/manager + manager/manager.go).

Boots the control-plane composition: model registry (versioned blobs),
cluster manager with keepalive TTLs, searcher, dynconfig server, job
broker.  ``--list-models DIR`` prints the registry persisted under DIR
(the ops inspection path the reference serves via console/REST).
"""

from __future__ import annotations

import sys
import time

from ..config import ManagerConfig, load_config
from ..jobs import JobQueue
from ..manager import ClusterManager, ModelRegistry, Searcher
from ..manager.registry import BlobStore
from .common import base_parser, init_debug, init_logging, init_tracing


def build(cfg: ManagerConfig):
    import os

    # ONE durable state backend for every manager surface (manager/
    # state.py seam): registry rows, CRUD rows, the job broker, the
    # shared topology cache, users — a restart reloads all of it from
    # one place, and the HA story swaps one backend, not five files.
    from ..manager.state import make_state_backend, migrate_legacy_sqlite

    backend = make_state_backend(
        os.path.join(cfg.registry.blob_dir, "manager-state.db")
    )
    # Pre-seam deployments kept per-store files; import them once so an
    # upgrade never silently drops models/CRUD rows.
    migrated = migrate_legacy_sqlite(
        backend,
        models_db=os.path.join(cfg.registry.blob_dir, "manager.db"),
        crud_db=os.path.join(cfg.registry.blob_dir, "crud.db"),
    )
    if migrated:
        print(f"manager: migrated legacy state {migrated}", flush=True)
    registry = ModelRegistry(
        BlobStore(cfg.registry.blob_dir), backend=backend,
    )
    clusters = ClusterManager(keepalive_ttl=cfg.keepalive_ttl_s)
    from ..manager.crud import CrudStore

    crud = CrudStore(backend=backend)
    crud.ensure_default_cluster()
    objectstorage = None
    if cfg.objectstorage:
        from ..objectstorage import make_backend

        kwargs = dict(cfg.objectstorage)
        objectstorage = make_backend(kwargs.pop("kind", "fs"), **kwargs)
    # Rollout controller (rollout/controller.py): evidence-gated
    # SHADOW→CANARY→ACTIVE promotion with auto-rollback; its rows ride
    # the same state backend, so in-flight rollouts survive a bounce.
    from ..rollout import RolloutController, RolloutGuardrails

    rollout = RolloutController(
        registry,
        guardrails=RolloutGuardrails(
            min_shadow_samples=cfg.rollout.min_shadow_samples,
            min_canary_samples=cfg.rollout.min_canary_samples,
            max_regret_ratio=cfg.rollout.max_regret_ratio,
            regret_slack=cfg.rollout.regret_slack,
            max_inversion_ratio=cfg.rollout.max_inversion_ratio,
            max_psi=cfg.rollout.max_psi,
            canary_percent=cfg.rollout.canary_percent,
        ),
        backend=backend,
    )
    # NOTE: no DynconfigServer here — the dynconfig payload schedulers
    # poll is served straight from the CrudStore's cluster rows
    # (/api/v1/clusters/<id>:config), one source of truth.
    return {
        "registry": registry,
        "clusters": clusters,
        "searcher": Searcher(),
        "jobs": JobQueue(backend=backend),
        "crud": crud,
        "objectstorage": objectstorage,
        "state_backend": backend,
        "rollout": rollout,
    }


def run(argv=None) -> int:
    p = base_parser("manager", "Control-plane manager service")
    p.add_argument("--list-models", action="store_true")
    args = p.parse_args(argv)
    init_logging(args, "manager")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(ManagerConfig, args.config)
    parts = build(cfg)

    if args.list_models:
        models = parts["registry"].list()
        if not models:
            print("manager: registry empty")
        for m in models:
            print(
                f"manager: {m.name} v{m.version} type={m.type} state={m.state.value} "
                f"scheduler={m.scheduler_id} eval={m.evaluation}"
            )
        return 0

    from ..manager.rest import ManagerRESTServer

    auth = {}
    if cfg.token_secret:
        from ..manager.users import UserStore
        from ..security.tokens import TokenIssuer, TokenVerifier

        secret = cfg.token_secret.encode()
        # users_db (if set) keeps its own file for operators who isolate
        # credentials; default shares the one state backend.  Legacy
        # users/pats tables in that file import once.
        if cfg.users_db:
            from ..manager.state import SQLiteBackend, migrate_legacy_sqlite

            user_backend = SQLiteBackend(cfg.users_db)
            migrate_legacy_sqlite(user_backend, users_db=cfg.users_db)
            users = UserStore(backend=user_backend)
        else:
            users = UserStore(backend=parts["state_backend"])
        if cfg.root_password:
            users.ensure_root(cfg.root_password)
        auth = {
            "token_verifier": TokenVerifier(secret),
            "token_issuer": TokenIssuer(secret),
            "users": users,
        }
        if cfg.oauth_providers:
            from ..manager.oauth import OAuthProvider, OAuthSignin

            oauth = OAuthSignin(users)
            for p in cfg.oauth_providers:
                oauth.register(OAuthProvider(**p))
            auth["oauth"] = oauth
    from ..rpc.ratelimit import maybe_bucket

    bucket = maybe_bucket(cfg.server.rate_limit_qps, cfg.server.rate_limit_burst)
    ca = None
    if cfg.ca_dir:
        try:
            from ..security.ca import CertificateAuthority
        except ImportError:
            # `cryptography` absent: serve without the CA surface rather
            # than dying at boot — identity issuance degrades to 404,
            # everything else (registry, jobs, topology) keeps working.
            print("manager: ca_dir set but `cryptography` unavailable; "
                  "serving without CA", flush=True)
        else:
            # Persistent: restarts keep the cluster trust root, so issued
            # peer identities stay valid across a manager bounce.
            ca = CertificateAuthority.persistent(cfg.ca_dir)
    rest = ManagerRESTServer(
        parts["registry"], parts["clusters"], parts["searcher"],
        host=cfg.server.host, port=cfg.server.port,
        jobqueue=parts["jobs"], crud=parts["crud"],
        objectstorage=parts["objectstorage"],
        rate_limit=bucket,
        ca=ca,
        state_backend=parts["state_backend"],
        jobs_min_requeue_s=cfg.jobs_min_requeue_s,
        rollout=parts["rollout"],
        **auth,
    )
    rest.serve()
    grpc_server = None
    if cfg.server.grpc_port >= 0:
        from ..rpc.grpc_transport import ManagerGRPCServer

        grpc_server = ManagerGRPCServer(
            parts["registry"], parts["clusters"], parts["searcher"],
            host=cfg.server.host, port=cfg.server.grpc_port,
            # Same RBAC as REST, same credentials: session tokens AND PATs;
            # same SHARED rate-limit bucket (qps bounds the service).
            token_verifier=auth.get("token_verifier"),
            users=auth.get("users"),
            rate_limit=bucket,
            ca=ca,
        )
        grpc_server.serve()
    # flush: under a pipe (supervisors, e2e harnesses) the ready line must
    # be visible immediately, not at buffer-fill.
    print(
        f"manager: serving REST on {rest.url}"
        + (f" and grpc on {grpc_server.target}" if grpc_server else "")
        + " (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rest.stop()
        if grpc_server is not None:
            grpc_server.stop()
        return 0


if __name__ == "__main__":
    sys.exit(run())
