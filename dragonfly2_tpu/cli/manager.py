"""manager service binary (reference: cmd/manager + manager/manager.go).

Boots the control-plane composition: model registry (versioned blobs),
cluster manager with keepalive TTLs, searcher, dynconfig server, job
broker.  ``--list-models DIR`` prints the registry persisted under DIR
(the ops inspection path the reference serves via console/REST).
"""

from __future__ import annotations

import sys
import time

from ..config import ManagerConfig, load_config
from ..jobs import JobQueue
from ..manager import ClusterManager, ModelRegistry, Searcher
from ..manager.registry import BlobStore
from .common import (
    base_parser,
    init_debug,
    init_flight_recorder,
    init_telemetry,
    init_logging,
    init_tracing,
)


def _build_consumers(cfg: ManagerConfig, backend, blob_store):
    """The backend-fed composition pieces: rebuilt wholesale by the
    standby every time the replication follower applies a batch (their
    in-memory caches must track the replicated rows)."""
    from ..manager.crud import CrudStore
    from ..rollout import RolloutController, RolloutGuardrails

    registry = ModelRegistry(blob_store, backend=backend)
    crud = CrudStore(backend=backend)
    rollout = RolloutController(
        registry,
        guardrails=RolloutGuardrails(
            min_shadow_samples=cfg.rollout.min_shadow_samples,
            min_canary_samples=cfg.rollout.min_canary_samples,
            max_regret_ratio=cfg.rollout.max_regret_ratio,
            regret_slack=cfg.rollout.regret_slack,
            max_inversion_ratio=cfg.rollout.max_inversion_ratio,
            max_psi=cfg.rollout.max_psi,
            canary_percent=cfg.rollout.canary_percent,
        ),
        backend=backend,
    )
    return {
        "registry": registry,
        "crud": crud,
        "rollout": rollout,
        "jobs": JobQueue(backend=backend),
    }


def build(cfg: ManagerConfig, *, replicate_from: str = ""):
    import os
    import socket as _socket

    # ONE durable state backend for every manager surface (manager/
    # state.py seam): registry rows, CRUD rows, the job broker, the
    # shared topology cache, users — a restart reloads all of it from
    # one place, and the HA story swaps one backend, not five files.
    from ..manager.state import make_state_backend, migrate_legacy_sqlite

    replicate_from = replicate_from or cfg.ha.replicate_from
    ha_enabled = bool(cfg.ha.enable or replicate_from)
    backend = make_state_backend(
        os.path.join(cfg.registry.blob_dir, "manager-state.db")
    )
    ha = None
    if ha_enabled:
        from ..manager.replication import ReplicatedStateBackend

        role = "standby" if replicate_from else "leader"
        node_id = cfg.ha.node_id or (
            f"mgr-{_socket.gethostname()}-{cfg.server.port}"
        )
        ha = backend = ReplicatedStateBackend(
            backend,
            node_id=node_id,
            role=role,
            lease_ttl_s=cfg.ha.lease_ttl_s,
            lease_secret=cfg.ha.lease_secret,
        )
    if not replicate_from:
        # Pre-seam deployments kept per-store files; import them once so
        # an upgrade never silently drops models/CRUD rows.  A standby
        # never migrates — its state comes from the leader's snapshot.
        migrated = migrate_legacy_sqlite(
            backend,
            models_db=os.path.join(cfg.registry.blob_dir, "manager.db"),
            crud_db=os.path.join(cfg.registry.blob_dir, "crud.db"),
        )
        if migrated:
            print(f"manager: migrated legacy state {migrated}", flush=True)
    # HA replicates artifacts WITH their registry rows (KVBlobStore rides
    # the same log); the single-node form keeps the blob directory.
    if ha_enabled:
        from ..manager.registry import KVBlobStore

        blob_store = KVBlobStore(backend)
    else:
        blob_store = BlobStore(cfg.registry.blob_dir)
    clusters = ClusterManager(keepalive_ttl=cfg.keepalive_ttl_s)
    objectstorage = None
    if cfg.objectstorage:
        from ..objectstorage import make_backend

        kwargs = dict(cfg.objectstorage)
        objectstorage = make_backend(kwargs.pop("kind", "fs"), **kwargs)
    # Rollout controller (rollout/controller.py): evidence-gated
    # SHADOW→CANARY→ACTIVE promotion with auto-rollback; its rows ride
    # the same state backend, so in-flight rollouts survive a bounce.
    # On a standby the boot-time reconciliation runs under applying()
    # (derived state, not new client mutations).
    if ha is not None and ha.role == "standby":
        with ha.applying():
            consumers = _build_consumers(cfg, backend, blob_store)
    else:
        consumers = _build_consumers(cfg, backend, blob_store)
        consumers["crud"].ensure_default_cluster()
    # NOTE: no DynconfigServer here — the dynconfig payload schedulers
    # poll is served straight from the CrudStore's cluster rows
    # (/api/v1/clusters/<id>:config), one source of truth.
    return {
        "registry": consumers["registry"],
        "clusters": clusters,
        "searcher": Searcher(),
        "jobs": consumers["jobs"],
        "crud": consumers["crud"],
        "objectstorage": objectstorage,
        "state_backend": backend,
        "rollout": consumers["rollout"],
        "ha": ha,
        "blob_store": blob_store,
    }


def run(argv=None) -> int:
    p = base_parser("manager", "Control-plane manager service")
    p.add_argument("--list-models", action="store_true")
    p.add_argument(
        "--replicate-from", default="", metavar="URL",
        help="boot as a hot standby tailing this leader's replication "
             "log; promotes itself when the leader's lease expires",
    )
    args = p.parse_args(argv)
    init_logging(args, "manager")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(ManagerConfig, args.config)
    init_flight_recorder(args, cfg.tracing, "manager")
    init_telemetry(args, cfg.telemetry, "manager")
    parts = build(cfg, replicate_from=args.replicate_from)

    if args.list_models:
        models = parts["registry"].list()
        if not models:
            print("manager: registry empty")
        for m in models:
            print(
                f"manager: {m.name} v{m.version} type={m.type} state={m.state.value} "
                f"scheduler={m.scheduler_id} eval={m.evaluation}"
            )
        return 0

    from ..manager.rest import ManagerRESTServer

    # A node configured as leader first asks its peers (if any) whether
    # a higher term already exists: followers PULL, so nothing would
    # otherwise deliver a successor's term to a restarted fenced leader
    # — it would boot at its stale term and accept writes again.  With a
    # higher term observed it demotes itself and tails that peer.
    replicate_from = args.replicate_from or cfg.ha.replicate_from
    ha = parts["ha"]
    if ha is not None and ha.role == "leader" and cfg.ha.peers:
        from ..manager.replication import probe_peer_term

        peer_term, peer_url = probe_peer_term(cfg.ha.peers)
        if peer_term > ha.term:
            ha.observe_term(peer_term)
            replicate_from = peer_url
            print(
                f"manager: peer {peer_url} holds term {peer_term}; "
                "joining as standby", flush=True,
            )

    auth = {}
    if cfg.token_secret:
        from ..manager.users import UserStore
        from ..security.tokens import TokenIssuer, TokenVerifier

        secret = cfg.token_secret.encode()
        # users_db (if set) keeps its own file for operators who isolate
        # credentials; default shares the one state backend.  Legacy
        # users/pats tables in that file import once.
        if cfg.users_db:
            from ..manager.state import SQLiteBackend, migrate_legacy_sqlite

            user_backend = SQLiteBackend(cfg.users_db)
            migrate_legacy_sqlite(user_backend, users_db=cfg.users_db)
            users = UserStore(backend=user_backend)
        else:
            users = UserStore(backend=parts["state_backend"])
        if cfg.root_password and not (
            parts["ha"] is not None and parts["ha"].role == "standby"
        ):
            # A standby never seeds accounts — the root user replicates
            # from the leader like every other row.
            users.ensure_root(cfg.root_password)
        auth = {
            "token_verifier": TokenVerifier(secret),
            "token_issuer": TokenIssuer(secret),
            "users": users,
        }
        if cfg.oauth_providers:
            from ..manager.oauth import OAuthProvider, OAuthSignin

            oauth = OAuthSignin(users)
            for p in cfg.oauth_providers:
                oauth.register(OAuthProvider(**p))
            auth["oauth"] = oauth
    from ..rpc.ratelimit import maybe_bucket

    bucket = maybe_bucket(cfg.server.rate_limit_qps, cfg.server.rate_limit_burst)
    ca = None
    if cfg.ca_dir:
        try:
            from ..security.ca import CertificateAuthority
        except ImportError:
            # `cryptography` absent: serve without the CA surface rather
            # than dying at boot — identity issuance degrades to 404,
            # everything else (registry, jobs, topology) keeps working.
            print("manager: ca_dir set but `cryptography` unavailable; "
                  "serving without CA", flush=True)
        else:
            # Persistent: restarts keep the cluster trust root, so issued
            # peer identities stay valid across a manager bounce.
            ca = CertificateAuthority.persistent(cfg.ca_dir)
    rest = ManagerRESTServer(
        parts["registry"], parts["clusters"], parts["searcher"],
        host=cfg.server.host, port=cfg.server.port,
        jobqueue=parts["jobs"], crud=parts["crud"],
        objectstorage=parts["objectstorage"],
        rate_limit=bucket,
        ca=ca,
        state_backend=parts["state_backend"],
        jobs_min_requeue_s=cfg.jobs_min_requeue_s,
        rollout=parts["rollout"],
        ha=parts["ha"],
        **auth,
    )
    rest.serve()
    # -- replication role (manager/replication.py, DESIGN.md §20) -------
    lease_keeper = None
    follower = None
    if ha is not None and ha.role == "leader":
        from ..manager.replication import LeaseKeeper

        lease_keeper = LeaseKeeper(ha)
        lease_keeper.serve()
    elif ha is not None and replicate_from:
        from ..manager.replication import LeaseKeeper, LogFollower

        def _rebuild(_touched) -> None:
            # Replicated rows changed: swap the REST surface onto fresh
            # consumers (their in-memory caches reload from the backend).
            with ha.applying():
                fresh = _build_consumers(
                    cfg, parts["state_backend"], parts["blob_store"]
                )
            rest.registry = fresh["registry"]
            rest.rollout = fresh["rollout"]
            rest.crud = fresh["crud"]
            rest.jobqueue = fresh["jobs"]
            if rest._topology_table is not None:
                with rest._topology_mu:
                    rest.topology_shared = rest._topology_table.load_all()

        def _on_promote() -> None:
            # Now the leader: reconcile as a leader would at boot, start
            # renewing the lease, and let the standing 503 gate fall
            # away (the REST handler reads ha.role per request).
            fresh = _build_consumers(
                cfg, parts["state_backend"], parts["blob_store"]
            )
            fresh["crud"].ensure_default_cluster()
            rest.registry = fresh["registry"]
            rest.rollout = fresh["rollout"]
            rest.crud = fresh["crud"]
            rest.jobqueue = fresh["jobs"]
            keeper = LeaseKeeper(ha)
            keeper.serve()
            print(
                f"manager: promoted to leader (term {ha.term})", flush=True
            )

        follower = LogFollower(
            ha, replicate_from,
            poll_interval_s=cfg.ha.poll_interval_s,
            on_apply=_rebuild,
            on_promote=_on_promote,
        )
        follower.serve()
    grpc_server = None
    if cfg.server.grpc_port >= 0:
        from ..rpc.grpc_transport import ManagerGRPCServer

        grpc_server = ManagerGRPCServer(
            parts["registry"], parts["clusters"], parts["searcher"],
            host=cfg.server.host, port=cfg.server.grpc_port,
            # Same RBAC as REST, same credentials: session tokens AND PATs;
            # same SHARED rate-limit bucket (qps bounds the service).
            token_verifier=auth.get("token_verifier"),
            users=auth.get("users"),
            rate_limit=bucket,
            ca=ca,
        )
        grpc_server.serve()
    # flush: under a pipe (supervisors, e2e harnesses) the ready line must
    # be visible immediately, not at buffer-fill.
    print(
        f"manager: serving REST on {rest.url}"
        + (f" and grpc on {grpc_server.target}" if grpc_server else "")
        + (
            f" as {parts['ha'].role} (term {parts['ha'].term})"
            if parts["ha"] is not None else ""
        )
        + " (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rest.stop()
        if grpc_server is not None:
            grpc_server.stop()
        if lease_keeper is not None:
            lease_keeper.stop()
        if follower is not None:
            follower.stop()
        return 0


if __name__ == "__main__":
    sys.exit(run())
