"""manager service binary (reference: cmd/manager + manager/manager.go).

Boots the control-plane composition: model registry (versioned blobs),
cluster manager with keepalive TTLs, searcher, dynconfig server, job
broker.  ``--list-models DIR`` prints the registry persisted under DIR
(the ops inspection path the reference serves via console/REST).
"""

from __future__ import annotations

import sys
import time

from ..config import ManagerConfig, load_config
from ..jobs import JobQueue
from ..manager import ClusterManager, ModelRegistry, Searcher
from ..manager.registry import BlobStore
from .common import base_parser, init_debug, init_logging, init_tracing


def build(cfg: ManagerConfig):
    import os

    registry = ModelRegistry(
        BlobStore(cfg.registry.blob_dir),
        db_path=os.path.join(cfg.registry.blob_dir, "manager.db"),
    )
    clusters = ClusterManager(keepalive_ttl=cfg.keepalive_ttl_s)
    # CRUD rows (applications + scheduler-cluster configs) share the
    # registry's durable directory — cluster overrides survive restarts.
    from ..manager.crud import CrudStore

    crud = CrudStore(os.path.join(cfg.registry.blob_dir, "crud.db"))
    crud.ensure_default_cluster()
    objectstorage = None
    if cfg.objectstorage:
        from ..objectstorage import make_backend

        kwargs = dict(cfg.objectstorage)
        objectstorage = make_backend(kwargs.pop("kind", "fs"), **kwargs)
    # NOTE: no DynconfigServer here — the dynconfig payload schedulers
    # poll is served straight from the CrudStore's cluster rows
    # (/api/v1/clusters/<id>:config), one source of truth.
    return {
        "registry": registry,
        "clusters": clusters,
        "searcher": Searcher(),
        "jobs": JobQueue(),
        "crud": crud,
        "objectstorage": objectstorage,
    }


def run(argv=None) -> int:
    p = base_parser("manager", "Control-plane manager service")
    p.add_argument("--list-models", action="store_true")
    args = p.parse_args(argv)
    init_logging(args, "manager")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(ManagerConfig, args.config)
    parts = build(cfg)

    if args.list_models:
        models = parts["registry"].list()
        if not models:
            print("manager: registry empty")
        for m in models:
            print(
                f"manager: {m.name} v{m.version} type={m.type} state={m.state.value} "
                f"scheduler={m.scheduler_id} eval={m.evaluation}"
            )
        return 0

    from ..manager.rest import ManagerRESTServer

    auth = {}
    if cfg.token_secret:
        from ..manager.users import UserStore
        from ..security.tokens import TokenIssuer, TokenVerifier

        secret = cfg.token_secret.encode()
        users = UserStore(cfg.users_db or None)
        if cfg.root_password:
            users.ensure_root(cfg.root_password)
        auth = {
            "token_verifier": TokenVerifier(secret),
            "token_issuer": TokenIssuer(secret),
            "users": users,
        }
        if cfg.oauth_providers:
            from ..manager.oauth import OAuthProvider, OAuthSignin

            oauth = OAuthSignin(users)
            for p in cfg.oauth_providers:
                oauth.register(OAuthProvider(**p))
            auth["oauth"] = oauth
    from ..rpc.ratelimit import maybe_bucket

    bucket = maybe_bucket(cfg.server.rate_limit_qps, cfg.server.rate_limit_burst)
    ca = None
    if cfg.ca_dir:
        from ..security.ca import CertificateAuthority

        # Persistent: restarts keep the cluster trust root, so issued
        # peer identities stay valid across a manager bounce.
        ca = CertificateAuthority.persistent(cfg.ca_dir)
    rest = ManagerRESTServer(
        parts["registry"], parts["clusters"], parts["searcher"],
        host=cfg.server.host, port=cfg.server.port,
        jobqueue=parts["jobs"], crud=parts["crud"],
        objectstorage=parts["objectstorage"],
        rate_limit=bucket,
        ca=ca,
        **auth,
    )
    rest.serve()
    grpc_server = None
    if cfg.server.grpc_port >= 0:
        from ..rpc.grpc_transport import ManagerGRPCServer

        grpc_server = ManagerGRPCServer(
            parts["registry"], parts["clusters"], parts["searcher"],
            host=cfg.server.host, port=cfg.server.grpc_port,
            # Same RBAC as REST, same credentials: session tokens AND PATs;
            # same SHARED rate-limit bucket (qps bounds the service).
            token_verifier=auth.get("token_verifier"),
            users=auth.get("users"),
            rate_limit=bucket,
            ca=ca,
        )
        grpc_server.serve()
    # flush: under a pipe (supervisors, e2e harnesses) the ready line must
    # be visible immediately, not at buffer-fill.
    print(
        f"manager: serving REST on {rest.url}"
        + (f" and grpc on {grpc_server.target}" if grpc_server else "")
        + " (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rest.stop()
        if grpc_server is not None:
            grpc_server.stop()
        return 0


if __name__ == "__main__":
    sys.exit(run())
