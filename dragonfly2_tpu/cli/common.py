"""Shared CLI plumbing (reference: cmd/dependency/dependency.go:59-120)."""

from __future__ import annotations

import argparse
from typing import Optional

from .. import __version__
from ..utils import dflog


def base_parser(prog: str, description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, description=description)
    p.set_defaults(_prog=prog)  # OTLP resource service.name
    p.add_argument("--config", default=None, help="YAML config file path")
    p.add_argument("--verbose", action="store_true", help="debug logging")
    p.add_argument("--console", action="store_true", help="log to stdout")
    p.add_argument("--log-dir", default=None, help="rotating log file directory")
    p.add_argument(
        "--debug-port", type=int, default=None, metavar="PORT",
        help="loopback debug endpoint: /debug/stacks, /debug/stats, "
             "/debug/profile (cmd/dependency --pprof-port analog; 0 = "
             "ephemeral)",
    )
    p.add_argument(
        "--trace-file", default=None, metavar="PATH",
        help="append spans as JSON lines (the --jaeger export analog, "
             "cmd/dependency/dependency.go:263-297); cross-process trace "
             "ids from the traceparent wire header land here",
    )
    p.add_argument(
        "--otlp", default=None, metavar="TARGET",
        help="export spans as OTLP/JSON: an http(s) collector endpoint "
             "(Jaeger/otel-collector at :4318/v1/traces) or a file path "
             "appended one ExportTraceServiceRequest per line — the "
             "reference's --jaeger flag analog "
             "(cmd/dependency/dependency.go:263-297)",
    )
    p.add_argument(
        "--trace-log", default=None, metavar="PATH",
        help="flight recorder: append-only crash-safe trace log "
             "(length-prefixed, digest-checked OTLP/JSON frames; "
             "head-sampled by trace id per config tracing.sample_rate) — "
             "feed per-process logs to tools/trace_assemble.py; "
             "overrides config tracing.log_path",
    )
    p.add_argument(
        "--metric-journal", default=None, metavar="PATH",
        help="fleet telemetry: append-only crash-safe metric journal "
             "(length-prefixed, digest-checked DFMJ1 frames of periodic "
             "counter/gauge/sketch snapshots + run identity) — feed "
             "per-process journals to tools/fleet_assemble.py; overrides "
             "config telemetry.journal_path",
    )
    p.add_argument(
        "--version", action="version", version=f"%(prog)s {__version__}"
    )
    return p


def init_tracing(args) -> None:
    """Point the process-default tracer at the configured exporter
    (every binary, like the reference's otel wiring in cmd/dependency):
    --otlp for standard-collector export, --trace-file for raw JSONL."""
    if getattr(args, "otlp", None):
        from ..utils.tracing import OTLPJSONExporter, default_tracer

        default_tracer.exporter = OTLPJSONExporter(
            args.otlp, service=getattr(args, "_prog", None) or "dragonfly"
        )
        return
    if not getattr(args, "trace_file", None):
        return
    from ..utils.tracing import JSONLExporter, default_tracer

    default_tracer.exporter = JSONLExporter(args.trace_file)


def init_flight_recorder(args, tracing_cfg, service: Optional[str] = None):
    """Config-driven tracer wiring, called AFTER load_config in every
    binary (init_tracing handled the pre-config CLI flags): applies the
    tracing.enable toggle, sizes the /debug/spans recent ring, keeps any
    --otlp/--trace-file exporter, and attaches the durable flight
    recorder when --trace-log or tracing.log_path names one.  Returns
    the DurableSpanExporter (or None) so callers can flush on shutdown.
    """
    from ..utils import tracing as tr

    service = service or getattr(args, "_prog", None) or "dragonfly"
    tr.default_tracer.service = service
    if tracing_cfg is not None:
        tr.set_enabled(tracing_cfg.enable)
    path = getattr(args, "trace_log", None) or (
        tracing_cfg.log_path if tracing_cfg is not None else ""
    )
    ring_spans = tracing_cfg.ring_spans if tracing_cfg is not None else 4096
    rate = tracing_cfg.sample_rate if tracing_cfg is not None else 1.0
    exporters = [tr.InMemoryExporter(max_spans=ring_spans)]
    current = tr.default_tracer.exporter
    if not isinstance(current, (tr.InMemoryExporter, tr.CompositeExporter)):
        exporters.append(current)  # the --otlp/--trace-file choice rides along
    durable = None
    if path:
        durable = tr.DurableSpanExporter(path, service=service, sample_rate=rate)
        exporters.append(durable)
    tr.default_tracer.exporter = (
        exporters[0] if len(exporters) == 1 else tr.CompositeExporter(exporters)
    )
    return durable


def init_telemetry(args, telemetry_cfg, service: Optional[str] = None):
    """Config-driven metric journal + SLO engine, called AFTER
    load_config in every binary next to ``init_flight_recorder``
    (DESIGN.md §23): attaches the crash-safe metric journal when
    ``--metric-journal`` or ``telemetry.journal_path`` names one, and —
    when ``telemetry.slos`` declares objectives — starts the burn-rate
    engine and installs it for the ``/debug/slo`` endpoints.  Returns
    ``(journal, engine)`` (either may be None) so callers can flush and
    stop on shutdown."""
    service = service or getattr(args, "_prog", None) or "dragonfly"
    path = getattr(args, "metric_journal", None) or (
        telemetry_cfg.journal_path if telemetry_cfg is not None else ""
    )
    journal = None
    if path:
        from ..utils.metric_journal import MetricJournal

        journal = MetricJournal(
            path,
            service=service,
            interval_s=(
                telemetry_cfg.journal_interval_s
                if telemetry_cfg is not None else 10.0
            ),
        ).start()
    engine = None
    if telemetry_cfg is not None and telemetry_cfg.slos:
        from ..utils import slo as slo_mod

        engine = slo_mod.SLOEngine(telemetry_cfg.slos)
        engine.start(telemetry_cfg.slo_interval_s)
        slo_mod.install_engine(engine)
    return journal, engine


def init_diagnostics(cfg_metrics, service: str):
    """The uniform /metrics + /debug/spans + /debug/exemplars sidecar on
    the scheduler and daemon (the manager serves the same routes on its
    REST port).  Gated behind config ``metrics.enable``; port conflicts
    degrade to a warning — diagnostics must never keep a plane down."""
    if cfg_metrics is None or not cfg_metrics.enable:
        return None
    try:
        from ..utils.diagnostics import DiagnosticsServer

        srv = DiagnosticsServer(port=cfg_metrics.port)
        srv.serve()
        print(
            f"{service}: diagnostics on {srv.url}/metrics "
            f"(+ /debug/spans, /debug/exemplars)", flush=True,
        )
        return srv
    except OSError as exc:
        import logging

        logging.getLogger(__name__).warning(
            "%s: diagnostics endpoint not started (%s)", service, exc
        )
        return None


def init_debug(args) -> None:
    """Start the debug endpoint when --debug-port is given (every binary,
    like the reference's pprof wiring in cmd/dependency)."""
    if getattr(args, "debug_port", None) is None:
        return
    from ..utils.debug import DebugServer

    srv = DebugServer(port=args.debug_port)
    srv.serve()
    print(f"debug endpoint on {srv.url}/debug/stacks", flush=True)


def init_logging(args, service: str) -> None:
    dflog.setup(
        level="debug" if args.verbose else "info",
        log_dir=args.log_dir,
        console=args.console or not args.log_dir,
        service=service,
    )
    # Chaos drills hand a fault scenario to service binaries via
    # DF_FAULTINJECT (utils/faultinject.py) — a child process then
    # drops/delays/SIGKILLs itself at deterministic call indices, with
    # no racy external kill timing.  No-op without the env var.
    from ..utils import faultinject

    faultinject.install_from_env()
