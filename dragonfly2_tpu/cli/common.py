"""Shared CLI plumbing (reference: cmd/dependency/dependency.go:59-120)."""

from __future__ import annotations

import argparse

from .. import __version__
from ..utils import dflog


def base_parser(prog: str, description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, description=description)
    p.set_defaults(_prog=prog)  # OTLP resource service.name
    p.add_argument("--config", default=None, help="YAML config file path")
    p.add_argument("--verbose", action="store_true", help="debug logging")
    p.add_argument("--console", action="store_true", help="log to stdout")
    p.add_argument("--log-dir", default=None, help="rotating log file directory")
    p.add_argument(
        "--debug-port", type=int, default=None, metavar="PORT",
        help="loopback debug endpoint: /debug/stacks, /debug/stats, "
             "/debug/profile (cmd/dependency --pprof-port analog; 0 = "
             "ephemeral)",
    )
    p.add_argument(
        "--trace-file", default=None, metavar="PATH",
        help="append spans as JSON lines (the --jaeger export analog, "
             "cmd/dependency/dependency.go:263-297); cross-process trace "
             "ids from the traceparent wire header land here",
    )
    p.add_argument(
        "--otlp", default=None, metavar="TARGET",
        help="export spans as OTLP/JSON: an http(s) collector endpoint "
             "(Jaeger/otel-collector at :4318/v1/traces) or a file path "
             "appended one ExportTraceServiceRequest per line — the "
             "reference's --jaeger flag analog "
             "(cmd/dependency/dependency.go:263-297)",
    )
    p.add_argument(
        "--version", action="version", version=f"%(prog)s {__version__}"
    )
    return p


def init_tracing(args) -> None:
    """Point the process-default tracer at the configured exporter
    (every binary, like the reference's otel wiring in cmd/dependency):
    --otlp for standard-collector export, --trace-file for raw JSONL."""
    if getattr(args, "otlp", None):
        from ..utils.tracing import OTLPJSONExporter, default_tracer

        default_tracer.exporter = OTLPJSONExporter(
            args.otlp, service=getattr(args, "_prog", None) or "dragonfly"
        )
        return
    if not getattr(args, "trace_file", None):
        return
    from ..utils.tracing import JSONLExporter, default_tracer

    default_tracer.exporter = JSONLExporter(args.trace_file)


def init_debug(args) -> None:
    """Start the debug endpoint when --debug-port is given (every binary,
    like the reference's pprof wiring in cmd/dependency)."""
    if getattr(args, "debug_port", None) is None:
        return
    from ..utils.debug import DebugServer

    srv = DebugServer(port=args.debug_port)
    srv.serve()
    print(f"debug endpoint on {srv.url}/debug/stacks", flush=True)


def init_logging(args, service: str) -> None:
    dflog.setup(
        level="debug" if args.verbose else "info",
        log_dir=args.log_dir,
        console=args.console or not args.log_dir,
        service=service,
    )
    # Chaos drills hand a fault scenario to service binaries via
    # DF_FAULTINJECT (utils/faultinject.py) — a child process then
    # drops/delays/SIGKILLs itself at deterministic call indices, with
    # no racy external kill timing.  No-op without the env var.
    from ..utils import faultinject

    faultinject.install_from_env()
