"""scheduler service binary (reference: cmd/scheduler + scheduler/scheduler.go).

Boots the scheduler composition: resource managers + GC, evaluator by
configured algorithm, scheduling engine, record storage, network-topology
store.  ``--simulate N`` runs an N-download synthetic swarm against the
live composition and reports record counts (the smoke/e2e mode; real
transport binds the same SchedulerService).
"""

from __future__ import annotations

import sys
import time

from ..config import SchedulerConfigFile, load_config
from ..records.storage import Storage
from ..scheduler import (
    NetworkTopology,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
    TopologyConfig,
    new_evaluator,
)
from ..utils import gc as dfgc
from .common import (
    base_parser,
    init_debug,
    init_diagnostics,
    init_flight_recorder,
    init_telemetry,
    init_logging,
    init_tracing,
)


def build(cfg: SchedulerConfigFile):
    """Composition root (scheduler.go:69-301 New)."""
    resource = Resource(
        host_ttl=cfg.gc.host_ttl_s,
        task_ttl=cfg.gc.task_ttl_s,
        peer_ttl=cfg.gc.peer_ttl_s,
    )
    topology = None
    if cfg.network_topology.enable:
        topology = NetworkTopology(
            resource.host_manager,
            TopologyConfig(
                probe_queue_length=cfg.network_topology.probe_queue_length,
                probe_count=cfg.network_topology.probe_count,
                collect_interval=cfg.network_topology.collect_interval_s,
            ),
        )
    # Every algorithm gets the columnar host store (DESIGN.md §18): the
    # slot matrix is the source of truth for host serving state, and
    # announce decode writes columns on arrival for the rule path too.
    # Only ml additionally gets cross-request scorer micro-batching.
    # Sized/paced from config so operators can tune per cluster.
    from ..scheduler import HostFeatureCache

    feature_cache = HostFeatureCache(
        max_hosts=cfg.scheduling.eval_feature_cache_hosts
    )
    batcher = None
    if cfg.scheduling.algorithm == "ml":
        from ..scheduler import ScorerBatcher

        batcher = ScorerBatcher(
            linger_s=cfg.scheduling.eval_batch_linger_ms / 1e3
        )
    evaluator = new_evaluator(
        cfg.scheduling.algorithm,
        networktopology=topology,
        feature_cache=feature_cache,
        batcher=batcher,
    )
    scheduling = Scheduling(
        evaluator,
        SchedulingConfig(
            candidate_parent_limit=cfg.scheduling.candidate_parent_limit,
            filter_parent_limit=cfg.scheduling.filter_parent_limit,
            retry_limit=cfg.scheduling.retry_limit,
            retry_back_to_source_limit=cfg.scheduling.retry_back_to_source_limit,
            retry_interval=cfg.scheduling.retry_interval_s,
        ),
    )
    storage = Storage(
        cfg.storage.dir,
        buffer_size=cfg.storage.buffer_size,
        max_size=cfg.storage.max_size,
        max_backups=cfg.storage.max_backups,
    )
    # Cold-task seed trigger: dials an announced seed daemon's
    # /obtain_seeds stream (seed_peer.go:93-229 TriggerDownloadTask) —
    # returns fast with False when no seed peer has announced.
    from ..scheduler.seed_client import RemoteSeedPeerClient

    service = SchedulerService(
        resource, scheduling, storage, topology,
        seed_peer_trigger=RemoteSeedPeerClient(resource),
    )
    runner = dfgc.GC()
    runner.add(
        dfgc.Task(
            "resource",
            interval=cfg.gc.interval_s,
            timeout=cfg.gc.interval_s / 2,
            runner=lambda: resource.run_gc(),
        )
    )
    return service, storage, runner


def run(argv=None) -> int:
    p = base_parser("scheduler", "Parent-peer scheduling service")
    p.add_argument("--simulate", type=int, default=0, metavar="N",
                   help="run an N-download synthetic swarm and exit")
    args = p.parse_args(argv)
    init_logging(args, "scheduler")
    init_debug(args)
    init_tracing(args)

    cfg = load_config(SchedulerConfigFile, args.config)
    init_flight_recorder(args, cfg.tracing, "scheduler")
    qos_journal, _qos_engine = init_telemetry(args, cfg.telemetry, "scheduler")
    init_diagnostics(cfg.metrics, "scheduler")
    service, storage, runner = build(cfg)

    # Durable probe graph (the Redis-persistence analog): reload the
    # saved state at boot so the nt evaluator keeps its RTT scores across
    # restarts; TopologySync (below) re-saves every interval + on stop.
    import os as _os

    topology_state_path = None
    if service.networktopology is not None:
        topology_state_path = _os.path.join(cfg.storage.dir, "topology_state.json")
        loaded = service.networktopology.load(topology_state_path)
        if loaded:
            print(f"scheduler: reloaded {loaded} probe edges", flush=True)
        # Periodic checkpoint when no manager is configured — a kill must
        # cost at most one interval of probes.  With a manager, the
        # TopologySync loop owns the checkpointing (ONE writer; two
        # unsynchronized savers would race on the state file).
        if not cfg.manager_addr:
            runner.add(
                dfgc.Task(
                    "topology-save", interval=60.0, timeout=30.0,
                    runner=lambda: service.networktopology.save(topology_state_path),
                )
            )

    if args.simulate:
        from ..sim import SwarmConfig, SwarmSimulator

        sim = SwarmSimulator(storage, config=SwarmConfig(num_hosts=32, seed=0))
        done = sim.run_downloads(args.simulate)
        sim.run_probe_rounds(1)
        n_topo = sim.snapshot_topology()
        storage.flush()
        print(
            f"scheduler: simulated {done} downloads -> "
            f"{storage.download_count} download records, "
            f"{storage.network_topology_count} topology records ({n_topo} snapshots)"
        )
        return 0

    runner.start()
    from ..rpc import SchedulerHTTPServer
    from ..rpc.ratelimit import maybe_bucket

    # Auto-issued mTLS (certify analog): provision this scheduler's
    # identity from the manager's cluster CA at boot; the gRPC port then
    # requires CA-issued client certificates.
    identity = None
    if cfg.security.auto_issue:
        if not cfg.manager_addr:
            raise SystemExit("scheduler: security.auto_issue needs manager_addr")
        import socket as _sock

        from ..security.ca import PeerIdentity
        from ..utils.hostinfo import local_ip

        # The SAN must carry the address clients DIAL (gRPC verifies the
        # target against it) — the advertise address, never the bind
        # host (0.0.0.0 would fail every handshake).  A non-IP dial
        # address is a DNS name and belongs in the DNS SANs.
        import ipaddress as _ipa

        dial = cfg.server.advertise_ip or (
            cfg.server.host
            if cfg.server.host not in ("0.0.0.0", "::")
            else local_ip()
        )
        try:
            _ipa.ip_address(dial)
            san_ips, san_names = [dial], [_sock.gethostname()]
        except ValueError:
            san_ips, san_names = [local_ip()], [dial, _sock.gethostname()]
        identity = PeerIdentity.request_from_manager(
            # One-shot bootstrap: the first replica in a comma-separated
            # manager_addr list (issuance needs the leader; a standby
            # would 503 and boot retries anyway).
            cfg.manager_addr.split(",")[0].strip(),
            common_name=f"sched-{_sock.gethostname()}",
            hostnames=san_names,
            ips=san_ips,
            token=cfg.manager_token or None,
            ttl_hours=cfg.security.cert_ttl_hours,
        )
        if cfg.security.identity_dir:
            identity.write(cfg.security.identity_dir)
        print("scheduler: mTLS identity issued by manager CA", flush=True)

    bucket = maybe_bucket(cfg.server.rate_limit_qps, cfg.server.rate_limit_burst)
    rpc_server = SchedulerHTTPServer(
        service, host=cfg.server.host, port=cfg.server.port, rate_limit=bucket
    )
    rpc_server.serve()
    # Both transports bind the SAME adapter: HTTP/JSON and binary gRPC
    # (pkg/rpc serves gRPC in the reference; JSON stays for curl/debug).
    grpc_server = None
    if cfg.server.grpc_port >= 0:
        from ..rpc.grpc_transport import SchedulerGRPCServer

        grpc_creds = None
        if identity is not None:
            import grpc as _grpc

            grpc_creds = _grpc.ssl_server_credentials(
                [(identity.key_pem, identity.cert_pem)],
                root_certificates=identity.ca_pem,
                require_client_auth=True,
            )
        # ONE shared bucket: the configured qps bounds the SERVICE, not
        # each transport separately.
        grpc_server = SchedulerGRPCServer(
            service, host=cfg.server.host, port=cfg.server.grpc_port,
            rate_limit=bucket,
            server_credentials=grpc_creds,
        )
        grpc_server.serve()
        # Stall sweep: server-initiated reschedules for idle peers on the
        # bidi wire (push.StallMonitor; needs the hub the gRPC server
        # attached to the service).
        if cfg.scheduling.stall_max_idle_s > 0:
            from ..scheduler.push import StallMonitor

            stall_monitor = StallMonitor(
                service,
                max_idle_s=cfg.scheduling.stall_max_idle_s,
                interval_s=cfg.scheduling.stall_sweep_interval_s,
            )
            stall_monitor.start()
    # Remote job worker (machinery-consumer analog, scheduler/job/job.go):
    # polls this scheduler's queue on the MANAGER's broker so preheat /
    # sync_peers fan-outs work across process boundaries.
    # ONE identity for registration, job-queue naming, and the announcer's
    # keepalive tick — their equality is load-bearing (the keepalive
    # self-heal only re-registers the id it registered).  The serving
    # port joins the id so REPLICAS on one host (process clusters,
    # sidecar deployments) stay distinct in the manager's cluster table
    # and the job broker's queue names.
    import socket as _socket

    scheduler_id = f"sched-{_socket.gethostname()}-{rpc_server.address[1]}"
    # Sharded-fleet guard (DESIGN.md §24): ownership steering + admission
    # control on the task-scoped entry points.  The ring arrives through
    # dynconfig (below) once a manager publishes it; until then the
    # guard is pass-through (single-shard behavior).
    from ..scheduler.sharding import AdmissionController, ShardGuard

    shard_admission = None
    qos_autopilot = None
    if cfg.scheduling.shard_max_inflight > 0:
        from ..qos.accounting import TenantAccounting

        # Tenant accounting rides admission from boot (DESIGN.md §26):
        # per-tenant usage/caps start on the default policy and adopt
        # the manager-published tenant_qos via dynconfig below.
        shard_admission = AdmissionController(
            max_inflight=cfg.scheduling.shard_max_inflight,
            p99_budget_s=cfg.scheduling.shard_p99_budget_ms / 1e3,
            accounting=TenantAccounting(),
        )
        if (
            cfg.scheduling.qos_autopilot
            and qos_journal is not None
            and cfg.telemetry.slos
        ):
            # SLO autopilot (qos/autopilot.py): rides the metric
            # journal's cadence — every written frame is ingested live,
            # so journal replay reproduces the decisions exactly.
            from ..qos.autopilot import SLOAutopilot

            qos_autopilot = SLOAutopilot(
                cfg.telemetry.slos,
                admission=shard_admission,
                accounting=shard_admission.accounting,
            )
            qos_journal.on_snapshot = qos_autopilot.ingest
    shard_guard = ShardGuard(scheduler_id, admission=shard_admission)
    shard_guard.resource = service.resource
    service.shard_guard = shard_guard
    job_worker = None
    cluster_link = None
    dynconfig = None
    topology_sync = None
    model_subscriber = None
    rollout_reporter = None
    if cfg.manager_addr:
        from ..jobs.preheat import PREHEAT
        from ..jobs.remote import RemoteJobWorker
        from ..rpc.cluster_client import RemoteClusterClient
        from ..jobs.sync_peers import SYNC_PEERS, make_sync_peers_handler
        from ..rpc.resolver import ManagerEndpoints
        from ..utils import idgen

        token = cfg.manager_token or None
        # ONE shared multi-endpoint resolver for every manager-facing
        # client in this process (manager_addr accepts a comma-separated
        # replica list): the first client to fail over to the surviving
        # manager replica moves keepalives, dynconfig polls, model/
        # rollout fetches, job polls, and topology sync with it.
        manager_endpoints = ManagerEndpoints(
            cfg.manager_addr, client="scheduler"
        )
        # Register THIS instance with the manager so the manager-side
        # producers (SyncPeers fans to f"scheduler:{sched.id}" for
        # *registered* schedulers, jobs/sync_peers.py) target the queue
        # this worker polls; the keepalive loop re-registers after a
        # manager restart.  A failed first registration only warns — the
        # loop keeps retrying while the worker polls.
        cluster_link = RemoteClusterClient(manager_endpoints, token=token)
        # Register the BOUND port (port: 0 configs bind an ephemeral
        # one): the manager publishes this address in the shard ring —
        # an unroutable member would black-hole every task it owns.
        cluster_link.register_scheduler(
            id=scheduler_id, cluster_id=cfg.cluster_id,
            hostname=_socket.gethostname(), ip=cfg.server.host,
            port=rpc_server.address[1],
        )
        job_worker = RemoteJobWorker(
            manager_endpoints, f"scheduler:{scheduler_id}", token=token
        )

        def preheat_handler(args):
            # Warm each URL into an announced seed daemon via the
            # ObtainSeeds trigger (job.go:244-283 → TriggerDownloadTask).
            if service.seed_peer_trigger is None:
                raise RuntimeError("no seed trigger configured")
            results = {}
            for url in args.get("urls", []):
                if not service.seed_peer_trigger(url, idgen.task_id(url)):
                    raise RuntimeError(f"preheat of {url}: no seed served it")
                results[url] = "seeded"
            return results

        job_worker.register(PREHEAT, preheat_handler)
        job_worker.register(SYNC_PEERS, make_sync_peers_handler(service.resource))
        job_worker.serve()

        # Cluster-scoped scheduling config, applied LIVE (config tier c):
        # the manager's scheduler-cluster record feeds candidate/filter
        # limits through dynconfig, and the scheduling pass reads the
        # shared SchedulingConfig on every call — a console PATCH changes
        # the very next pass (scheduling.go:404-410 consumption; disk
        # cache keeps the last-known config through manager outages).
        import json as _json
        import os as _os
        import urllib.request as _request

        from ..manager.dynconfig import Dynconfig

        import logging as _logging
        import urllib.error as _urlerror

        _dynlog = _logging.getLogger("dragonfly2_tpu.cli.scheduler.dynconfig")
        _warned_404 = []

        def _fetch_one_endpoint(base):
            req = _request.Request(
                f"{base}/api/v1/clusters/{cfg.cluster_id}:config"
            )
            try:
                with _request.urlopen(req, timeout=10) as resp:
                    return _json.loads(resp.read())
            except _urlerror.HTTPError as exc:
                if exc.code == 404 and not _warned_404:
                    # Misconfiguration, not an outage: the manager has no
                    # record for this cluster_id, so console PATCHes will
                    # never reach this scheduler — say so ONCE, loudly
                    # (Dynconfig's refresh swallows fetch errors silently).
                    _warned_404.append(True)
                    _dynlog.warning(
                        "cluster %r has no config record on the manager — "
                        "live scheduling overrides are inactive until it "
                        "is created (POST /api/v1/clusters)", cfg.cluster_id,
                    )
                raise

        def _fetch_cluster_config():
            # Sweep the replica list before giving up: the disk cache is
            # the LAST resort (all replicas down), not the answer to one
            # dead leader.
            return manager_endpoints.call(_fetch_one_endpoint)

        def _apply_cluster_config(data):
            scc = data.get("scheduler_cluster_config")
            if not isinstance(scc, dict):
                return
            sc = service.scheduling.config
            # Read-validate EVERYTHING before writing anything — a bad
            # value must not leave the live config half-updated (the
            # manager validates writes, but the disk cache or an older
            # manager may still hand back junk).
            updates = {}
            for key in (
                "candidate_parent_limit",
                "filter_parent_limit",
                "retry_limit",
                "retry_back_to_source_limit",
            ):
                if key in scc:
                    try:
                        updates[key] = int(scc[key])
                    except (TypeError, ValueError):
                        _dynlog.warning(
                            "ignoring cluster config with bad %s=%r",
                            key, scc[key],
                        )
                        return
            for key, value in updates.items():
                setattr(sc, key, value)

        dynconfig = Dynconfig(
            _fetch_cluster_config,
            refresh_interval=cfg.dynconfig_refresh_s,
            cache_path=_os.path.join(cfg.storage.dir, "dynconfig_cache.json"),
        )
        dynconfig.register(_apply_cluster_config)
        # Ring adoption: the manager publishes the shard ring with the
        # cluster config; a version bump triggers the guard's handoff
        # sweep (tasks this shard no longer owns steer to their new
        # owner on the peers' next call).
        dynconfig.register(shard_guard.on_config)
        # Tenant QoS adoption (DESIGN.md §26): the manager publishes the
        # per-tenant table with the same payload; the service installs
        # it across admission accounting + the batcher's DRR weights and
        # re-publishes it on announce answers.
        dynconfig.register(service.on_qos_config)
        dynconfig.serve()

        # Cross-replica topology sharing through the manager (the Redis
        # analog): probes landed on OTHER schedulers inform this one's nt
        # evaluator, and each sync checkpoints the local graph to disk.
        if service.networktopology is not None:
            from ..scheduler.topology_sync import TopologySync

            topology_sync = TopologySync(
                service.networktopology, manager_endpoints, scheduler_id,
                token=token, interval_s=cfg.topology_sync_interval_s,
                state_path=topology_state_path,
            )
            topology_sync.serve()

        # Model rollout plane (DESIGN.md §15): the ml evaluator polls the
        # manager registry for the active AND candidate versions (seeded
        # ±jitter so a fleet never herds the registry), shadow-scores a
        # sampled announce slice into a replay log, and reports joined
        # outcome quality back to the rollout controller.
        if cfg.scheduling.algorithm == "ml":
            from ..rollout import RolloutReporter, RolloutRESTClient
            from ..rpc.registry_client import RemoteRegistry
            from ..scheduler import ModelSubscriber

            model_subscriber = ModelSubscriber(
                RemoteRegistry(manager_endpoints, token=token),
                service.scheduling.evaluator,
                scheduler_id=scheduler_id,
                idc=cfg.scheduling.idc or None,
                refresh_interval=cfg.scheduling.model_poll_interval_s,
                jitter=cfg.scheduling.model_poll_jitter,
                rollout_client=RolloutRESTClient(manager_endpoints, token=token),
                shadow_sample_rate=cfg.scheduling.shadow_sample_rate,
                shadow_log_path=_os.path.join(
                    cfg.storage.dir, "shadow_replay.dfc"
                ),
            )
            model_subscriber.serve()
            rollout_reporter = RolloutReporter(
                model_subscriber, storage,
                RolloutRESTClient(manager_endpoints, token=token),
                interval_s=cfg.scheduling.rollout_report_interval_s,
            )
            rollout_reporter.serve()

    # Periodic dataset upload to the trainer (announcer.go:127-142 train
    # ticker, default 7d) — the link that feeds the learning loop in a
    # real deployment.
    announcer = None
    if cfg.trainer.enable and cfg.trainer.addr:
        from ..scheduler.announcer import Announcer

        if cfg.trainer.addr.startswith("grpc://"):
            from ..rpc.grpc_transport import GRPCTrainerClient

            from ..rpc.trainer_transport import RemoteTrainerSession  # noqa: F401

            class _GRPCTrainerLink:
                """Adapts the Train-stream client to the announcer's
                open_train_stream session surface."""

                def __init__(self, target):
                    self._client = GRPCTrainerClient(target)

                def open_train_stream(self, *, ip, hostname, scheduler_id):
                    client = self._client

                    class _Session:
                        def __init__(self):
                            self.downloads = []
                            self.topologies = []

                        def send_download_shard(self, path):
                            self.downloads.append(path)

                        def send_network_topology_shard(self, path):
                            self.topologies.append(path)

                        def close_and_train(self):
                            return client.train(
                                ip=ip, hostname=hostname,
                                scheduler_id=scheduler_id,
                                download_shards=self.downloads,
                                topology_shards=self.topologies,
                            )

                    return _Session()

            trainer_link = _GRPCTrainerLink(cfg.trainer.addr[len("grpc://"):])
        else:
            from ..rpc import RemoteTrainer

            trainer_link = RemoteTrainer(cfg.trainer.addr)
        announcer = Announcer(
            scheduler_id=scheduler_id,
            storage=storage,
            trainer=trainer_link,
            # The Announcer's own loop drives manager liveness over the
            # REST wire when both links are configured (one loop, not
            # two) — same ip/port the CLI registered, so the keepalive
            # self-heal re-registers a reachable address.
            cluster_manager=cluster_link,
            cluster_id=cfg.cluster_id,
            ip=cfg.server.host,
            port=rpc_server.address[1],
            hostname=_socket.gethostname(),
            train_interval=cfg.trainer.interval_s,
        )
        announcer.serve()
    if cluster_link is not None and announcer is None:
        # No Announcer to tick liveness → the client's own thin loop.
        cluster_link.serve()

    print(
        f"scheduler: serving rpc on {rpc_server.url}"
        + (f" and grpc on {grpc_server.target}" if grpc_server else "")
        + (f", dataset uploads to {cfg.trainer.addr} every "
           f"{cfg.trainer.interval_s:.0f}s" if announcer else "")
        + (f", job queue {job_worker.queue_name} on {cfg.manager_addr}"
           if job_worker else "")
        + " (ctrl-c to stop)",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rpc_server.stop()
        if grpc_server is not None:
            grpc_server.stop()
        if announcer is not None:
            announcer.stop()
        if job_worker is not None:
            job_worker.stop()
        if cluster_link is not None:
            cluster_link.stop()
        if dynconfig is not None:
            dynconfig.stop()
        if qos_autopilot is not None:
            qos_autopilot.close()
        if rollout_reporter is not None:
            rollout_reporter.stop()
        if model_subscriber is not None:
            model_subscriber.stop()
        if topology_sync is not None:
            topology_sync.stop()  # final disk checkpoint
        elif topology_state_path is not None and service.networktopology:
            service.networktopology.save(topology_state_path)
        return 0


if __name__ == "__main__":
    sys.exit(run())
