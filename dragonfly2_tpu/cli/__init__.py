"""CLI entry points (reference: cmd/ — manager, scheduler, trainer, dfget,
dfcache, dfstore via cobra).

argparse equivalents, runnable as ``python -m dragonfly2_tpu.cli.<tool>``:

- ``dfget``     — one-shot download through an embedded daemon+scheduler
                  stack (the reference's dfget self-spawns a daemon,
                  cmd/dfget/cmd/root.go:234-260; embedded here).
- ``dfcache``   — import/export/stat of cache tasks against the local
                  piece store (client/dfcache).
- ``scheduler`` / ``trainer`` / ``manager`` / ``dfdaemon`` — service
  binaries: load config, boot the composition, serve (or run a bounded
  simulation round in --simulate mode for smoke checks).

Shared flags mirror cmd/dependency/dependency.go: --config, --verbose,
--console.
"""
