"""dfcache: import/export/stat cache tasks (reference: cmd/dfcache +
client/dfcache — import/export/stat of cache tasks via the daemon)."""

from __future__ import annotations

import os
import sys

from ..daemon.storage import DaemonStorage
from ..utils import idgen
from .common import base_parser, init_debug, init_logging


def run(argv=None) -> int:
    p = base_parser("dfcache", "Import/export/stat local cache tasks")
    p.add_argument("command", choices=["import", "export", "stat"])
    p.add_argument("path_or_id", help="file path (import) or cache id")
    p.add_argument("-O", "--output", default=None, help="output path (export)")
    p.add_argument("--work-dir", default=os.path.expanduser("~/.dragonfly/dfcache"))
    p.add_argument("--piece-size", type=int, default=4 << 20)
    args = p.parse_args(argv)
    init_logging(args, "dfcache")
    init_debug(args)

    storage = DaemonStorage(args.work_dir)

    if args.command == "import":
        path = args.path_or_id
        size = os.path.getsize(path)
        cache_id = idgen.cache_task_id(os.path.abspath(path))
        storage.register_task(cache_id, piece_size=args.piece_size, content_length=size)
        with open(path, "rb") as f:
            n = 0
            while True:
                chunk = f.read(args.piece_size)
                if not chunk:
                    break
                storage.write_piece(cache_id, n, chunk)
                n += 1
        print(f"dfcache: imported {size} bytes as {cache_id} ({n} pieces)")
        return 0

    cache_id = args.path_or_id
    if not storage.reload_persistent_tasks([cache_id]):
        print(f"dfcache: {cache_id} not found", file=sys.stderr)
        return 1

    if args.command == "stat":
        cl = storage.engine.content_length(cache_id)
        print(
            f"dfcache: {cache_id} content_length={cl} "
            f"pieces={storage.engine.piece_count(cache_id)} bytes={storage.task_bytes(cache_id)}"
        )
        return 0

    # export
    if not args.output:
        print("dfcache: export needs -O", file=sys.stderr)
        return 1
    cl = storage.engine.content_length(cache_id)
    ps = storage.engine.piece_size(cache_id)
    with open(args.output, "wb") as out:
        remaining = cl
        n = 0
        while remaining > 0:
            piece = storage.read_piece(cache_id, n)
            out.write(piece[: min(len(piece), remaining)])
            remaining -= len(piece)
            n += 1
    print(f"dfcache: exported {cl} bytes -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(run())
