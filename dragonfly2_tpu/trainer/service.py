"""Trainer service: dataset ingest boundary + train-on-EOF + model push.

Reference (trainer/service/service_v1.go:59-160): the ``Train`` client
stream keys per-host dataset files by HostIDV2(ip, hostname), demuxes
TrainMlpRequest → download data and TrainGnnRequest → networktopology
data, and on EOF kicks ``training.Train`` in a goroutine, which was a stub
(training/training.go:82-99).  Here training is real:

1. train the MLP bandwidth regressor on the download rows;
2. train the GAT ranker on the probe graph + download edges (when the
   topology dataset is non-empty);
3. evaluate (MSE/MAE + ranking P/R/F1), export local-scorer artifacts,
   and CreateModel into the manager registry (the reference's
   managerclient.CreateModel → manager_server_v1.go:802).

Ingest accepts shard *paths* (co-located zero-copy) or raw bytes (remote
chunked stream), mirroring trainer/storage's per-host files
(storage.go:143-151).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..manager.registry import ModelRegistry
from ..records.columnar import concat_readers
from ..records.features import HOST_FEATURE_DIM
from ..utils import idgen
from ..utils.types import TrainingModelType
from . import metrics as trainer_metrics
from .export import export_from_state, scorer_to_bytes
from .ingest import EdgeBatches
from .train import EvalMetrics, TrainConfig, train_mlp

logger = logging.getLogger(__name__)

MLP_MODEL_NAME = "parent-bandwidth-mlp"
GNN_MODEL_NAME = "parent-ranker-gnn"


@dataclass
class TrainRun:
    key: str
    scheduler_id: str
    download_rows: int = 0
    topology_rows: int = 0
    models: List[str] = field(default_factory=list)  # registry model ids
    metrics: Dict[str, EvalMetrics] = field(default_factory=dict)
    error: Optional[str] = None
    done: threading.Event = field(default_factory=threading.Event)


class TrainSession:
    """One open Train stream (per announcing scheduler)."""

    def __init__(self, service: "TrainerService", host_key: str, scheduler_id: str):
        self._service = service
        self.host_key = host_key
        self.scheduler_id = scheduler_id
        self.download_shards: List[str] = []
        self.topology_shards: List[str] = []
        self.chunk_seq: Dict = {}  # (kind, name) -> last applied chunk seq
        self.decoders: Dict = {}   # (kind, name) -> StreamingRowDecoder (online mode)

    def send_download_shard(self, path: str) -> None:
        self.download_shards.append(
            self._service._stage_shard(self.host_key, "download", path)
        )

    def send_network_topology_shard(self, path: str) -> None:
        self.topology_shards.append(
            self._service._stage_shard(self.host_key, "networktopology", path)
        )

    def close_and_train(self, *, synchronous: bool = True) -> str:
        """EOF: kick training (service_v1.go:153-158 runs it in a goroutine;
        ``synchronous=False`` matches that)."""
        return self._service._train(
            self, synchronous=synchronous
        )


class TrainerService:
    def __init__(
        self,
        registry: Optional[ModelRegistry] = None,
        *,
        data_dir: Optional[str] = None,
        train_config: Optional[TrainConfig] = None,
        mlp_epochs: int = 30,
        gnn_model: str = "hop",
        online_sink=None,
    ) -> None:
        self.registry = registry or ModelRegistry()
        self.data_dir = data_dir
        self.train_config = train_config or TrainConfig(
            epochs=mlp_epochs, learning_rate=3e-3, warmup_steps=20
        )
        # GNN family for the ingest-triggered training: "hop" (flagship —
        # precomputed aggregation, scatter-free step, models/hop.py) or
        # "gat" (models/gnn.py).  Both export the same GNNScorer artifact.
        if gnn_model not in ("hop", "gat"):
            raise ValueError(f"gnn_model {gnn_model!r} not in ('hop', 'gat')")
        self.gnn_model = gnn_model
        # ONLINE mode (service_v1.go:128-143 continuous feed): with a
        # sink attached (OnlineGraphTrainer.make_wire_adapter()), every
        # chunk landing on the wire ALSO decodes incrementally
        # (records.columnar.StreamingRowDecoder) and streams into the
        # online trainer — rows reach the train loop while the stream is
        # still open, not at EOF.  Staging continues regardless (the
        # durable record of the stream; batch retraining still works).
        self.online_sink = online_sink
        # Rows already fed to the sink per (host_key, kind, name) — the
        # cross-SESSION dedup: a client that reconnects and resends a
        # shard (fresh TrainSession, empty chunk_seq) re-decodes the
        # same prefix, and only rows BEYOND this high-water mark feed.
        self._online_fed: Dict = {}
        self.runs: Dict[str, TrainRun] = {}
        self._mu = threading.Lock()
        self._counter = 0

    # -- ingest --------------------------------------------------------------

    def open_train_stream(
        self, *, ip: str, hostname: str, scheduler_id: str
    ) -> TrainSession:
        host_key = idgen.host_id_v2(ip, hostname)[:24]
        return TrainSession(self, host_key, scheduler_id)

    def _stage_shard(self, host_key: str, kind: str, path: str) -> str:
        """Co-located: reference the shard in place. With a data_dir:
        copy into per-host staging (the remote-upload landing zone)."""
        if self.data_dir is None:
            return path
        staged_dir = os.path.join(self.data_dir, host_key)
        os.makedirs(staged_dir, exist_ok=True)
        staged = os.path.join(staged_dir, f"{kind}_{os.path.basename(path)}")
        shutil.copyfile(path, staged)
        return staged

    def receive_shard_bytes(
        self, session: TrainSession, kind: str, name: str, data: bytes, *, seq: int = 0
    ) -> None:
        """Remote path: raw columnar bytes land in the staging dir.

        Chunks append in ``seq`` order; a RETRIED chunk (same or lower seq
        than already applied) is a no-op — wire clients retry on lost
        responses and a blind append would duplicate 128 MiB blocks into
        the dataset.
        """
        if self.data_dir is None:
            raise RuntimeError("byte ingest requires a data_dir")
        staged_dir = os.path.join(self.data_dir, session.host_key)
        os.makedirs(staged_dir, exist_ok=True)
        staged = os.path.join(staged_dir, f"{kind}_{name}")
        applied = session.chunk_seq.get((kind, name), -1)
        if seq <= applied:
            return  # duplicate delivery
        if seq != applied + 1:
            raise ValueError(f"chunk gap for {kind}/{name}: got {seq}, want {applied + 1}")
        with open(staged, "wb" if seq == 0 else "ab") as f:
            f.write(data)
        session.chunk_seq[(kind, name)] = seq
        if seq == 0:
            if kind == "download":
                session.download_shards.append(staged)
            else:
                session.topology_shards.append(staged)
        if self.online_sink is not None:
            self._feed_online(session, kind, name, data, seq)

    def _feed_online(
        self, session: TrainSession, kind: str, name: str, data: bytes, seq: int
    ) -> None:
        """Online mode: decode the chunk incrementally and stream NEW rows
        to the sink.  Runs after the in-session seq dedup; cross-session
        resends dedupe on the per-dataset row high-water mark."""
        from ..records.columnar import MAGIC, StreamingRowDecoder

        key = (kind, name)
        if key not in session.decoders:
            # Sniff the format once per dataset: reference-CSV shards
            # (the compat path _normalize_shard converts at train time)
            # skip online decode — a ValueError here would kill the
            # legacy client's stream.
            session.decoders[key] = (
                StreamingRowDecoder()
                if seq == 0 and data[: len(MAGIC)] == MAGIC
                else None
            )
        dec = session.decoders[key]
        if dec is None:
            return
        rows = dec.feed(data)
        if not rows.size:
            return
        fed_key = (session.host_key, kind, name)
        with self._mu:
            fed = self._online_fed.get(fed_key, 0)
            start = dec.rows_decoded - len(rows)
            skip = max(fed - start, 0)
            self._online_fed[fed_key] = max(fed, dec.rows_decoded)
        if skip >= len(rows):
            return
        rows = rows[skip:]
        if kind == "download":
            self.online_sink.feed_download_rows(rows)
        else:
            self.online_sink.feed_topology_rows(rows)

    # -- training ------------------------------------------------------------

    @staticmethod
    def _normalize_shard(path: str, kind: str) -> str:
        """Accept the REFERENCE's wire format too: a staged shard that is
        not DFC1 columnar is parsed as the reference's headerless CSV
        (scheduler/storage CSV via announcer.go upload) and converted —
        a reference scheduler can stream its datasets here unmodified."""
        from ..records.columnar import MAGIC

        try:
            with open(path, "rb") as f:
                head = f.read(len(MAGIC))
        except OSError:
            return path
        if head == MAGIC or not head:
            return path
        from ..records import csv_compat

        converted = path + ".dfc"
        # Cached: a retrained session must not re-parse a multi-GB CSV.
        if (
            os.path.exists(converted)
            and os.path.getmtime(converted) >= os.path.getmtime(path)
        ):
            return converted
        import tempfile

        # Per-attempt tmp name: two concurrent retrains over the same
        # staged shard must never interleave writes into one file.
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), suffix=".dfc.tmp"
        )
        os.close(fd)
        os.unlink(tmp)  # ColumnarWriter must create the file itself
        try:
            if kind == "download":
                csv_compat.convert_download_csv_to_columnar(path, tmp)
            else:
                csv_compat.convert_topology_csv_to_columnar(path, tmp)
            os.replace(tmp, converted)  # atomic: readers see whole files
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return converted

    def _normalize_session(self, session: TrainSession) -> None:
        session.download_shards = [
            self._normalize_shard(p, "download") for p in session.download_shards
        ]
        session.topology_shards = [
            self._normalize_shard(p, "networktopology")
            for p in session.topology_shards
        ]

    def _train(self, session: TrainSession, *, synchronous: bool) -> str:
        with self._mu:
            self._counter += 1
            key = f"train-{session.host_key}-{self._counter}"
        run = TrainRun(key=key, scheduler_id=session.scheduler_id)
        self.runs[key] = run
        if synchronous:
            self._run_training(run, session)
        else:
            threading.Thread(
                target=self._run_training, args=(run, session), daemon=True
            ).start()
        return key

    def _run_training(self, run: TrainRun, session: TrainSession) -> None:
        t0 = time.perf_counter()
        try:
            # Inside the (possibly async) worker: a multi-GB reference-CSV
            # conversion must not hold the ingest RPC handler thread.
            self._normalize_session(session)
            self._train_mlp(run, session)
            self._train_gnn(run, session)
        except Exception as exc:  # noqa: BLE001 — surfaced on the run record
            logger.exception("training run %s failed", run.key)
            run.error = str(exc)
            trainer_metrics.TRAINING_TOTAL.inc(model="all", result="failure")
        else:
            trainer_metrics.TRAINING_TOTAL.inc(model="all", result="success")
            logger.info(
                "training run %s done in %.1fs: %d download rows, "
                "%d topology rows, models=%s",
                run.key, time.perf_counter() - t0, run.download_rows,
                run.topology_rows, run.models,
            )
        finally:
            trainer_metrics.TRAINING_DURATION.observe(time.perf_counter() - t0)
            run.done.set()

    def _train_mlp(self, run: TrainRun, session: TrainSession) -> None:
        shards = [p for p in session.download_shards if os.path.getsize(p) > 0]
        if not shards:
            return
        rows = concat_readers(shards)
        run.download_rows = rows.shape[0]
        if rows.shape[0] < 64:
            logger.info("run %s: too few download rows (%d)", run.key, rows.shape[0])
            return
        # The deployed scorer ranks parents BEFORE any piece moves: train on
        # serve-time-available features only (features.mask_post_hoc).
        from ..records.features import DOWNLOAD_FEATURE_DIM, mask_post_hoc

        rows = np.array(rows, copy=True)
        rows[:, 2 : 2 + DOWNLOAD_FEATURE_DIM] = mask_post_hoc(
            rows[:, 2 : 2 + DOWNLOAD_FEATURE_DIM]
        )
        rng = np.random.default_rng(0)
        order = rng.permutation(rows.shape[0])
        n_val = max(int(rows.shape[0] * 0.1), 1)
        batch = int(min(4096, max(64, 2 ** int(np.log2(max(rows.shape[0] // 8, 64))))))
        train_rows, val_rows = rows[order[n_val:]], rows[order[:n_val]]
        train = EdgeBatches(train_rows, batch_size=min(batch, len(train_rows)), seed=0)
        val = EdgeBatches(
            val_rows,
            batch_size=min(batch, len(val_rows)),
            shuffle=False,
            drop_remainder=False,
        )
        try:
            state, metrics, _ = train_mlp(train, val, config=self.train_config)
        except ValueError as exc:
            # Corpus too small for the mesh (no full batches) — skip this
            # model rather than registering untrained weights.
            logger.warning("run %s: MLP skipped: %s", run.key, exc)
            return
        # Stamp the drift baseline (rollout PSI gate) over the SAME
        # prepared rows the model trained on.
        scorer = export_from_state(
            state,
            train_feature_rows=train_rows[:, 2 : 2 + DOWNLOAD_FEATURE_DIM],
        )
        model = self.registry.create_model(
            name=MLP_MODEL_NAME,
            type=TrainingModelType.MLP.value,
            scheduler_id=run.scheduler_id,
            artifact=scorer_to_bytes(scorer),
            evaluation=metrics.to_dict(),
        )
        run.models.append(model.id)
        run.metrics[MLP_MODEL_NAME] = metrics
        trainer_metrics.TRAINING_RECORDS.inc(run.download_rows, model="mlp")
        trainer_metrics.MODELS_PUBLISHED.inc(model="mlp")

    def _train_gnn(self, run: TrainRun, session: TrainSession) -> None:
        """GNN over the probe graph; needs both topology and download rows."""
        topo_shards = [p for p in session.topology_shards if os.path.getsize(p) > 0]
        dl_shards = [p for p in session.download_shards if os.path.getsize(p) > 0]
        if not topo_shards or not dl_shards:
            return
        topo = concat_readers(topo_shards)
        run.topology_rows = topo.shape[0]
        dl = concat_readers(dl_shards)
        if topo.shape[0] < 8 or dl.shape[0] < 256:
            return

        from ..models.gnn import GNNConfig, build_neighbor_table
        from .train import train_gat_ranker

        # Node index = dense renumbering of the hash buckets seen anywhere.
        buckets = np.unique(
            np.concatenate(
                [topo[:, 0], topo[:, 1], dl[:, 0], dl[:, 1]]
            ).astype(np.int64)
        )
        n_nodes = len(buckets)

        def reindex(col: np.ndarray) -> np.ndarray:
            # buckets is sorted-unique (np.unique) — searchsorted is the
            # vectorized bucket→dense-index map (the Python-dict version is
            # interpreter-bound and would dominate north-star-scale ingest).
            return np.searchsorted(buckets, col.astype(np.int64)).astype(np.int32)

        # Probe graph: src → dst with normalized RTT as the edge feature.
        p_src, p_dst = reindex(topo[:, 0]), reindex(topo[:, 1])
        rtt = topo[:, 2].astype(np.float32)
        table = build_neighbor_table(n_nodes, p_src, p_dst, rtt, max_neighbors=8)

        # Node features averaged from download rows (parent-side features
        # appear under the src bucket, child-side under dst) — the SAME
        # accumulator the online wire adapter uses.
        from ..records.features import accumulate_host_feature_sums

        node_feats = np.zeros((n_nodes, HOST_FEATURE_DIM), dtype=np.float32)
        counts = np.zeros(n_nodes, dtype=np.float32)
        d_src, d_dst = reindex(dl[:, 0]), reindex(dl[:, 1])
        accumulate_host_feature_sums(dl, d_src, d_dst, node_feats, counts)
        node_feats /= np.maximum(counts[:, None], 1.0)

        target = dl[:, -1].astype(np.float32)
        batch = min(2048, max(len(d_src) // 4, 64))
        try:
            if self.gnn_model == "hop":
                import jax.numpy as jnp

                from ..models.hop import HopConfig, HopRanker, precompute_hop_features
                from .train import train_hop_ranker

                cfg = HopConfig(hidden=64, out_dim=32, dropout=0.0)
                # Compute the hop features ONCE: training and the scorer
                # export must see the same array.
                export_feats = np.asarray(
                    precompute_hop_features(
                        jnp.asarray(node_feats, jnp.float32), table,
                        hops=cfg.hops,
                    )
                )
                state, metrics, _ = train_hop_ranker(
                    node_feats, table, d_src, d_dst, target,
                    model_config=cfg, config=self.train_config,
                    batch_size=batch, hop_feats=export_feats,
                )
                export_model = HopRanker(cfg)
            else:
                cfg = GNNConfig(hidden=64, out_dim=32, num_layers=1,
                                num_heads=2, dropout=0.0)
                state, metrics, _ = train_gat_ranker(
                    node_feats, table, d_src, d_dst, target,
                    model_config=cfg, config=self.train_config,
                    batch_size=batch,
                )
                from ..models.gnn import GATRanker

                export_model = GATRanker(cfg)
                export_feats = node_feats
        except ValueError as exc:
            logger.warning("run %s: GNN skipped: %s", run.key, exc)
            return
        from .export import export_gnn_scorer, gnn_scorer_to_bytes

        scorer = export_gnn_scorer(
            export_model, state.params, export_feats, table, buckets
        )
        model = self.registry.create_model(
            name=GNN_MODEL_NAME,
            type=TrainingModelType.GNN.value,
            scheduler_id=run.scheduler_id,
            artifact=gnn_scorer_to_bytes(scorer),
            evaluation=metrics.to_dict(),
        )
        run.models.append(model.id)
        run.metrics[GNN_MODEL_NAME] = metrics
        trainer_metrics.TRAINING_RECORDS.inc(len(d_src), model="gnn")
        trainer_metrics.MODELS_PUBLISHED.inc(model="gnn")
