"""Host-side input pipeline: columnar shards → device batches.

The reference streams whole CSV files from scheduler to trainer in 128 MiB
gRPC chunks (announcer.go:173-237) and would have re-parsed text server
side.  Here the scheduler already wrote fixed-width float32 rows
(records/columnar.py); ingest is:

    np.memmap shards → permuted index stream → [B, W] slices →
    jax.device_put with the batch dim sharded over the mesh's data axis

No parsing, no copies beyond the batch slice, static shapes throughout —
the XLA train step compiles once and the page cache feeds the chips.
Multi-host: each process opens only its own shard subset
(``shard_for_process``) and device_puts its addressable slice; the global
batch is assembled by the sharding, not by any host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..records.columnar import concat_readers
from ..records.features import DOWNLOAD_COLUMNS, DOWNLOAD_FEATURE_DIM


@dataclass
class EdgeBatches:
    """An epoch-iterable over download-record rows.

    Splits each row into (features [B, F], target [B], src [B], dst [B]).
    """

    rows: np.ndarray              # [N, W] in DOWNLOAD_COLUMNS layout
    batch_size: int
    shuffle: bool = True
    seed: int = 0
    drop_remainder: bool = True

    def __post_init__(self) -> None:
        if self.rows.shape[-1] != len(DOWNLOAD_COLUMNS):
            raise ValueError(
                f"row width {self.rows.shape[-1]} != {len(DOWNLOAD_COLUMNS)}"
            )

    def __len__(self) -> int:
        n = self.rows.shape[0] // self.batch_size
        if not self.drop_remainder and self.rows.shape[0] % self.batch_size:
            n += 1
        return n

    def epoch(self, epoch_idx: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
        n = self.rows.shape[0]
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch_idx)
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        for start in range(0, n, self.batch_size):
            idx = order[start : start + self.batch_size]
            if len(idx) < self.batch_size:
                if self.drop_remainder:
                    return
                # Pad the tail batch by wrapping — keeps shapes static.
                idx = np.concatenate([idx, order[: self.batch_size - len(idx)]])
            yield split_columns(self.rows[idx])


def split_columns(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """[B, W] → (features [B, F], target [B], src_bucket [B], dst_bucket [B])."""
    src = rows[:, 0].astype(np.int32)
    dst = rows[:, 1].astype(np.int32)
    feats = rows[:, 2 : 2 + DOWNLOAD_FEATURE_DIM].astype(np.float32)
    target = rows[:, -1].astype(np.float32)
    return feats, target, src, dst


def shard_for_process(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """Round-robin shard assignment: each host opens only its files."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return [p for i, p in enumerate(sorted(paths)) if i % pc == pi]


def load_download_dataset(
    paths: Sequence[str],
    *,
    batch_size: int = 8192,
    val_fraction: float = 0.1,
    seed: int = 0,
    multihost: bool = False,
) -> Tuple[EdgeBatches, EdgeBatches]:
    """Open shards → (train, val) batch streams with a stable split."""
    if multihost:
        paths = shard_for_process(paths)
    rows = concat_readers(list(paths))
    rng = np.random.default_rng(seed)
    order = rng.permutation(rows.shape[0])
    n_val = int(rows.shape[0] * val_fraction)
    val_rows = rows[order[:n_val]]
    train_rows = rows[order[n_val:]]
    train = EdgeBatches(train_rows, batch_size=batch_size, seed=seed)
    val = EdgeBatches(
        val_rows,
        batch_size=min(batch_size, max(len(val_rows), 1)),
        shuffle=False,
        drop_remainder=False,
    )
    return train, val
