"""Trainer service — the north-star component (reference: trainer/).

The reference's trainer ingests scheduler CSV uploads and stubs the
training (trainer/training/training.go:82-99 — ``trainGNN``/``trainMLP``
are TODO bodies).  This package is the real implementation, TPU-native:

- ``ingest``     — columnar shards → shuffled, static-shape, mesh-sharded
                   device batches (replaces the 128 MiB CSV chunk stream,
                   scheduler/announcer/announcer.go:173-237).
- ``train``      — jit/pjit train loops for the MLP regressor and the
                   GraphSAGE/GAT graph models; data-parallel over the
                   ``data`` mesh axis; orbax checkpointing.
- ``export``     — model → local scorer artifact for the scheduler's ML
                   evaluator + model push to the manager registry.
- ``service``    — the Train ingest boundary (per-host dataset keying,
                   trainer/service/service_v1.go:59-160) and the
                   train-on-EOF kick.
"""

from .ingest import EdgeBatches, load_download_dataset, split_columns  # noqa: F401
from .train import (  # noqa: F401
    EvalMetrics,
    TrainConfig,
    train_gat_ranker,
    train_graphsage,
    train_mlp,
)
from .export import MLPScorer, export_from_state, export_mlp_scorer, load_scorer  # noqa: F401
