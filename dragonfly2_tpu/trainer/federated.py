"""Federated multi-cluster training (BASELINE configs[3]).

The reference's deployment model is many scheduler clusters federated by
one manager (SURVEY §2.6 cluster sharding); its intended trainer design
uploads every cluster's records to one trainer.  At fleet scale the
records should stay near their cluster: each cluster trains on its own
shard (its slice's ICI doing the in-cluster data parallelism) and only
**model deltas** cross the WAN/DCN to the manager — classic cross-silo
federated averaging, coordinated through the same model registry the
single-cluster path uses.

Protocol per round (manager-coordinated):
 1. coordinator broadcasts the current global params (round 0: init);
 2. each cluster runs ``local_epochs`` on its own records starting from
    the global params;
 3. coordinator aggregates: FedAvg — weighted mean of params by local
    sample count (McMahan et al. 2017's weighting);
 4. the aggregated model is evaluated on a held-out global split and
    registered (state inactive → operator/auto activation).

Normalization stats federate the same way: weighted moments merge, so one
global scorer artifact serves every cluster.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.mlp import MLPConfig, MLPRegressor, warm_start_output_bias
from ..records.features import mask_post_hoc
from .export import MLPScorer, export_mlp_scorer
from .train import (
    EvalMetrics,
    TrainConfig,
    _huber,
    _make_optimizer,
    _regression_metrics,
)


@dataclass
class FederatedConfig:
    rounds: int = 5
    local_epochs: int = 3
    batch_size: int = 1024
    learning_rate: float = 1e-3
    warmup_steps: int = 10
    seed: int = 0


@dataclass
class ClusterShard:
    """One scheduler cluster's local dataset (rows in DOWNLOAD_COLUMNS)."""

    cluster_id: str
    rows: np.ndarray

    @property
    def n_samples(self) -> int:
        return self.rows.shape[0]


def _tree_weighted_mean(trees: Sequence, weights: Sequence[float]):
    total = float(sum(weights))
    scaled = [
        jax.tree_util.tree_map(lambda x, w=w: np.asarray(x) * (w / total), t)
        for t, w in zip(trees, weights)
    ]
    out = scaled[0]
    for t in scaled[1:]:
        out = jax.tree_util.tree_map(np.add, out, t)
    return out


class FederatedTrainer:
    """Cross-cluster FedAvg of the MLP bandwidth regressor.

    ``train_local`` is overridable: the default runs in-process (each
    cluster's shard trained sequentially); a deployment runs it as the per
    cluster TPU job and ships params back through the manager.
    """

    def __init__(
        self,
        shards: Sequence[ClusterShard],
        *,
        config: Optional[FederatedConfig] = None,
        model_config: Optional[MLPConfig] = None,
    ) -> None:
        if not shards:
            raise ValueError("no cluster shards")
        self.shards = list(shards)
        self.config = config or FederatedConfig()
        self.model_config = model_config or MLPConfig()
        self.model = MLPRegressor(self.model_config)
        self._rng = jax.random.PRNGKey(self.config.seed)
        # Global normalizer from pooled moment merge (post-hoc masked).
        ms, ws = [], []
        for s in self.shards:
            feats = mask_post_hoc(s.rows[:, 2 : 2 + self.model_config.in_dim])
            ms.append((feats.mean(axis=0), feats.var(axis=0)))
            ws.append(s.n_samples)
        total = float(sum(ws))
        mean = sum(m * (w / total) for (m, _), w in zip(ms, ws))
        var = sum(
            (v + (m - mean) ** 2) * (w / total) for (m, v), w in zip(ms, ws)
        )
        std = np.sqrt(var)
        self.feat_mean = mean.astype(np.float32)
        self.feat_std = np.where(std < 1e-3, 1.0, std).astype(np.float32)
        sample = jnp.zeros((2, self.model_config.in_dim), jnp.float32)
        self.global_params = self.model.init(self._rng, sample)["params"]
        # Output bias starts at the global target mean: with Huber's linear
        # tail, a zero-init regressor ~17 log-units from the targets needs
        # many federated rounds just to close the constant offset.
        target_mean = float(
            sum(float(s.rows[:, -1].sum()) for s in self.shards)
            / max(sum(s.n_samples for s in self.shards), 1)
        )
        self.global_params = warm_start_output_bias(self.global_params, target_mean)
        self.history: List[Dict] = []

    # -- local work ----------------------------------------------------------

    def _local_step(self):
        """One shared jitted SGD step: compiled ONCE for the whole
        federation (S shards × R rounds would otherwise recompile S·R
        identical programs).  The optimizer schedule uses the mean shard
        size — per-shard step counts differ only in LR decay pacing."""
        if getattr(self, "_step_fn", None) is not None:
            return self._tx, self._step_fn
        cfg = self.config
        mean_rows = int(np.mean([s.n_samples for s in self.shards]))
        tx = _make_optimizer(
            TrainConfig(
                learning_rate=cfg.learning_rate,
                warmup_steps=cfg.warmup_steps,
                epochs=cfg.local_epochs,
            ),
            max(mean_rows // cfg.batch_size, 1),
        )

        @jax.jit
        def step(params, opt_state, feats, target):
            def loss_fn(p):
                pred = self.model.apply({"params": p}, feats)
                return _huber(pred, target)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            import optax

            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._tx, self._step_fn = tx, step
        return tx, step

    def train_local(self, shard: ClusterShard, params) -> Tuple[dict, int]:
        """One cluster's round: local_epochs of SGD from the global params.
        Returns (new_params, n_samples)."""
        cfg = self.config
        feats_all = mask_post_hoc(
            shard.rows[:, 2 : 2 + self.model_config.in_dim]
        )
        feats_all = (feats_all - self.feat_mean) / self.feat_std
        targets_all = shard.rows[:, -1].astype(np.float32)

        tx, step = self._local_step()
        opt_state = tx.init(params)
        rng = np.random.default_rng(cfg.seed)
        b = min(cfg.batch_size, len(feats_all))
        for epoch in range(cfg.local_epochs):
            order = rng.permutation(len(feats_all))
            for start in range(0, len(order) - b + 1, b):
                idx = order[start : start + b]
                params, opt_state, _ = step(
                    params,
                    opt_state,
                    jnp.asarray(feats_all[idx]),
                    jnp.asarray(targets_all[idx]),
                )
        return params, shard.n_samples

    # -- coordination --------------------------------------------------------

    def run_round(self) -> None:
        results = [self.train_local(s, self.global_params) for s in self.shards]
        params_list = [p for p, _ in results]
        weights = [n for _, n in results]
        self.global_params = jax.tree_util.tree_map(
            jnp.asarray, _tree_weighted_mean(params_list, weights)
        )

    def run(self, eval_rows: Optional[np.ndarray] = None) -> EvalMetrics:
        metrics = EvalMetrics()
        for r in range(self.config.rounds):
            self.run_round()
            if eval_rows is not None:
                metrics = self.evaluate(eval_rows)
                self.history.append({"round": r, "mae": metrics.mae})
        return metrics

    def evaluate(self, rows: np.ndarray) -> EvalMetrics:
        feats = mask_post_hoc(rows[:, 2 : 2 + self.model_config.in_dim])
        feats = (feats - self.feat_mean) / self.feat_std
        pred = np.asarray(
            self.model.apply({"params": self.global_params}, jnp.asarray(feats))
        )
        return _regression_metrics(pred, rows[:, -1].astype(np.float32))

    def export_scorer(self) -> MLPScorer:
        return export_mlp_scorer(
            self.global_params,
            feat_mean=self.feat_mean,
            feat_std=self.feat_std,
            post_hoc_masked=True,
        )

    def publish(self, registry, *, scheduler_id: str = "federated") -> "object":
        """Register the aggregated model (manager CreateModel path)."""
        from .export import scorer_to_bytes

        return registry.create_model(
            name="parent-bandwidth-mlp",
            type="mlp",
            scheduler_id=scheduler_id,
            artifact=scorer_to_bytes(self.export_scorer()),
            evaluation=self.history[-1] if self.history else {},
        )
