"""Trainer metrics (reference: trainer/metrics/metrics.go:33-50 —
training_total / training_failure_total, extended with the TPU loop's
observables)."""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

TRAINING_TOTAL = _reg.counter(
    "trainer_training_total", "Training runs", ["model", "result"]
)
TRAINING_RECORDS = _reg.counter(
    "trainer_training_records_total", "Records consumed by training", ["model"]
)
TRAINING_DURATION = _reg.histogram(
    "trainer_training_duration_seconds", "Wall time per training run",
    buckets=(1, 5, 15, 60, 300, 900, 3600),
)
MODELS_PUBLISHED = _reg.counter(
    "trainer_models_published_total", "Models pushed to the registry", ["model"]
)
# Online node-id lifecycle (trainer/online_graph.py WireIngestAdapter —
# the scheduler host-GC analog, reference scheduler/config/config.go:176-197).
ONLINE_NODES_EVICTED = _reg.counter(
    "trainer_online_nodes_evicted_total",
    "Dense node ids reclaimed by TTL eviction in the online ingest adapter",
)
ONLINE_NODES_RECYCLED = _reg.counter(
    "trainer_online_nodes_recycled_total",
    "Embedding/optimizer rows reset after node-id recycling",
)
ONLINE_OVERFLOW_EDGES = _reg.counter(
    "trainer_online_overflow_edges_total",
    "Edges dropped because the online node table was full",
)
