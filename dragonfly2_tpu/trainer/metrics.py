"""Trainer metrics (reference: trainer/metrics/metrics.go:33-50 —
training_total / training_failure_total, extended with the TPU loop's
observables)."""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

TRAINING_TOTAL = _reg.counter(
    "trainer_training_total", "Training runs", ["model", "result"]
)
TRAINING_RECORDS = _reg.counter(
    "trainer_training_records_total", "Records consumed by training", ["model"]
)
TRAINING_DURATION = _reg.histogram(
    "trainer_training_duration_seconds", "Wall time per training run",
    buckets=(1, 5, 15, 60, 300, 900, 3600),
)
MODELS_PUBLISHED = _reg.counter(
    "trainer_models_published_total", "Models pushed to the registry", ["model"]
)
