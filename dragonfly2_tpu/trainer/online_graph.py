"""Online graph trainer: continuous two-stream ingest + mid-training
snapshot refresh (BASELINE configs[5] as written).

The reference's Train stream feeds BOTH record types continuously —
download rows and network-topology rows (trainer/service/service_v1.go:
128-143 demuxes TrainMlpRequest / TrainGnnRequest on one stream).  Its
training consumer was a stub; here the consumer is the flagship hop
ranker running ONLINE:

- **downloads stream** → fixed-shape edge dispatches ([super_steps,
  batch] src/dst/target), one jitted ``lax.scan`` per dispatch;
- **topology stream** → a bounded most-recent window of probe edges;
  every ``refresh_every`` dispatches the window becomes a NEW graph
  snapshot: ``build_neighbor_table`` + ``precompute_hop_features`` re-run
  mid-training and the hop tables hot-swap **without touching the
  optimizer** (params, Adam moments, LR schedule position, dropout
  stream all continue — the learnable node embedding persists across
  snapshots because node identity does);
- the swap does not recompile: hop features and table are *arguments*
  of the jitted dispatch, and every snapshot has the same static shape
  ([num_nodes, F] / [num_nodes, K]).

Checkpoint/resume (orbax): params, opt state, step, dispatch, snapshot
index, records seen, PLUS the current topology window and node features
— the graph snapshot itself is derived state, rebuilt (deterministically:
build_neighbor_table seeds its sampler) at restore, so a resume lands on
the identical hop tables even when the kill fell between two refreshes.
Byte-identity across a refresh boundary is asserted in
tests/test_online_graph.py and proven at the 1B scale by
tools/soak_online_1b.py.
"""

from __future__ import annotations

import hashlib
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gnn import NeighborTable, build_neighbor_table
from ..models.hop import HopConfig, HopRanker
# Hoisted + static-hops so every snapshot build hits ONE traced program —
# the single cached wrapper shared with trainer/train.py (one DF010
# compile-budget site instead of one per importer).
from ..models.hop import precompute_hop_features_jit as _precompute_jit
from ..parallel.mesh import MODEL_AXIS
from .train import TrainConfig, TrainState, _graph_train_step, _make_optimizer

logger = logging.getLogger(__name__)


def state_hash(state) -> str:
    """sha256 over the params + optimizer bytes — THE byte-identity
    fingerprint the soak tools and tests compare (one definition, so
    'identical' always means the same thing)."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(
        {"params": state.params, "opt": state.opt_state}
    ):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


class WireIngestAdapter:
    """Routes the ``Train`` stream's DECODED rows into an
    ``OnlineGraphTrainer`` — the reference's continuous two-stream feed
    (service_v1.go:128-143) closed end to end over the real wire:
    ``TrainerService(online_sink=this)`` + ``StreamingRowDecoder``.

    Row endpoints arrive as HASH BUCKETS (records/features.py); the
    adapter assigns dense node ids on first sight (capped at the
    trainer's ``num_nodes`` — overflow edges are counted and dropped,
    with a WARNING on first overflow, never silently remapped), keeps
    per-node host-feature sums from the download payloads (the
    node-feature stream), and hands the trainer a LAZY feature source —
    the running mean is materialized once per snapshot build, not per
    wire chunk.

    **Node-id lifecycle** (``OnlineGraphConfig.node_ttl > 0``): real
    swarms churn, so a full table must not freeze the trainer on the
    early-arrivals subgraph.  Mirroring the scheduler's host TTL GC
    (reference scheduler/config/config.go:176-197), a host unseen on
    either stream for ``node_ttl`` seconds is evicted when capacity is
    needed: its dense id returns to a free pool, its feature
    accumulators reset, and the trainer queues an embedding +
    optimizer-moment row reset (applied on the training thread —
    ``OnlineGraphTrainer.apply_pending_recycles``).  Drops while the
    table is full and nothing has expired are TRANSIENT: the same host
    maps successfully once an eviction frees capacity.  Aliasing —
    topology-window or queued edges that still reference a recycled id
    describe the id's previous owner until they age out of the bounded
    window — matches the reference, where GC'd hosts vanish only at the
    next probe round.  Lifecycle mode is wall-clock-driven and therefore
    trades strict byte-identity replay for capacity recycling; the
    determinism soaks keep ``node_ttl=0`` (the default, which preserves
    the fixed first-come mapping exactly).

    **Native fast path** (``OnlineGraphConfig.native_ingest``, default
    on, silent fallback): this class is the SPEC; when the C++ engine
    is available the whole per-chunk pass — mapping, lifecycle,
    feature accumulation, edge buffering — runs in native.cpp's oi_*
    engine without the GIL, and the trainer takes dispatch blocks
    straight from the engine's edge ring (``trainer.block_source``)
    instead of the Python queue.  The measured ceiling of the composed
    wire-fed loop was the single Python consumer process compositing
    every stage under one GIL (BENCHMARKS.md bottleneck ledger), not
    any stage's algorithm.  One deliberate divergence: the native
    engine folds EVERY kept row into the feature means (no
    FEATURE_SAMPLE_ROWS sampling — C++ can afford the full pass).
    """

    def __init__(
        self, trainer: "OnlineGraphTrainer", *, use_native: bool = None
    ) -> None:
        from ..records.features import (
            DOWNLOAD_COLUMNS,
            HOST_FEATURE_DIM,
            NUM_HASH_BUCKETS,
        )

        self.trainer = trainer
        n = trainer.config.num_nodes
        self._native = None
        if use_native is None:
            use_native = trainer.config.native_ingest
        if use_native:
            try:
                from ..native import NativeOnlineIngest

                cfg = trainer.config
                ring = max(cfg.queue_capacity, 2) * (
                    cfg.super_steps * cfg.batch_size
                )
                self._native = NativeOnlineIngest(
                    n, NUM_HASH_BUCKETS, HOST_FEATURE_DIM,
                    len(DOWNLOAD_COLUMNS), cfg.node_ttl, ring,
                )
            except Exception as exc:  # noqa: BLE001 — optimization only
                logger.warning(
                    "native ingest unavailable (%s); python fallback", exc
                )
                self._native = None
            if self._native is not None:
                if (
                    not trainer._downloads.empty()
                    or trainer._leftover is not None
                ):
                    # Switching to the engine's edge ring would silently
                    # strand edges already in the Python queue.  (When
                    # the library is UNAVAILABLE the python fallback
                    # keeps them — so check only after construction.)
                    self._native.close()
                    self._native = None
                    raise RuntimeError(
                        "cannot attach a native-ingest adapter after "
                        "feed_downloads: queued edges would be lost "
                        "(attach the adapter first, or set "
                        "native_ingest=False)"
                    )
                trainer.block_source = self._native_block
        # Vectorized bucket → dense-id table (the ingest hot path must
        # sustain wire rate): -2 = unseen, -1 = overflow.  Unused (but
        # kept allocated) when the native engine owns the mapping.
        self._id_table = np.full(NUM_HASH_BUCKETS, -2, np.int32)
        self._next_id = 0
        self._feat_sum = np.zeros((n, HOST_FEATURE_DIM), np.float32)
        self._feat_cnt = np.zeros(n, np.float32)
        self._py_overflow = 0  # python-path edges + native-path topo drops
        self._py_evicted = 0
        self._native_overflow_seen = 0  # engine counter high-water (metrics)
        self._warned_full = False
        # Lifecycle state: last time each dense id was seen on any
        # stream, its current bucket (for reverse unmapping), and the
        # free pool of recycled ids.
        self._last_seen = np.zeros(n, np.float64)
        self._bucket_of = np.full(n, -1, np.int64)
        self._free: List[int] = []
        self._last_evict_scan = float("-inf")
        # EPOCH time, not monotonic: last-seen stamps live in the
        # checkpoint and must stay comparable across process restarts.
        self.clock = time.time  # injectable for deterministic tests
        self._mu = threading.Lock()
        trainer.node_feature_source = self.node_features
        trainer._adapter = self
        if trainer._adapter_restore is not None:
            self._apply_restore(trainer._adapter_restore)

    @property
    def overflow_edges(self) -> int:
        if self._native is not None:
            return self._native.stats()["overflow_edges"] + self._py_overflow
        return self._py_overflow

    @property
    def evicted_nodes(self) -> int:
        if self._native is not None:
            return self._native.stats()["evicted_nodes"]
        return self._py_evicted

    def _native_block(self, timeout: float):
        """trainer.block_source: one [super_steps, batch] dispatch block
        straight out of the engine's edge ring (a single C++ memcpy —
        no Python-level queue/concatenate on the hot path)."""
        cfg = self.trainer.config
        need = cfg.super_steps * cfg.batch_size
        got = self._native.take_edges(need, timeout)
        if got is None:
            return None
        shape = (cfg.super_steps, cfg.batch_size)
        return (
            got[0].reshape(shape), got[1].reshape(shape),
            got[2].reshape(shape),
        )

    def poll_recycled(self) -> None:
        """Drain engine-side evictions into the trainer's recycle queue
        (the python path queues them inline in _evict_expired)."""
        if self._native is None:
            return
        from .metrics import ONLINE_NODES_EVICTED

        while True:
            ids = self._native.take_recycled()
            if not len(ids):
                return
            ONLINE_NODES_EVICTED.inc(len(ids))
            self.trainer.request_recycle(ids)

    def _apply_restore(self, st: dict) -> None:
        """Re-attach a checkpointed id mapping: the mapping is NOT
        derivable from the stream in ttl mode (eviction is clock-driven),
        so it rides in the trainer checkpoint — host X keeps the dense id
        whose embedding learned X.  The state format is shared between
        the python and native engines: either can restore the other's."""
        n = self.trainer.config.num_nodes
        if len(st["adapter_bucket_of"]) != n:
            # A mismatched num_nodes would OOB-read in the native import
            # (and silently desync the python arrays).
            raise ValueError(
                f"checkpoint adapter state is for num_nodes="
                f"{len(st['adapter_bucket_of'])}, trainer has {n}"
            )
        free = [int(i) for i in st["adapter_free"] if i >= 0]
        if self._native is not None:
            self._native.import_state(
                st["adapter_id_table"], st["adapter_bucket_of"],
                st["adapter_last_seen"], np.asarray(free, np.int32),
                st["adapter_feat_sum"], st["adapter_feat_cnt"],
                int(st["adapter_next_id"]),
                int(st["adapter_overflow_edges"]),
                int(st["adapter_evicted_nodes"]),
            )
            self._py_overflow = 0
            # Sync the metrics high-water to the imported counter, else
            # the first post-restore drop re-counts the whole history.
            self._native_overflow_seen = int(st["adapter_overflow_edges"])
            return
        with self._mu:
            self._id_table = np.asarray(st["adapter_id_table"], np.int32).copy()
            self._bucket_of = np.asarray(st["adapter_bucket_of"], np.int64).copy()
            self._last_seen = np.asarray(st["adapter_last_seen"], np.float64).copy()
            self._free = free
            self._next_id = int(st["adapter_next_id"])
            self._feat_sum = np.asarray(st["adapter_feat_sum"], np.float32).copy()
            self._feat_cnt = np.asarray(st["adapter_feat_cnt"], np.float32).copy()
            self._py_overflow = int(st["adapter_overflow_edges"])
            self._py_evicted = int(st["adapter_evicted_nodes"])
            self._last_evict_scan = float("-inf")

    def snapshot_for_checkpoint(self) -> dict:
        """A consistent (mapping, applied-row-resets) pair for the
        trainer checkpoint: drains + applies pending recycles, then
        snapshots the mapping, retrying if an eviction raced in between
        — a saved mapping must never outrun its embedding resets."""
        while True:
            self.poll_recycled()
            self.trainer.apply_pending_recycles()
            if self._native is not None:
                st = self._native.export_state()
                if st is None:  # eviction landed after the drain
                    continue
                return {
                    "adapter_id_table": st["id_table"],
                    "adapter_bucket_of": st["bucket_of"],
                    "adapter_last_seen": st["last_seen"],
                    # Trailing -1 sentinel: orbax rejects zero-size
                    # arrays, and free ids are always >= 0.
                    "adapter_free": np.concatenate(
                        [st["free"].astype(np.int64), [-1]]
                    ),
                    "adapter_next_id": st["next_id"],
                    "adapter_feat_sum": st["feat_sum"],
                    "adapter_feat_cnt": st["feat_cnt"],
                    "adapter_overflow_edges": (
                        st["overflow_edges"] + self._py_overflow
                    ),
                    "adapter_evicted_nodes": st["evicted_nodes"],
                }
            with self._mu:
                with self.trainer._recycle_lock:
                    if self.trainer._pending_recycle:
                        continue
                return {
                    "adapter_id_table": self._id_table.copy(),
                    "adapter_bucket_of": self._bucket_of.copy(),
                    "adapter_last_seen": self._last_seen.copy(),
                    "adapter_free": np.concatenate(
                        [np.asarray(self._free, np.int64), [-1]]
                    ),
                    "adapter_next_id": int(self._next_id),
                    "adapter_feat_sum": self._feat_sum.copy(),
                    "adapter_feat_cnt": self._feat_cnt.copy(),
                    "adapter_overflow_edges": int(self._py_overflow),
                    "adapter_evicted_nodes": int(self._py_evicted),
                }

    def _evict_expired(self, now: float) -> int:
        """Reclaim dense ids whose hosts fell silent for ``node_ttl``
        (the scheduler's host GC semantics).  Called under ``_mu`` from
        the mapping slow path when the table is full; the O(num_nodes)
        scan is throttled to once per ttl/4."""
        ttl = float(self.trainer.config.node_ttl)
        if ttl <= 0 or now - self._last_evict_scan < ttl * 0.25:
            return 0
        self._last_evict_scan = now
        active = self._bucket_of >= 0
        expired = np.nonzero(active & (now - self._last_seen > ttl))[0]
        if len(expired) == 0:
            return 0
        self._id_table[self._bucket_of[expired]] = -2
        self._bucket_of[expired] = -1
        self._feat_sum[expired] = 0.0
        self._feat_cnt[expired] = 0.0
        self._free.extend(int(i) for i in expired)
        self._py_evicted += len(expired)
        # Un-memoize overflow buckets: freed capacity means previously
        # dropped hosts may claim ids on their next appearance.
        self._id_table[self._id_table == -1] = -2
        self.trainer.request_recycle(expired)
        from .metrics import ONLINE_NODES_EVICTED

        ONLINE_NODES_EVICTED.inc(len(expired))
        logger.info(
            "node lifecycle: evicted %d expired hosts (ttl=%.0fs), "
            "%d ids free", len(expired), ttl, len(self._free),
        )
        return len(expired)

    def _map_ids(self, buckets: np.ndarray, now: float) -> np.ndarray:
        """bucket → dense id; -1 for overflow (node table full).  One
        vectorized gather in steady state; Python only touches buckets
        never seen before (or, in ttl mode, previously dropped)."""
        b = buckets.astype(np.int64)
        out = self._id_table[b]
        ttl_mode = self.trainer.config.node_ttl > 0
        if ttl_mode:
            # Touch BEFORE any eviction: a host present in this very
            # chunk is alive by definition and must not be reclaimed by
            # the scan below, however long it was silent before.
            seen = out[out >= 0]
            if len(seen):
                self._last_seen[seen] = now
        # ttl mode also retries -1 (dropped) buckets: expired capacity
        # may have freed up since — drops must stay transient even when
        # no brand-new bucket arrives to trigger the slow path.
        if (out == -2).any() or (ttl_mode and (out == -1).any()):
            cap = self.trainer.config.num_nodes
            if not self._free and self._next_id >= cap:
                if self._evict_expired(now):
                    # Eviction un-memoized -1 buckets; re-gather so this
                    # chunk's dropped hosts remap right now.
                    out = self._id_table[b]
            for nb in np.unique(b[out == -2]):
                if self._id_table[nb] != -2:
                    continue
                if not self._free and self._next_id >= cap:
                    # The pre-loop attempt only fires when the pool was
                    # ALREADY empty; a small leftover pool can drain
                    # mid-chunk with expired ids still reclaimable (the
                    # scan throttle keeps repeat calls cheap).
                    self._evict_expired(now)
                if self._free:
                    nid = self._free.pop()
                elif self._next_id < cap:
                    nid = self._next_id
                    self._next_id += 1
                else:
                    self._id_table[nb] = -1
                    continue
                self._id_table[nb] = nid
                self._bucket_of[nid] = nb
                self._last_seen[nid] = now
            out = self._id_table[b]
        return out

    def _warn_table_full_once(self) -> None:
        """One warning per adapter lifetime, whichever path drops first
        (callers hold _mu)."""
        if self._warned_full:
            return
        self._warned_full = True
        logger.warning(
            "node table full (num_nodes=%d): dropping edges touching "
            "unmapped hosts%s", self.trainer.config.num_nodes,
            "" if self.trainer.config.node_ttl > 0
            else " (node_ttl=0: drops are permanent)",
        )

    def _count_overflow(self, n_dropped: int) -> None:
        if n_dropped <= 0:
            return
        self._warn_table_full_once()
        self._py_overflow += n_dropped
        from .metrics import ONLINE_OVERFLOW_EDGES

        ONLINE_OVERFLOW_EDGES.inc(n_dropped)

    def close(self) -> None:
        """Release the native engine (its buffers are invisible to the
        Python gc; a parked wire feeder also keeps it alive).  Final
        counters fold into the python-side fields so overflow_edges /
        evicted_nodes stay readable after close.  Idempotent."""
        if self._native is None:
            return
        st = self._native.stats()
        self._py_overflow += int(st["overflow_edges"])
        self._py_evicted = int(st["evicted_nodes"])
        self._native_overflow_seen = 0
        self._native.close()
        self._native = None
        self.trainer.block_source = None

    def node_features(self) -> np.ndarray:
        """Materialize the running per-node feature means — called by the
        trainer ONCE per snapshot build (lazy; never per chunk)."""
        if self._native is not None:
            return self._native.node_features()
        with self._mu:
            return self._feat_sum / np.maximum(self._feat_cnt[:, None], 1.0)

    # Feature-mean accumulation samples at most this many rows per feed:
    # the means converge long before every row has voted, and the full
    # per-row bincount pass was a measured chunk of the wire-ingest
    # budget.  Edges (the training signal) are NEVER sampled.
    FEATURE_SAMPLE_ROWS = 262_144

    def feed_download_rows(self, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        now = self.clock()
        if self._native is not None:
            # The whole per-chunk pass (map, lifecycle, accumulate,
            # ring append w/ backpressure) is ONE GIL-free call.
            self._native.feed_download_rows(rows, now)
            # Engine-side drops must stay observable: same warning +
            # metric the python path emits, driven by the counter delta
            # (under _mu — wire threads feed concurrently).
            with self._mu:
                ov = self._native.stats()["overflow_edges"]
                dropped = ov - self._native_overflow_seen
                if dropped > 0:
                    self._native_overflow_seen = ov
                    self._warn_table_full_once()
                    from .metrics import ONLINE_OVERFLOW_EDGES

                    ONLINE_OVERFLOW_EDGES.inc(dropped)
            return
        with self._mu:
            # ONE mapping call over both endpoint columns: every host in
            # the chunk is touched before any eviction runs, so a live
            # dst can never be reclaimed by the src column's slow path.
            both = self._map_ids(
                np.concatenate([rows[:, 0], rows[:, 1]]), now
            )
            src, dst = both[: len(rows)], both[len(rows):]
            ok = (src >= 0) & (dst >= 0)
            n_bad = int(len(ok) - np.count_nonzero(ok))
            self._count_overflow(n_bad)
            if n_bad:
                src, dst = src[ok], dst[ok]
                kept = rows[ok]
            else:
                kept = rows  # fast path: no 100MB boolean-mask copy
            # Node-feature stream: ONE shared accumulator with the batch
            # trainer (records.features.accumulate_host_feature_sums) so
            # the parent/child attribution cannot drift between paths.
            from ..records.features import accumulate_host_feature_sums

            m = min(len(kept), self.FEATURE_SAMPLE_ROWS)
            accumulate_host_feature_sums(
                kept[:m], src[:m], dst[:m], self._feat_sum, self._feat_cnt
            )
        if len(src):
            self.trainer.feed_downloads(
                src, dst, kept[:, -1].astype(np.float32)
            )

    def feed_topology_rows(self, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        now = self.clock()
        with self._mu:
            # Only the mapping call differs between engines; the engine
            # has its own mutex, so holding _mu around it just keeps the
            # counter updates below single-writer like the python path.
            flat = np.concatenate([rows[:, 0], rows[:, 1]])
            if self._native is not None:
                both = self._native.map_buckets(flat, now)
            else:
                both = self._map_ids(flat, now)
            src, dst = both[: len(rows)], both[len(rows):]
            ok = (src >= 0) & (dst >= 0)
            self._count_overflow(int((~ok).sum()))
            src, dst = src[ok], dst[ok]
            rtt = rows[ok, 2].astype(np.float32)
        if len(src):
            self.trainer.feed_topology(src, dst, rtt)


@dataclass
class OnlineGraphConfig:
    num_nodes: int
    max_neighbors: int = 16
    batch_size: int = 131_072
    super_steps: int = 64            # train steps per jitted dispatch
    refresh_every: int = 0           # dispatches between snapshot swaps (0 = static)
    topo_window: int = 1_000_000     # most-recent probe edges kept for the next snapshot
    checkpoint_every: int = 0        # dispatches (0 = off)
    # Node-id lifecycle for the wire adapter: hosts unseen for this many
    # seconds are evicted when the table is full and their dense ids
    # recycled (embedding + moment rows reset).  0 = off: the mapping is
    # frozen first-come and overflow drops are permanent (the strictly
    # deterministic mode the byte-identity soaks use).
    node_ttl: float = 0.0
    queue_capacity: int = 2          # dispatch blocks of ingest backpressure
    model: HopConfig = field(default_factory=HopConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    total_steps_hint: int = 100_000  # LR schedule horizon
    # C++ wire-ingest fast path (native.cpp oi_* engine): mapping,
    # lifecycle, feature accumulation and edge buffering run GIL-free,
    # and the trainer takes dispatch blocks straight from the engine's
    # ring.  Silently falls back to the (spec) Python adapter when the
    # native library can't build.
    native_ingest: bool = True
    # The config[4]×[5] mode: a (data, model) Mesh with
    # node_sharding="model" partitions the hop table, the embedding
    # table (+ its optimizer moments) AND the snapshot precompute by
    # node over the model axis — the online trainer at the scale where
    # node tables exceed one chip's HBM.  None = single-device.
    mesh: object = None
    node_sharding: str = "replicated"


class OnlineGraphTrainer:
    """The configs[5] consumer: see module docstring."""

    def __init__(
        self,
        config: OnlineGraphConfig,
        *,
        node_feats: np.ndarray,
        topo_src: np.ndarray,
        topo_dst: np.ndarray,
        topo_rtt: np.ndarray,
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        """``node_feats`` + the initial probe edges bootstrap snapshot 0 —
        an online trainer still needs one graph to start ranking on."""
        self.config = config
        self.checkpoint_dir = checkpoint_dir
        self.model = HopRanker(config.model)

        self._topo_lock = threading.Lock()
        self._topo_parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._topo_count = 0
        self._fed_since_swap = 0
        self.node_feats = np.asarray(node_feats, np.float32)
        # Optional lazy provider (the wire adapter sets it): consulted at
        # each snapshot build INSTEAD of the last set_node_features value,
        # so per-chunk feeds never materialize the full feature matrix.
        self.node_feature_source = None
        self.feed_topology(topo_src, topo_dst, topo_rtt)

        self._downloads: "queue.Queue" = queue.Queue(maxsize=config.queue_capacity)
        self._leftover: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        # Set by a native-ingest adapter: dispatch blocks come straight
        # from the C++ edge ring instead of the Python queue.
        self.block_source = None

        self.dispatch = 0
        self.snapshot_idx = 0
        self.records_seen = 0
        # Recycled ids queued by the (ingest-thread) wire adapter; the
        # row resets run on the TRAINING thread between dispatches —
        # the state may be donated mid-dispatch when the adapter fires.
        self._recycle_lock = threading.Lock()
        self._pending_recycle: List[np.ndarray] = []
        self.nodes_recycled = 0
        # Attached wire adapter (if any) — its id mapping checkpoints
        # with the trainer; resume() stashes the restored copy here for
        # the next make_wire_adapter() to re-attach.
        self._adapter: Optional["WireIngestAdapter"] = None
        self._adapter_restore: Optional[dict] = None
        self._window: Tuple[np.ndarray, np.ndarray, np.ndarray] = self._drain_window()
        self._fed_since_swap = 0  # bootstrap topology = snapshot 0's input
        # Snapshot 0 builds LAZILY (_ensure_snapshot) — a resume() right
        # after the constructor replaces the window anyway, and the build
        # is seconds at 100k nodes.
        self.table: Optional[NeighborTable] = None
        self.hop_feats: Optional[jax.Array] = None

        # -- model / optimizer (created ONCE; survives every swap) ----------
        # Params depend on SHAPES only — dummy zero tables keep the
        # constructor free of the snapshot build.
        d_in = self.node_feats.shape[1]
        hop_dim = d_in * (1 + 2 * config.model.hops) + 2  # _hop_parts layout
        dummy_feats = jnp.zeros((config.num_nodes, hop_dim), jnp.float32)
        dummy_table = NeighborTable(
            indices=jnp.zeros((config.num_nodes, config.max_neighbors), jnp.int32),
            mask=jnp.zeros((config.num_nodes, config.max_neighbors), jnp.float32),
            edge_feats=jnp.zeros(
                (config.num_nodes, config.max_neighbors, 1), jnp.float32
            ),
        )
        rng0 = np.random.default_rng(config.train.seed)
        init_ids = jnp.asarray(rng0.integers(0, config.num_nodes, 2), jnp.int32)
        params = self.model.init(
            jax.random.PRNGKey(config.train.seed),
            dummy_feats, dummy_table, init_ids, init_ids,
        )["params"]
        tx = _make_optimizer(
            config.train, config.total_steps_hint // max(config.train.epochs, 1)
        )
        self.state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            dropout_rng=jax.random.PRNGKey(config.train.seed + 1),
        )
        if config.node_sharding not in ("replicated", "model"):
            raise ValueError(f"unknown node_sharding {config.node_sharding!r}")
        if config.node_sharding == "model":
            # config[4]×[5]: node tables (hop features, embedding +
            # moments) partition by node over the mesh's model axis —
            # the SAME leaf sharding train_hop_ranker's MP mode uses —
            # and edge batches shard over the data axis.
            if config.mesh is None:
                raise ValueError('node_sharding="model" needs a mesh')
            if config.num_nodes % config.mesh.shape[MODEL_AXIS]:
                raise ValueError(
                    f"num_nodes {config.num_nodes} not divisible by the "
                    f"model axis {config.mesh.shape[MODEL_AXIS]}"
                )
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, batch_sharding, replicated
            from .train import _node_sharded_state_spec, _node_table_sharding

            mesh = config.mesh
            if config.batch_size % mesh.shape[DATA_AXIS]:
                raise ValueError(
                    f"batch_size {config.batch_size} not divisible by the "
                    f"data axis {mesh.shape[DATA_AXIS]}"
                )
            self._repl = replicated(mesh)
            self._data_shard = batch_sharding(mesh)
            # Dispatch blocks are [super_steps, batch]: the BATCH dim
            # (axis 1) shards over data; the scan dim stays whole.
            block_shard = NamedSharding(mesh, P(None, DATA_AXIS))
            self._nf_shard = _node_table_sharding(mesh)
            self._state_shard = _node_sharded_state_spec(mesh, self.state)
            self.state = jax.device_put(self.state, self._state_shard)
            # The bare replicated sharding acts as a pytree PREFIX for
            # the NeighborTable argument (train.py precedent) — no
            # per-field spelling to desync if the table grows a field.
            self._dispatch_fn = jax.jit(
                self._train_dispatch,
                in_shardings=(
                    self._state_shard, self._nf_shard, self._repl,
                    block_shard, block_shard, block_shard,
                ),
                out_shardings=(self._state_shard, self._repl),
                donate_argnums=(0,),
            )
            self._eval_fn = jax.jit(
                self._eval_mae,
                in_shardings=(
                    self._state_shard, self._nf_shard, self._repl,
                    self._data_shard, self._data_shard, self._data_shard,
                ),
                out_shardings=self._repl,
            )
            self._recycle_fn = jax.jit(
                self._recycle_rows,
                in_shardings=(self._state_shard, self._repl),
                out_shardings=self._state_shard,
                donate_argnums=(0,),
            )
        else:
            # Commit the state once: freshly-created leaves are
            # UNcommitted and the first dispatch would compile a second
            # program the moment the (donated, committed) output comes
            # back for dispatch 2.
            self.state = jax.device_put(self.state, jax.local_devices()[0])
            self._dispatch_fn = jax.jit(
                self._train_dispatch, donate_argnums=(0,)
            )
            self._eval_fn = jax.jit(self._eval_mae)
            self._recycle_fn = jax.jit(
                self._recycle_rows, donate_argnums=(0,)
            )

    # -- ingest: downloads stream -------------------------------------------

    def feed_downloads(
        self, src: np.ndarray, dst: np.ndarray, target: np.ndarray,
        *, block: bool = True,
    ) -> bool:
        """Offer download edges (flat arrays; any length).  Blocks when the
        queue is full — ingest backpressure, like the wire handler."""
        if self.block_source is not None:
            raise RuntimeError(
                "native-ingest adapter attached: downloads must arrive "
                "via the wire adapter, not feed_downloads (the queue "
                "would be silently ignored)"
            )
        try:
            self._downloads.put(
                (
                    np.asarray(src, np.int32),
                    np.asarray(dst, np.int32),
                    np.asarray(target, np.float32),
                ),
                block=block,
            )
            return True
        except queue.Full:
            return False

    def end_of_stream(self) -> None:
        if (
            self._adapter is not None
            and getattr(self._adapter, "_native", None) is not None
        ):
            self._adapter._native.eof()
            return
        self._downloads.put(None)

    def _next_dispatch_block(self, timeout: Optional[float]):
        """Accumulate queued edges into one [super_steps, batch] block
        (static shapes — one compiled program for the whole run)."""
        if self.block_source is not None:
            return self.block_source(timeout if timeout is not None else 3600.0)
        need = self.config.super_steps * self.config.batch_size
        parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        have = 0
        if self._leftover is not None:
            parts.append(self._leftover)
            have = len(self._leftover[0])
            self._leftover = None
        while have < need:
            try:
                item = self._downloads.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                self._downloads.put(None)  # re-post for other waiters
                break
            parts.append(item)
            have += len(item[0])
        if not parts:
            return None
        es = np.concatenate([p[0] for p in parts])
        ed = np.concatenate([p[1] for p in parts])
        y = np.concatenate([p[2] for p in parts])
        if len(es) < need:
            self._leftover = (es, ed, y)
            return None
        self._leftover = (
            (es[need:], ed[need:], y[need:]) if len(es) > need else None
        )
        shape = (self.config.super_steps, self.config.batch_size)
        return (
            es[:need].reshape(shape), ed[:need].reshape(shape),
            y[:need].reshape(shape),
        )

    # -- ingest: topology stream --------------------------------------------

    def feed_topology(
        self, src: np.ndarray, dst: np.ndarray, rtt: np.ndarray
    ) -> None:
        """Offer probe edges (prober → probed, rtt in seconds-scale units —
        whatever build_neighbor_table should see as the edge feature).
        Only the most recent ``topo_window`` edges count toward the next
        snapshot."""
        part = (
            np.asarray(src, np.int32),
            np.asarray(dst, np.int32),
            np.asarray(rtt, np.float32),
        )
        with self._topo_lock:
            self._topo_parts.append(part)
            self._topo_count += len(part[0])
            self._fed_since_swap += len(part[0])
            # Trim whole parts from the front while the window still holds.
            while (
                self._topo_count - len(self._topo_parts[0][0])
                >= self.config.topo_window
            ):
                dropped = self._topo_parts.pop(0)
                self._topo_count -= len(dropped[0])

    def set_node_features(self, node_feats: np.ndarray) -> None:
        """Refresh the host feature matrix (host-record stream analog);
        picked up at the next snapshot build."""
        self.node_feats = np.asarray(node_feats, np.float32)

    def _drain_window(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        with self._topo_lock:
            parts = list(self._topo_parts)
        if not parts:
            return (
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32),
            )
        src = np.concatenate([p[0] for p in parts])[-self.config.topo_window:]
        dst = np.concatenate([p[1] for p in parts])[-self.config.topo_window:]
        rtt = np.concatenate([p[2] for p in parts])[-self.config.topo_window:]
        return src, dst, rtt

    # -- snapshot refresh ----------------------------------------------------

    def _build_snapshot(self, *, use_source: bool = True) -> None:
        """window + node_feats → neighbor table + hop features (device).
        ``use_source=False`` builds from the CURRENT node_feats — the
        resume path restored them from the checkpoint and a fresh
        adapter's (empty) means must not clobber them."""
        if use_source and self.node_feature_source is not None:
            self.node_feats = np.asarray(
                self.node_feature_source(), np.float32
            )
        src, dst, rtt = self._window
        self.table = build_neighbor_table(
            self.config.num_nodes, src, dst, rtt,
            max_neighbors=self.config.max_neighbors,
        )
        if self.config.node_sharding == "model":
            # The snapshot precompute itself runs NODE-SHARDED on the
            # mesh (halo exchange per hop) — at config[4] scale the
            # [N, F] hop table is the memory wall, so no device ever
            # materializes it whole; the output lands already
            # partitioned for the sharded train step.
            from ..parallel.graph_sharding import (
                build_halo_plan,
                precompute_hop_features_sharded,
            )

            plan = build_halo_plan(self.table, self.config.mesh, axis=MODEL_AXIS)
            self.hop_feats = precompute_hop_features_sharded(
                self.config.mesh,
                jnp.asarray(self.node_feats),
                self.table,
                plan,
                hops=self.config.model.hops,
                axis=MODEL_AXIS,
            )
        else:
            self.hop_feats = _precompute_jit(
                jnp.asarray(self.node_feats), self.table,
                hops=self.config.model.hops,
            )
        self.hop_feats.block_until_ready()

    def refresh_snapshot(self) -> Optional[str]:
        """Swap in a snapshot built from the current topology window.
        Returns the new hop-table digest, or None if no topology arrived
        since the last swap (keep serving the old graph rather than pay
        a rebuild for an identical one).  The optimizer, params, LR
        position and dropout stream are untouched."""
        with self._topo_lock:
            fed = self._fed_since_swap
        window = self._drain_window()
        if fed == 0 or len(window[0]) == 0:
            logger.info("snapshot refresh skipped: no new topology")
            return None
        t0 = time.perf_counter()
        self._window = window
        with self._topo_lock:
            self._fed_since_swap = 0
        self._build_snapshot()
        self.snapshot_idx += 1
        digest = self.snapshot_digest()
        logger.info(
            "snapshot %d: %d probe edges, hop digest %s (%.2fs)",
            self.snapshot_idx, len(window[0]), digest[:12],
            time.perf_counter() - t0,
        )
        return digest

    def _ensure_snapshot(self) -> None:
        """Build snapshot 0 on first use (the constructor defers it so a
        resume() doesn't pay for a build it immediately replaces)."""
        if self.hop_feats is None:
            self._build_snapshot()

    def snapshot_digest(self) -> str:
        self._ensure_snapshot()
        return hashlib.sha256(
            np.asarray(self.hop_feats).tobytes()
        ).hexdigest()

    # -- node-id lifecycle ---------------------------------------------------

    def request_recycle(self, node_ids: np.ndarray) -> None:
        """Queue recycled dense ids for an embedding/optimizer row reset.
        Thread-safe; the reset itself runs between dispatches on the
        training thread (``apply_pending_recycles``) because the train
        state is donated while a dispatch is in flight."""
        ids = np.asarray(node_ids, np.int32)
        if ids.size:
            with self._recycle_lock:
                self._pending_recycle.append(ids)

    def apply_pending_recycles(self) -> int:
        """Zero the learnable embedding rows AND their Adam moments for
        every id queued by ``request_recycle`` — a recycled id is a NEW
        host and must not inherit its predecessor's learned state.  Rows
        reset to the embedding init's mean (zero), deterministically.
        Returns the number of distinct rows reset."""
        if self._adapter is not None:
            self._adapter.poll_recycled()  # native evictions queue here
        with self._recycle_lock:
            if not self._pending_recycle:
                return 0
            ids = np.unique(np.concatenate(self._pending_recycle))
            self._pending_recycle = []
        mask = np.zeros(self.config.num_nodes, bool)
        mask[ids] = True
        self.state = self._recycle_fn(self.state, jnp.asarray(mask))
        self.nodes_recycled += int(len(ids))
        from .metrics import ONLINE_NODES_RECYCLED

        ONLINE_NODES_RECYCLED.inc(len(ids))
        return int(len(ids))

    def _recycle_rows(self, state, mask):
        """jitted [N]-mask row reset over every node-table leaf — the
        SAME path predicate as the model-parallel sharding spec
        (train._is_node_table_path), so sharded and replicated modes
        reset identically."""
        from .train import _is_node_table_path

        n = self.config.num_nodes

        def zero_rows(path, leaf):
            if (
                _is_node_table_path(path)
                and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == n
            ):
                bmask = mask.reshape((-1,) + (1,) * (leaf.ndim - 1))
                return jnp.where(bmask, jnp.zeros_like(leaf), leaf)
            return leaf

        return state.replace(
            params=jax.tree_util.tree_map_with_path(zero_rows, state.params),
            opt_state=jax.tree_util.tree_map_with_path(
                zero_rows, state.opt_state
            ),
        )

    # -- train loop ----------------------------------------------------------

    def _train_dispatch(self, state, hop_feats, table, es, ed, y):
        def body(carry, xs):
            b_es, b_ed, b_y = xs
            new_s, loss = _graph_train_step(
                carry, hop_feats, table, b_es, b_ed, b_y, None
            )
            return new_s, loss

        state, losses = jax.lax.scan(body, state, (es, ed, y))
        return state, losses.mean()

    def _eval_mae(self, state, hop_feats, table, es, ed, y):
        pred = state.apply_fn(
            {"params": state.params}, hop_feats, table, es, ed, train=False
        )
        return jnp.abs(pred - y).mean()

    def eval_mae(self, es, ed, y) -> float:
        """Val MAE against the CURRENT snapshot's hop features."""
        self._ensure_snapshot()
        self.apply_pending_recycles()
        return float(
            self._eval_fn(
                self.state, self.hop_feats, self.table,
                jnp.asarray(es, jnp.int32), jnp.asarray(ed, jnp.int32),
                jnp.asarray(y, jnp.float32),
            )
        )

    def run(
        self, *, max_dispatches: Optional[int] = None, idle_timeout: float = 1.0,
    ) -> int:
        """Consume the downloads stream until end_of_stream/idle; refresh
        the graph snapshot every ``refresh_every`` dispatches from the
        topology stream.  Returns dispatches run."""
        cfg = self.config
        self._ensure_snapshot()
        ran = 0
        while max_dispatches is None or ran < max_dispatches:
            block = self._next_dispatch_block(timeout=idle_timeout)
            if block is None:
                break
            # Chaos seam: the trainer-crash drill SIGKILLs here at a
            # deterministic dispatch index — after the previous
            # checkpoint committed, before this block trains.
            from ..utils import faultinject

            faultinject.fire("trainer.dispatch")
            from ..utils.tracing import default_tracer

            # Dispatch span (flight recorder, DESIGN.md §21): one per
            # trained block, so online-training stalls line up against
            # the download/announce traces feeding them.
            with default_tracer.span(
                "trainer/dispatch", dispatch=self.dispatch,
                records=int(block[0].size),
            ):
                self.apply_pending_recycles()
                es, ed, y = block
                self.state, loss = self._dispatch_fn(
                    self.state, self.hop_feats, self.table,
                    jnp.asarray(es), jnp.asarray(ed), jnp.asarray(y),
                )
            self.dispatch += 1
            ran += 1
            self.records_seen += es.size
            if cfg.refresh_every and self.dispatch % cfg.refresh_every == 0:
                self.refresh_snapshot()
            if (
                self.checkpoint_dir
                and cfg.checkpoint_every
                and self.dispatch % cfg.checkpoint_every == 0
            ):
                self.checkpoint()
        # Resets queued after the last dispatch must not linger: an
        # eval/export/checkpoint after run() returns would otherwise
        # score recycled ids with their previous owner's embedding.
        self.apply_pending_recycles()
        return ran

    # -- checkpoint / resume -------------------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(os.path.abspath(self.checkpoint_dir), "online_graph")

    def _payload(self):
        # The pending probe buffer feeds the NEXT drain — without it a
        # resumed run would rebuild a different window at the following
        # refresh than the uninterrupted run (measured: byte-identity
        # broke exactly there).
        with self._topo_lock:
            parts = list(self._topo_parts)
        if parts:
            pend = tuple(
                np.concatenate([p[i] for p in parts]) for i in range(3)
            )
        else:
            pend = (
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32),
            )
        src, dst, rtt = self._window
        # Adapter id-mapping state: clock-driven eviction makes the
        # mapping non-replayable, so it must travel with the checkpoint.
        # Live adapter wins; else carry a restored-but-unclaimed stash
        # forward; else empty-table defaults (same as a fresh adapter).
        ad = self._adapter
        if ad is not None:
            # Consistent pair: the mapping snapshot must not include an
            # eviction whose row reset is still queued (a restore would
            # resurrect the previous owner's embedding/moments).
            ad_state = ad.snapshot_for_checkpoint()
        elif self._adapter_restore is not None:
            ad_state = dict(self._adapter_restore)
        else:
            # No adapter: 1-element sentinel arrays (restore detects the
            # real thing by adapter_id_table's length) — batch-fed
            # trainers don't pay MB-scale dead payload per checkpoint.
            ad_state = {
                "adapter_id_table": np.full(1, -2, np.int32),
                "adapter_bucket_of": np.full(1, -1, np.int64),
                "adapter_last_seen": np.zeros(1, np.float64),
                "adapter_free": np.full(1, -1, np.int64),
                "adapter_next_id": 0,
                "adapter_feat_sum": np.zeros((1, 1), np.float32),
                "adapter_feat_cnt": np.zeros(1, np.float32),
                "adapter_overflow_edges": 0,
                "adapter_evicted_nodes": 0,
            }
        return {
            **ad_state,
            "pending_src": pend[0],
            "pending_dst": pend[1],
            "pending_rtt": pend[2],
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            "step": jnp.asarray(self.state.step, jnp.int32),
            "dropout_rng": self.state.dropout_rng,
            "dispatch": self.dispatch,
            "snapshot_idx": self.snapshot_idx,
            "records_seen": self.records_seen,
            "fed_since_swap": self._fed_since_swap,
            # Derived-state inputs: the snapshot is rebuilt from these at
            # restore (build_neighbor_table seeds its sampler, so the
            # rebuild is bit-identical), instead of checkpointing the
            # [N, F] hop table itself.
            "window_src": src,
            "window_dst": dst,
            "window_rtt": rtt,
            "node_feats": self.node_feats,
        }

    def checkpoint(self) -> None:
        import orbax.checkpoint as ocp

        # Queued row resets are not part of the payload — fold them into
        # the state now so a restore cannot resurrect a recycled id's
        # previous-owner embedding/moments.
        self.apply_pending_recycles()
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(self._ckpt_path(), self._payload(), force=True)
        ckptr.wait_until_finished()

    def make_wire_adapter(self) -> "WireIngestAdapter":
        """An adapter TrainerService(online_sink=...) feeds straight off
        the Train stream — the full wire → online-trainer path."""
        return WireIngestAdapter(self)

    def close(self) -> None:
        """Release stream-side resources (the wire adapter's native
        engine, if any).  Training state is unaffected — checkpoint
        first if it matters."""
        if self._adapter is not None:
            self._adapter.close()

    def resume(self) -> bool:
        """Restore params/opt/step/stream position AND rebuild the graph
        snapshot from the checkpointed topology window; False if no
        checkpoint exists.  A resumed run continues byte-identically —
        including when the checkpoint straddles a refresh boundary."""
        import orbax.checkpoint as ocp

        if not self.checkpoint_dir or not os.path.exists(self._ckpt_path()):
            return False
        ckptr = ocp.StandardCheckpointer()
        abstract = self._payload()
        # Window length varies run to run — restore against the saved
        # shapes, not the current ones.  Orbax's metadata() return shape
        # differs across versions: older releases hand back the tree
        # dict directly, newer ones wrap it in CheckpointMetadata with
        # .item_metadata.tree — accept both (the trainer-crash chaos
        # drill runs resume in whatever orbax the image bakes in).
        meta = ckptr.metadata(self._ckpt_path())
        if not isinstance(meta, dict):
            meta = meta.item_metadata.tree
        for k in (
            "window_src", "window_dst", "window_rtt",
            "pending_src", "pending_dst", "pending_rtt",
        ):
            abstract[k] = np.zeros(meta[k].shape, abstract[k].dtype)
        # Adapter arrays restore against their SAVED shapes (sentinel
        # 1-element when no adapter was attached); checkpoints from
        # before the adapter rode along restore fine without them.
        for k in [k for k in abstract if k.startswith("adapter_")]:
            if k not in meta:
                del abstract[k]
            elif hasattr(abstract[k], "dtype"):
                abstract[k] = np.zeros(meta[k].shape, abstract[k].dtype)
        abstract["node_feats"] = np.zeros(
            meta["node_feats"].shape, np.float32
        )
        restored = ckptr.restore(self._ckpt_path(), abstract)
        # step restores as a STRONG int32 scalar — a weak Python int would
        # compile a different XLA program than the mid-run state's (the
        # byte-identity lesson from the r3 soak).
        self.state = self.state.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            step=jnp.asarray(restored["step"], jnp.int32),
            dropout_rng=jnp.asarray(restored["dropout_rng"], jnp.uint32),
        )
        self.dispatch = int(restored["dispatch"])
        self.snapshot_idx = int(restored["snapshot_idx"])
        self.records_seen = int(restored["records_seen"])
        self.node_feats = np.asarray(restored["node_feats"], np.float32)
        self._window = (
            np.asarray(restored["window_src"], np.int32),
            np.asarray(restored["window_dst"], np.int32),
            np.asarray(restored["window_rtt"], np.float32),
        )
        pend = (
            np.asarray(restored["pending_src"], np.int32),
            np.asarray(restored["pending_dst"], np.int32),
            np.asarray(restored["pending_rtt"], np.float32),
        )
        with self._topo_lock:
            self._topo_parts = [pend] if len(pend[0]) else []
            self._topo_count = len(pend[0])
            self._fed_since_swap = int(restored["fed_since_swap"])
        # Stash the adapter id-mapping for the next make_wire_adapter()
        # (or re-attach it to an already-live adapter in place).  A
        # sentinel-length id table means no adapter state was saved.
        from ..records.features import NUM_HASH_BUCKETS

        saved_table = restored.get("adapter_id_table")
        if saved_table is not None and len(saved_table) == NUM_HASH_BUCKETS:
            self._adapter_restore = {
                k: restored[k] for k in restored if k.startswith("adapter_")
            }
            if self._adapter is not None:
                self._adapter._apply_restore(self._adapter_restore)
        else:
            self._adapter_restore = None
        self._build_snapshot(use_source=False)
        return True
