"""Online graph trainer: continuous two-stream ingest + mid-training
snapshot refresh (BASELINE configs[5] as written).

The reference's Train stream feeds BOTH record types continuously —
download rows and network-topology rows (trainer/service/service_v1.go:
128-143 demuxes TrainMlpRequest / TrainGnnRequest on one stream).  Its
training consumer was a stub; here the consumer is the flagship hop
ranker running ONLINE:

- **downloads stream** → fixed-shape edge dispatches ([super_steps,
  batch] src/dst/target), one jitted ``lax.scan`` per dispatch;
- **topology stream** → a bounded most-recent window of probe edges;
  every ``refresh_every`` dispatches the window becomes a NEW graph
  snapshot: ``build_neighbor_table`` + ``precompute_hop_features`` re-run
  mid-training and the hop tables hot-swap **without touching the
  optimizer** (params, Adam moments, LR schedule position, dropout
  stream all continue — the learnable node embedding persists across
  snapshots because node identity does);
- the swap does not recompile: hop features and table are *arguments*
  of the jitted dispatch, and every snapshot has the same static shape
  ([num_nodes, F] / [num_nodes, K]).

Checkpoint/resume (orbax): params, opt state, step, dispatch, snapshot
index, records seen, PLUS the current topology window and node features
— the graph snapshot itself is derived state, rebuilt (deterministically:
build_neighbor_table seeds its sampler) at restore, so a resume lands on
the identical hop tables even when the kill fell between two refreshes.
Byte-identity across a refresh boundary is asserted in
tests/test_online_graph.py and proven at the 1B scale by
tools/soak_online_1b.py.
"""

from __future__ import annotations

import hashlib
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gnn import NeighborTable, build_neighbor_table
from ..models.hop import HopConfig, HopRanker, precompute_hop_features
from ..parallel.mesh import MODEL_AXIS
from .train import TrainConfig, TrainState, _graph_train_step, _make_optimizer

logger = logging.getLogger(__name__)

# Hoisted + static-hops so every snapshot build hits ONE traced program.
_precompute_jit = jax.jit(precompute_hop_features, static_argnames="hops")


def state_hash(state) -> str:
    """sha256 over the params + optimizer bytes — THE byte-identity
    fingerprint the soak tools and tests compare (one definition, so
    'identical' always means the same thing)."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(
        {"params": state.params, "opt": state.opt_state}
    ):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


class WireIngestAdapter:
    """Routes the ``Train`` stream's DECODED rows into an
    ``OnlineGraphTrainer`` — the reference's continuous two-stream feed
    (service_v1.go:128-143) closed end to end over the real wire:
    ``TrainerService(online_sink=this)`` + ``StreamingRowDecoder``.

    Row endpoints arrive as HASH BUCKETS (records/features.py); the
    adapter assigns dense node ids on first sight (capped at the
    trainer's ``num_nodes`` — overflow edges are counted and dropped,
    with a WARNING on first overflow, never silently remapped), keeps
    per-node host-feature sums from the download payloads (the
    node-feature stream), and hands the trainer a LAZY feature source —
    the running mean is materialized once per snapshot build, not per
    wire chunk.
    """

    def __init__(self, trainer: "OnlineGraphTrainer") -> None:
        from ..records.features import HOST_FEATURE_DIM, NUM_HASH_BUCKETS

        self.trainer = trainer
        n = trainer.config.num_nodes
        # Vectorized bucket → dense-id table (the ingest hot path must
        # sustain wire rate): -2 = unseen, -1 = overflow.
        self._id_table = np.full(NUM_HASH_BUCKETS, -2, np.int32)
        self._next_id = 0
        self._feat_sum = np.zeros((n, HOST_FEATURE_DIM), np.float32)
        self._feat_cnt = np.zeros(n, np.float32)
        self.overflow_edges = 0
        self._mu = threading.Lock()
        trainer.node_feature_source = self.node_features

    def _map_ids(self, buckets: np.ndarray) -> np.ndarray:
        """bucket → dense id; -1 for overflow (node table full).  One
        vectorized gather in steady state; Python only touches buckets
        never seen before."""
        b = buckets.astype(np.int64)
        out = self._id_table[b]
        if (out == -2).any():
            cap = self.trainer.config.num_nodes
            for nb in np.unique(b[out == -2]):
                if self._id_table[nb] != -2:
                    continue
                if self._next_id >= cap:
                    self._id_table[nb] = -1
                    continue
                self._id_table[nb] = self._next_id
                self._next_id += 1
            out = self._id_table[b]
        return out

    def _count_overflow(self, n_dropped: int) -> None:
        if n_dropped <= 0:
            return
        if self.overflow_edges == 0:
            logger.warning(
                "node table full (num_nodes=%d): dropping edges touching "
                "unmapped hosts", self.trainer.config.num_nodes,
            )
        self.overflow_edges += n_dropped

    def node_features(self) -> np.ndarray:
        """Materialize the running per-node feature means — called by the
        trainer ONCE per snapshot build (lazy; never per chunk)."""
        with self._mu:
            return self._feat_sum / np.maximum(self._feat_cnt[:, None], 1.0)

    # Feature-mean accumulation samples at most this many rows per feed:
    # the means converge long before every row has voted, and the full
    # per-row bincount pass was a measured chunk of the wire-ingest
    # budget.  Edges (the training signal) are NEVER sampled.
    FEATURE_SAMPLE_ROWS = 262_144

    def feed_download_rows(self, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        with self._mu:
            src = self._map_ids(rows[:, 0])
            dst = self._map_ids(rows[:, 1])
            ok = (src >= 0) & (dst >= 0)
            n_bad = int(len(ok) - np.count_nonzero(ok))
            self._count_overflow(n_bad)
            if n_bad:
                src, dst = src[ok], dst[ok]
                kept = rows[ok]
            else:
                kept = rows  # fast path: no 100MB boolean-mask copy
            # Node-feature stream: ONE shared accumulator with the batch
            # trainer (records.features.accumulate_host_feature_sums) so
            # the parent/child attribution cannot drift between paths.
            from ..records.features import accumulate_host_feature_sums

            m = min(len(kept), self.FEATURE_SAMPLE_ROWS)
            accumulate_host_feature_sums(
                kept[:m], src[:m], dst[:m], self._feat_sum, self._feat_cnt
            )
        if len(src):
            self.trainer.feed_downloads(
                src, dst, kept[:, -1].astype(np.float32)
            )

    def feed_topology_rows(self, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        with self._mu:
            src = self._map_ids(rows[:, 0])
            dst = self._map_ids(rows[:, 1])
            ok = (src >= 0) & (dst >= 0)
            self._count_overflow(int((~ok).sum()))
            src, dst = src[ok], dst[ok]
            rtt = rows[ok, 2].astype(np.float32)
        if len(src):
            self.trainer.feed_topology(src, dst, rtt)


@dataclass
class OnlineGraphConfig:
    num_nodes: int
    max_neighbors: int = 16
    batch_size: int = 131_072
    super_steps: int = 64            # train steps per jitted dispatch
    refresh_every: int = 0           # dispatches between snapshot swaps (0 = static)
    topo_window: int = 1_000_000     # most-recent probe edges kept for the next snapshot
    checkpoint_every: int = 0        # dispatches (0 = off)
    queue_capacity: int = 2          # dispatch blocks of ingest backpressure
    model: HopConfig = field(default_factory=HopConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    total_steps_hint: int = 100_000  # LR schedule horizon
    # The config[4]×[5] mode: a (data, model) Mesh with
    # node_sharding="model" partitions the hop table, the embedding
    # table (+ its optimizer moments) AND the snapshot precompute by
    # node over the model axis — the online trainer at the scale where
    # node tables exceed one chip's HBM.  None = single-device.
    mesh: object = None
    node_sharding: str = "replicated"


class OnlineGraphTrainer:
    """The configs[5] consumer: see module docstring."""

    def __init__(
        self,
        config: OnlineGraphConfig,
        *,
        node_feats: np.ndarray,
        topo_src: np.ndarray,
        topo_dst: np.ndarray,
        topo_rtt: np.ndarray,
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        """``node_feats`` + the initial probe edges bootstrap snapshot 0 —
        an online trainer still needs one graph to start ranking on."""
        self.config = config
        self.checkpoint_dir = checkpoint_dir
        self.model = HopRanker(config.model)

        self._topo_lock = threading.Lock()
        self._topo_parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._topo_count = 0
        self._fed_since_swap = 0
        self.node_feats = np.asarray(node_feats, np.float32)
        # Optional lazy provider (the wire adapter sets it): consulted at
        # each snapshot build INSTEAD of the last set_node_features value,
        # so per-chunk feeds never materialize the full feature matrix.
        self.node_feature_source = None
        self.feed_topology(topo_src, topo_dst, topo_rtt)

        self._downloads: "queue.Queue" = queue.Queue(maxsize=config.queue_capacity)
        self._leftover: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None

        self.dispatch = 0
        self.snapshot_idx = 0
        self.records_seen = 0
        self._window: Tuple[np.ndarray, np.ndarray, np.ndarray] = self._drain_window()
        self._fed_since_swap = 0  # bootstrap topology = snapshot 0's input
        # Snapshot 0 builds LAZILY (_ensure_snapshot) — a resume() right
        # after the constructor replaces the window anyway, and the build
        # is seconds at 100k nodes.
        self.table: Optional[NeighborTable] = None
        self.hop_feats: Optional[jax.Array] = None

        # -- model / optimizer (created ONCE; survives every swap) ----------
        # Params depend on SHAPES only — dummy zero tables keep the
        # constructor free of the snapshot build.
        d_in = self.node_feats.shape[1]
        hop_dim = d_in * (1 + 2 * config.model.hops) + 2  # _hop_parts layout
        dummy_feats = jnp.zeros((config.num_nodes, hop_dim), jnp.float32)
        dummy_table = NeighborTable(
            indices=jnp.zeros((config.num_nodes, config.max_neighbors), jnp.int32),
            mask=jnp.zeros((config.num_nodes, config.max_neighbors), jnp.float32),
            edge_feats=jnp.zeros(
                (config.num_nodes, config.max_neighbors, 1), jnp.float32
            ),
        )
        rng0 = np.random.default_rng(config.train.seed)
        init_ids = jnp.asarray(rng0.integers(0, config.num_nodes, 2), jnp.int32)
        params = self.model.init(
            jax.random.PRNGKey(config.train.seed),
            dummy_feats, dummy_table, init_ids, init_ids,
        )["params"]
        tx = _make_optimizer(
            config.train, config.total_steps_hint // max(config.train.epochs, 1)
        )
        self.state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            dropout_rng=jax.random.PRNGKey(config.train.seed + 1),
        )
        if config.node_sharding not in ("replicated", "model"):
            raise ValueError(f"unknown node_sharding {config.node_sharding!r}")
        if config.node_sharding == "model":
            # config[4]×[5]: node tables (hop features, embedding +
            # moments) partition by node over the mesh's model axis —
            # the SAME leaf sharding train_hop_ranker's MP mode uses —
            # and edge batches shard over the data axis.
            if config.mesh is None:
                raise ValueError('node_sharding="model" needs a mesh')
            if config.num_nodes % config.mesh.shape[MODEL_AXIS]:
                raise ValueError(
                    f"num_nodes {config.num_nodes} not divisible by the "
                    f"model axis {config.mesh.shape[MODEL_AXIS]}"
                )
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, batch_sharding, replicated
            from .train import _node_sharded_state_spec, _node_table_sharding

            mesh = config.mesh
            if config.batch_size % mesh.shape[DATA_AXIS]:
                raise ValueError(
                    f"batch_size {config.batch_size} not divisible by the "
                    f"data axis {mesh.shape[DATA_AXIS]}"
                )
            self._repl = replicated(mesh)
            self._data_shard = batch_sharding(mesh)
            # Dispatch blocks are [super_steps, batch]: the BATCH dim
            # (axis 1) shards over data; the scan dim stays whole.
            block_shard = NamedSharding(mesh, P(None, DATA_AXIS))
            self._nf_shard = _node_table_sharding(mesh)
            self._state_shard = _node_sharded_state_spec(mesh, self.state)
            self.state = jax.device_put(self.state, self._state_shard)
            # The bare replicated sharding acts as a pytree PREFIX for
            # the NeighborTable argument (train.py precedent) — no
            # per-field spelling to desync if the table grows a field.
            self._dispatch_fn = jax.jit(
                self._train_dispatch,
                in_shardings=(
                    self._state_shard, self._nf_shard, self._repl,
                    block_shard, block_shard, block_shard,
                ),
                out_shardings=(self._state_shard, self._repl),
                donate_argnums=(0,),
            )
            self._eval_fn = jax.jit(
                self._eval_mae,
                in_shardings=(
                    self._state_shard, self._nf_shard, self._repl,
                    self._data_shard, self._data_shard, self._data_shard,
                ),
                out_shardings=self._repl,
            )
        else:
            # Commit the state once: freshly-created leaves are
            # UNcommitted and the first dispatch would compile a second
            # program the moment the (donated, committed) output comes
            # back for dispatch 2.
            self.state = jax.device_put(self.state, jax.local_devices()[0])
            self._dispatch_fn = jax.jit(
                self._train_dispatch, donate_argnums=(0,)
            )
            self._eval_fn = jax.jit(self._eval_mae)

    # -- ingest: downloads stream -------------------------------------------

    def feed_downloads(
        self, src: np.ndarray, dst: np.ndarray, target: np.ndarray,
        *, block: bool = True,
    ) -> bool:
        """Offer download edges (flat arrays; any length).  Blocks when the
        queue is full — ingest backpressure, like the wire handler."""
        try:
            self._downloads.put(
                (
                    np.asarray(src, np.int32),
                    np.asarray(dst, np.int32),
                    np.asarray(target, np.float32),
                ),
                block=block,
            )
            return True
        except queue.Full:
            return False

    def end_of_stream(self) -> None:
        self._downloads.put(None)

    def _next_dispatch_block(self, timeout: Optional[float]):
        """Accumulate queued edges into one [super_steps, batch] block
        (static shapes — one compiled program for the whole run)."""
        need = self.config.super_steps * self.config.batch_size
        parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        have = 0
        if self._leftover is not None:
            parts.append(self._leftover)
            have = len(self._leftover[0])
            self._leftover = None
        while have < need:
            try:
                item = self._downloads.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                self._downloads.put(None)  # re-post for other waiters
                break
            parts.append(item)
            have += len(item[0])
        if not parts:
            return None
        es = np.concatenate([p[0] for p in parts])
        ed = np.concatenate([p[1] for p in parts])
        y = np.concatenate([p[2] for p in parts])
        if len(es) < need:
            self._leftover = (es, ed, y)
            return None
        self._leftover = (
            (es[need:], ed[need:], y[need:]) if len(es) > need else None
        )
        shape = (self.config.super_steps, self.config.batch_size)
        return (
            es[:need].reshape(shape), ed[:need].reshape(shape),
            y[:need].reshape(shape),
        )

    # -- ingest: topology stream --------------------------------------------

    def feed_topology(
        self, src: np.ndarray, dst: np.ndarray, rtt: np.ndarray
    ) -> None:
        """Offer probe edges (prober → probed, rtt in seconds-scale units —
        whatever build_neighbor_table should see as the edge feature).
        Only the most recent ``topo_window`` edges count toward the next
        snapshot."""
        part = (
            np.asarray(src, np.int32),
            np.asarray(dst, np.int32),
            np.asarray(rtt, np.float32),
        )
        with self._topo_lock:
            self._topo_parts.append(part)
            self._topo_count += len(part[0])
            self._fed_since_swap += len(part[0])
            # Trim whole parts from the front while the window still holds.
            while (
                self._topo_count - len(self._topo_parts[0][0])
                >= self.config.topo_window
            ):
                dropped = self._topo_parts.pop(0)
                self._topo_count -= len(dropped[0])

    def set_node_features(self, node_feats: np.ndarray) -> None:
        """Refresh the host feature matrix (host-record stream analog);
        picked up at the next snapshot build."""
        self.node_feats = np.asarray(node_feats, np.float32)

    def _drain_window(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        with self._topo_lock:
            parts = list(self._topo_parts)
        if not parts:
            return (
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32),
            )
        src = np.concatenate([p[0] for p in parts])[-self.config.topo_window:]
        dst = np.concatenate([p[1] for p in parts])[-self.config.topo_window:]
        rtt = np.concatenate([p[2] for p in parts])[-self.config.topo_window:]
        return src, dst, rtt

    # -- snapshot refresh ----------------------------------------------------

    def _build_snapshot(self, *, use_source: bool = True) -> None:
        """window + node_feats → neighbor table + hop features (device).
        ``use_source=False`` builds from the CURRENT node_feats — the
        resume path restored them from the checkpoint and a fresh
        adapter's (empty) means must not clobber them."""
        if use_source and self.node_feature_source is not None:
            self.node_feats = np.asarray(
                self.node_feature_source(), np.float32
            )
        src, dst, rtt = self._window
        self.table = build_neighbor_table(
            self.config.num_nodes, src, dst, rtt,
            max_neighbors=self.config.max_neighbors,
        )
        if self.config.node_sharding == "model":
            # The snapshot precompute itself runs NODE-SHARDED on the
            # mesh (halo exchange per hop) — at config[4] scale the
            # [N, F] hop table is the memory wall, so no device ever
            # materializes it whole; the output lands already
            # partitioned for the sharded train step.
            from ..parallel.graph_sharding import (
                build_halo_plan,
                precompute_hop_features_sharded,
            )

            plan = build_halo_plan(self.table, self.config.mesh, axis=MODEL_AXIS)
            self.hop_feats = precompute_hop_features_sharded(
                self.config.mesh,
                jnp.asarray(self.node_feats),
                self.table,
                plan,
                hops=self.config.model.hops,
                axis=MODEL_AXIS,
            )
        else:
            self.hop_feats = _precompute_jit(
                jnp.asarray(self.node_feats), self.table,
                hops=self.config.model.hops,
            )
        self.hop_feats.block_until_ready()

    def refresh_snapshot(self) -> Optional[str]:
        """Swap in a snapshot built from the current topology window.
        Returns the new hop-table digest, or None if no topology arrived
        since the last swap (keep serving the old graph rather than pay
        a rebuild for an identical one).  The optimizer, params, LR
        position and dropout stream are untouched."""
        with self._topo_lock:
            fed = self._fed_since_swap
        window = self._drain_window()
        if fed == 0 or len(window[0]) == 0:
            logger.info("snapshot refresh skipped: no new topology")
            return None
        t0 = time.perf_counter()
        self._window = window
        with self._topo_lock:
            self._fed_since_swap = 0
        self._build_snapshot()
        self.snapshot_idx += 1
        digest = self.snapshot_digest()
        logger.info(
            "snapshot %d: %d probe edges, hop digest %s (%.2fs)",
            self.snapshot_idx, len(window[0]), digest[:12],
            time.perf_counter() - t0,
        )
        return digest

    def _ensure_snapshot(self) -> None:
        """Build snapshot 0 on first use (the constructor defers it so a
        resume() doesn't pay for a build it immediately replaces)."""
        if self.hop_feats is None:
            self._build_snapshot()

    def snapshot_digest(self) -> str:
        self._ensure_snapshot()
        return hashlib.sha256(
            np.asarray(self.hop_feats).tobytes()
        ).hexdigest()

    # -- train loop ----------------------------------------------------------

    def _train_dispatch(self, state, hop_feats, table, es, ed, y):
        def body(carry, xs):
            b_es, b_ed, b_y = xs
            new_s, loss = _graph_train_step(
                carry, hop_feats, table, b_es, b_ed, b_y, None
            )
            return new_s, loss

        state, losses = jax.lax.scan(body, state, (es, ed, y))
        return state, losses.mean()

    def _eval_mae(self, state, hop_feats, table, es, ed, y):
        pred = state.apply_fn(
            {"params": state.params}, hop_feats, table, es, ed, train=False
        )
        return jnp.abs(pred - y).mean()

    def eval_mae(self, es, ed, y) -> float:
        """Val MAE against the CURRENT snapshot's hop features."""
        self._ensure_snapshot()
        return float(
            self._eval_fn(
                self.state, self.hop_feats, self.table,
                jnp.asarray(es, jnp.int32), jnp.asarray(ed, jnp.int32),
                jnp.asarray(y, jnp.float32),
            )
        )

    def run(
        self, *, max_dispatches: Optional[int] = None, idle_timeout: float = 1.0,
    ) -> int:
        """Consume the downloads stream until end_of_stream/idle; refresh
        the graph snapshot every ``refresh_every`` dispatches from the
        topology stream.  Returns dispatches run."""
        cfg = self.config
        self._ensure_snapshot()
        ran = 0
        while max_dispatches is None or ran < max_dispatches:
            block = self._next_dispatch_block(timeout=idle_timeout)
            if block is None:
                break
            es, ed, y = block
            self.state, loss = self._dispatch_fn(
                self.state, self.hop_feats, self.table,
                jnp.asarray(es), jnp.asarray(ed), jnp.asarray(y),
            )
            self.dispatch += 1
            ran += 1
            self.records_seen += es.size
            if cfg.refresh_every and self.dispatch % cfg.refresh_every == 0:
                self.refresh_snapshot()
            if (
                self.checkpoint_dir
                and cfg.checkpoint_every
                and self.dispatch % cfg.checkpoint_every == 0
            ):
                self.checkpoint()
        return ran

    # -- checkpoint / resume -------------------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(os.path.abspath(self.checkpoint_dir), "online_graph")

    def _payload(self):
        # The pending probe buffer feeds the NEXT drain — without it a
        # resumed run would rebuild a different window at the following
        # refresh than the uninterrupted run (measured: byte-identity
        # broke exactly there).
        with self._topo_lock:
            parts = list(self._topo_parts)
        if parts:
            pend = tuple(
                np.concatenate([p[i] for p in parts]) for i in range(3)
            )
        else:
            pend = (
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32),
            )
        src, dst, rtt = self._window
        return {
            "pending_src": pend[0],
            "pending_dst": pend[1],
            "pending_rtt": pend[2],
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            "step": jnp.asarray(self.state.step, jnp.int32),
            "dropout_rng": self.state.dropout_rng,
            "dispatch": self.dispatch,
            "snapshot_idx": self.snapshot_idx,
            "records_seen": self.records_seen,
            "fed_since_swap": self._fed_since_swap,
            # Derived-state inputs: the snapshot is rebuilt from these at
            # restore (build_neighbor_table seeds its sampler, so the
            # rebuild is bit-identical), instead of checkpointing the
            # [N, F] hop table itself.
            "window_src": src,
            "window_dst": dst,
            "window_rtt": rtt,
            "node_feats": self.node_feats,
        }

    def checkpoint(self) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(self._ckpt_path(), self._payload(), force=True)
        ckptr.wait_until_finished()

    def make_wire_adapter(self) -> "WireIngestAdapter":
        """An adapter TrainerService(online_sink=...) feeds straight off
        the Train stream — the full wire → online-trainer path."""
        return WireIngestAdapter(self)

    def resume(self) -> bool:
        """Restore params/opt/step/stream position AND rebuild the graph
        snapshot from the checkpointed topology window; False if no
        checkpoint exists.  A resumed run continues byte-identically —
        including when the checkpoint straddles a refresh boundary."""
        import orbax.checkpoint as ocp

        if not self.checkpoint_dir or not os.path.exists(self._ckpt_path()):
            return False
        ckptr = ocp.StandardCheckpointer()
        abstract = self._payload()
        # Window length varies run to run — restore against the saved
        # shapes, not the current ones.
        meta = ckptr.metadata(self._ckpt_path()).item_metadata.tree
        for k in (
            "window_src", "window_dst", "window_rtt",
            "pending_src", "pending_dst", "pending_rtt",
        ):
            abstract[k] = np.zeros(meta[k].shape, abstract[k].dtype)
        abstract["node_feats"] = np.zeros(
            meta["node_feats"].shape, np.float32
        )
        restored = ckptr.restore(self._ckpt_path(), abstract)
        # step restores as a STRONG int32 scalar — a weak Python int would
        # compile a different XLA program than the mid-run state's (the
        # byte-identity lesson from the r3 soak).
        self.state = self.state.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            step=jnp.asarray(restored["step"], jnp.int32),
            dropout_rng=jnp.asarray(restored["dropout_rng"], jnp.uint32),
        )
        self.dispatch = int(restored["dispatch"])
        self.snapshot_idx = int(restored["snapshot_idx"])
        self.records_seen = int(restored["records_seen"])
        self.node_feats = np.asarray(restored["node_feats"], np.float32)
        self._window = (
            np.asarray(restored["window_src"], np.int32),
            np.asarray(restored["window_dst"], np.int32),
            np.asarray(restored["window_rtt"], np.float32),
        )
        pend = (
            np.asarray(restored["pending_src"], np.int32),
            np.asarray(restored["pending_dst"], np.int32),
            np.asarray(restored["pending_rtt"], np.float32),
        )
        with self._topo_lock:
            self._topo_parts = [pend] if len(pend[0]) else []
            self._topo_count = len(pend[0])
            self._fed_since_swap = int(restored["fed_since_swap"])
        self._build_snapshot(use_source=False)
        return True
