"""Model export: trained params → scheduler-side scorer artifact.

The reference planned scheduler→Triton RPC inference per scheduling
decision (KServe client at pkg/rpc/inference/client/client_v1.go:86-100,
never wired; Triton model layout at manager/types/model.go:24-73).  A
network round-trip on the parent-selection hot path is the wrong design
for a scheduler that decides in microseconds — instead the trainer exports
the model as a **self-contained numpy artifact** the scheduler applies
locally (scheduler/evaluator.py MLEvaluator).  The manager still versions
and activates these artifacts exactly like the reference versions Triton
dirs (manager/service/model.go:103-190).

Artifact format (.npz):
    meta: json (model type, feature names, version schema)
    w0,b0,w1,b1,...: dense layer weights

The scorer is pure numpy: a 3-layer MLP forward pass over ≤64 candidates
is ~10 µs — cheaper than serializing one Triton request.
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..records.features import DOWNLOAD_FEATURE_NAMES

SCORER_SCHEMA_VERSION = 1


@dataclass
class MLPScorer:
    """EdgeScorer implementation (scheduler/evaluator.py protocol): gelu MLP
    with the training-time feature standardization baked in.

    Batched-score contract: every row of ``features`` is scored from that
    row alone (row-wise standardize → row-wise dense stack), so the
    scheduler's ``ScorerBatcher`` may pad the matrix and coalesce rows
    from unrelated announces into one call — padded/stranger rows cannot
    perturb a request's scores."""

    weights: List[Tuple[np.ndarray, np.ndarray]]  # [(W, b), ...]
    feat_mean: Optional[np.ndarray] = None
    feat_std: Optional[np.ndarray] = None
    # True when the model was trained with post-hoc transfer features zeroed
    # (records/features.mask_post_hoc). The scorer applies the SAME mask at
    # serve time so the train/serve contract travels WITH the artifact —
    # callers never pre-mask.
    post_hoc_masked: bool = True
    # Training-snapshot feature histograms (rollout/shadow.py drift PSI):
    # per-feature quantile bin edges [D, B+1] and the expected bin mass
    # [D, B] over the rows this model trained on.  Stamped INTO the blob
    # so the drift baseline always matches the weights it ships with;
    # None on artifacts exported without rows (drift gating then skips).
    train_bin_edges: Optional[np.ndarray] = None
    train_bin_fracs: Optional[np.ndarray] = None
    feature_names: Tuple[str, ...] = DOWNLOAD_FEATURE_NAMES
    model_type: str = "mlp"
    version: int = SCORER_SCHEMA_VERSION

    def _serving_weights(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Serving fast path: with no standardization in front, zeroing the
        post-hoc feature COLUMNS of x is bit-identical to zeroing those
        input ROWS of W1 (both make the dot-product terms exact 0.0), so
        the per-call mask copy folds into the weights once.  Cached on
        first use; scorer artifacts are immutable after load."""
        folded = getattr(self, "_folded_weights", None)
        if folded is None:
            from ..records.features import POST_HOC_FEATURE_IDX

            w0, b0 = self.weights[0]
            w0 = w0.copy()
            w0[list(POST_HOC_FEATURE_IDX), :] = 0.0
            folded = [(w0, b0)] + list(self.weights[1:])
            object.__setattr__(self, "_folded_weights", folded)
        return folded

    def score(self, features: np.ndarray, **_buckets) -> np.ndarray:  # dflint: hotpath
        # _buckets: src/dst host buckets offered uniformly by the evaluator;
        # the feature-based MLP ignores them (the GNN scorer consumes them).
        x = np.asarray(features, dtype=np.float32)
        if self.feat_mean is not None:
            # Standardization sits BETWEEN mask and stack: masked columns
            # become (0-mean)/std ≠ 0, so the mask cannot fold into W1 —
            # apply it per call, exactly as trained.
            if self.post_hoc_masked:
                from ..records.features import mask_post_hoc

                x = mask_post_hoc(x)
            x = (x - self.feat_mean) / self.feat_std
            weights = self.weights
        elif self.post_hoc_masked:
            weights = self._serving_weights()
        else:
            weights = self.weights
        n = len(weights)
        for i, (w, b) in enumerate(weights):  # dflint: disable=DF007 — per-LAYER (3 fixed), not per-item
            x = x @ w + b
            if i < n - 1:
                x = _np_gelu(x)
        return x[..., 0]


# ---------------------------------------------------------------------------
# Post-training quantization: int8 / bf16 serving variants
# ---------------------------------------------------------------------------

QUANT_MODES = ("int8", "bf16")


def _bf16_round(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(bf16 bit pattern uint16, float32 round-trip) of ``w`` with
    round-to-nearest-even — bf16 is the top 16 bits of float32, so the
    round-trip is pure bit math (no ml_dtypes dependency)."""
    u = np.ascontiguousarray(w, dtype=np.float32).view(np.uint32)
    bits = ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1)))
            >> np.uint32(16)).astype(np.uint16)
    back = (bits.astype(np.uint32) << np.uint32(16)).view(np.float32)
    return bits, back


def _int8_quantize(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(int8 weights, per-output-column float32 scales, float32
    dequantized round-trip) — symmetric per-channel weight-only PTQ:
    ``W ≈ Wq * scale`` with scale_j = max|W[:, j]| / 127."""
    w = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w), axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    deq = (q.astype(np.float32) * scale).astype(np.float32)
    return q, scale, deq


@dataclass
class QuantizedMLPScorer(MLPScorer):
    """Post-training-quantized serving variant of ``MLPScorer``.

    ``weights`` holds the DEQUANTIZED float32 weights, so the entire
    serving machinery (mask-fold into W1, batched-score contract, gelu
    stack) is inherited unchanged — the quantization effect on scores is
    exactly the weight rounding, which is what the rollout plane's
    replay evaluation judges (DESIGN.md §15/§18: a quantized scorer is
    admitted to ACTIVE only through the CANDIDATE → replay-gate flow,
    never assumed score-equivalent).  The blob stores the int8/bf16
    payloads + scales (``_pack``), stamped next to the drift histograms.
    """

    quant_mode: str = "int8"
    # Per-layer quantized payloads: [(int8 W, f32 scales)] for int8,
    # [(uint16 bf16 bits, None)] for bf16.  Kept for packing; scoring
    # uses the dequantized ``weights``.
    qlayers: Optional[List[Tuple[np.ndarray, Optional[np.ndarray]]]] = None


def quantize_scorer(scorer: MLPScorer, mode: str = "int8") -> QuantizedMLPScorer:
    """PTQ an exported float scorer into an int8/bf16 serving variant.

    Carries the ENTIRE serving contract over: post-hoc mask flag,
    standardizer, feature names, and the training-snapshot drift
    histograms (the scales are stamped next to them in the blob, so the
    PSI gate judges the quantized artifact against its own baseline).
    """
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quantization mode {mode!r}; use {QUANT_MODES}")
    qlayers: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
    deq_weights: List[Tuple[np.ndarray, np.ndarray]] = []
    for w, b in scorer.weights:
        if mode == "int8":
            q, scale, deq = _int8_quantize(w)
            qlayers.append((q, scale))
        else:
            bits, deq = _bf16_round(w)
            qlayers.append((bits, None))
        deq_weights.append((deq, np.asarray(b, np.float32)))
    return QuantizedMLPScorer(
        weights=deq_weights,
        feat_mean=scorer.feat_mean,
        feat_std=scorer.feat_std,
        post_hoc_masked=scorer.post_hoc_masked,
        train_bin_edges=scorer.train_bin_edges,
        train_bin_fracs=scorer.train_bin_fracs,
        feature_names=scorer.feature_names,
        model_type=f"mlp_{mode}",
        version=scorer.version,
        quant_mode=mode,
        qlayers=qlayers,
    )


def _dequantize_layers(
    mode: str,
    qlayers: List[Tuple[np.ndarray, Optional[np.ndarray]]],
    biases: List[np.ndarray],
) -> List[Tuple[np.ndarray, np.ndarray]]:
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for (payload, scale), b in zip(qlayers, biases):
        if mode == "int8":
            deq = (payload.astype(np.float32) * scale).astype(np.float32)
        else:
            deq = (payload.astype(np.uint32) << np.uint32(16)).view(np.float32)
        out.append((deq, np.asarray(b, np.float32)))
    return out


def _flatten_mlp_params(params: Dict) -> List[Tuple[np.ndarray, np.ndarray]]:
    """flax MLPRegressor params → ordered [(W, b)] list."""
    layers = sorted(params.keys(), key=lambda k: int(k.split("_")[-1]) if "_" in k else 0)
    out = []
    for name in layers:
        leaf = params[name]
        out.append((np.asarray(leaf["kernel"], np.float32), np.asarray(leaf["bias"], np.float32)))
    return out


def export_mlp_scorer(
    params: Dict,
    *,
    feat_mean: Optional[np.ndarray] = None,
    feat_std: Optional[np.ndarray] = None,
    post_hoc_masked: bool = True,
    feature_names: Tuple[str, ...] = DOWNLOAD_FEATURE_NAMES,
) -> MLPScorer:
    return MLPScorer(
        weights=_flatten_mlp_params(params),
        feat_mean=None if feat_mean is None else np.asarray(feat_mean, np.float32),
        feat_std=None if feat_std is None else np.asarray(feat_std, np.float32),
        post_hoc_masked=post_hoc_masked,
        feature_names=feature_names,
    )


DRIFT_BINS = 10


def feature_snapshot_stats(
    feature_rows: np.ndarray, n_bins: int = DRIFT_BINS
) -> Tuple[np.ndarray, np.ndarray]:
    """(bin edges [D, n_bins+1], bin fractions [D, n_bins]) of the
    training feature distribution — the drift baseline the rollout
    plane's PSI check runs against (rollout/shadow.py).  Quantile edges
    so every feature's expected mass is ~uniform regardless of scale;
    constant features degenerate to one occupied bin, which PSI handles
    (the serve side bins with the SAME edges)."""
    # Reviewed float64 binning intermediates: quantile edges/fractions
    # compute in float64 and round ONCE to float32 on return.
    rows = np.asarray(feature_rows, dtype=np.float64)  # dflint: disable=DF012
    d = rows.shape[1]
    qs = np.linspace(0.0, 1.0, n_bins + 1)
    edges = np.quantile(rows, qs, axis=0).T  # [D, B+1]
    fracs = np.empty((d, n_bins), dtype=np.float64)  # dflint: disable=DF012
    for j in range(d):  # per-FEATURE (32 fixed), export time only
        idx = np.searchsorted(edges[j, 1:-1], rows[:, j])
        fracs[j] = np.bincount(idx, minlength=n_bins) / rows.shape[0]
    return edges.astype(np.float32), fracs.astype(np.float32)


def export_from_state(
    state, *, post_hoc_masked: bool = True, train_feature_rows=None
) -> MLPScorer:
    """TrainState (trainer/train.py) → scorer with its normalizer.

    ``post_hoc_masked`` must state how the training rows were prepared:
    True when they went through features.mask_post_hoc (the deployment
    pipeline, trainer/service.py), False for raw-row experiments.
    ``train_feature_rows`` ([n, DOWNLOAD_FEATURE_DIM], already prepared
    exactly as trained) stamps the drift-baseline histograms into the
    artifact.
    """
    scorer = export_mlp_scorer(
        state.params,
        feat_mean=state.feat_mean,
        feat_std=state.feat_std,
        post_hoc_masked=post_hoc_masked,
    )
    if train_feature_rows is not None and len(train_feature_rows):
        edges, fracs = feature_snapshot_stats(train_feature_rows)
        scorer.train_bin_edges = edges
        scorer.train_bin_fracs = fracs
    return scorer


def _pack(scorer: MLPScorer) -> Dict[str, np.ndarray]:
    arrays: Dict[str, np.ndarray] = {}
    quant_mode = None
    if isinstance(scorer, QuantizedMLPScorer) and scorer.qlayers is not None:
        # Quantized payloads + scales travel IN the blob (scales sit
        # next to the drift histograms below — the artifact is
        # self-contained exactly like the float one).
        quant_mode = scorer.quant_mode
        for i, ((payload, scale), (_, b)) in enumerate(
            zip(scorer.qlayers, scorer.weights)
        ):
            arrays[f"wq{i}"] = payload
            if scale is not None:
                arrays[f"wscale{i}"] = scale
            arrays[f"b{i}"] = b
    else:
        for i, (w, b) in enumerate(scorer.weights):
            arrays[f"w{i}"] = w
            arrays[f"b{i}"] = b
    if scorer.feat_mean is not None:
        arrays["feat_mean"] = scorer.feat_mean
        arrays["feat_std"] = scorer.feat_std
    if scorer.train_bin_edges is not None:
        arrays["train_bin_edges"] = scorer.train_bin_edges
        arrays["train_bin_fracs"] = scorer.train_bin_fracs
    meta = json.dumps(
        {
            "model_type": scorer.model_type,
            "version": scorer.version,
            "n_layers": len(scorer.weights),
            "post_hoc_masked": scorer.post_hoc_masked,
            "feature_names": list(scorer.feature_names),
            "quant_mode": quant_mode,
        }
    )
    arrays["meta"] = np.frombuffer(meta.encode("utf-8"), dtype=np.uint8)
    return arrays


def save_scorer(scorer: MLPScorer, path: str) -> None:
    np.savez(path, **_pack(scorer))


def scorer_to_bytes(scorer: MLPScorer) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_pack(scorer))
    return buf.getvalue()


def load_scorer(path_or_bytes):
    if isinstance(path_or_bytes, (bytes, bytearray)):
        src = io.BytesIO(bytes(path_or_bytes))
    else:
        src = path_or_bytes
    with np.load(src) as data:
        meta = json.loads(bytes(data["meta"]).decode("utf-8"))
        if meta["model_type"] == "gnn":
            return GNNScorer(
                buckets=data["buckets"],
                embeddings=data["embeddings"],
                head_weights=[
                    (data[f"w{i}"], data[f"b{i}"]) for i in range(meta["n_layers"])
                ],
                version=meta["version"],
            )
        quant_mode = meta.get("quant_mode")
        if quant_mode:
            qlayers = [
                (
                    data[f"wq{i}"],
                    data[f"wscale{i}"] if f"wscale{i}" in data else None,
                )
                for i in range(meta["n_layers"])
            ]
            biases = [data[f"b{i}"] for i in range(meta["n_layers"])]
        else:
            weights = [
                (data[f"w{i}"], data[f"b{i}"]) for i in range(meta["n_layers"])
            ]
        feat_mean = data["feat_mean"] if "feat_mean" in data else None
        feat_std = data["feat_std"] if "feat_std" in data else None
        bin_edges = data["train_bin_edges"] if "train_bin_edges" in data else None
        bin_fracs = data["train_bin_fracs"] if "train_bin_fracs" in data else None
    common = dict(
        feat_mean=feat_mean,
        feat_std=feat_std,
        post_hoc_masked=meta.get("post_hoc_masked", True),
        train_bin_edges=bin_edges,
        train_bin_fracs=bin_fracs,
        feature_names=tuple(meta["feature_names"]),
        model_type=meta["model_type"],
        version=meta["version"],
    )
    if quant_mode:
        return QuantizedMLPScorer(
            weights=_dequantize_layers(quant_mode, qlayers, biases),
            quant_mode=quant_mode,
            qlayers=qlayers,
            **common,
        )
    return MLPScorer(weights=weights, **common)


# ---------------------------------------------------------------------------
# GNN scorer: embedding table + head, served host-side by bucket lookup
# ---------------------------------------------------------------------------


def _np_gelu(x: np.ndarray) -> np.ndarray:
    """gelu (tanh approx — matches flax nn.gelu default).  ``x * x * x``,
    NOT ``x**3``: float32 integer-power lowers to a per-element libm
    ``powf`` call (~100× the cost of two multiplies) and was the single
    largest term in the serving path's scorer profile (BENCHMARKS.md)."""
    x3 = x * x * x
    return 0.5 * x * (1.0 + np.tanh(0.7978845608 * (x + 0.044715 * x3)))


@dataclass
class GNNScorer:
    """The GAT ranker's serve-time form.

    The trainer bakes the encoder INTO an embedding table (one forward pass
    per training round — node embeddings change with the graph, not per
    request) and exports table + head.  Serving is two table lookups and a
    3-layer numpy head — same no-RPC hot-path budget as the MLP scorer.
    Hosts unseen at training time fall back to the mean embedding.
    """

    buckets: np.ndarray                       # [N] sorted hash buckets
    embeddings: np.ndarray                    # [N, D]
    head_weights: List[Tuple[np.ndarray, np.ndarray]]
    model_type: str = "gnn"
    version: int = SCORER_SCHEMA_VERSION
    # The evaluator skips per-parent featurization for scorers that rank
    # purely from host identity (scheduler hot-path economy).
    wants_features: bool = False

    def __post_init__(self) -> None:
        self._mean_emb = self.embeddings.mean(axis=0)

    def _lookup(self, bucket_ids: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self.buckets, bucket_ids)
        idx = np.clip(idx, 0, len(self.buckets) - 1)
        hit = self.buckets[idx] == bucket_ids
        emb = self.embeddings[idx]
        emb[~hit] = self._mean_emb
        return emb

    def score(  # dflint: hotpath
        self,
        features: np.ndarray,
        *,
        src_buckets: Optional[np.ndarray] = None,
        dst_buckets: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        # Batched-score contract (EdgeScorer): rows score independently —
        # two table lookups + a row-wise head — so padded micro-batches
        # are safe.  The feature-axis concatenate below is per-CALL
        # column assembly on [n, 3D], not a per-item build loop.
        if src_buckets is None or dst_buckets is None:
            raise ValueError("GNNScorer needs src/dst host buckets")
        s = self._lookup(np.asarray(src_buckets, np.int64))
        d = self._lookup(np.asarray(dst_buckets, np.int64))
        x = np.concatenate([s, d, s * d], axis=-1).astype(np.float32)  # dflint: disable=DF007
        n = len(self.head_weights)
        for i, (w, b) in enumerate(self.head_weights):  # dflint: disable=DF007 — per-LAYER (3 fixed), not per-item
            x = x @ w + b
            if i < n - 1:
                x = _np_gelu(x)
        return x[..., 0]


def export_gnn_scorer(
    model,
    params: Dict,
    node_feats: np.ndarray,
    table,
    buckets: np.ndarray,
) -> GNNScorer:
    """Bake the trained GATRanker into a scorer artifact.

    ``buckets[i]`` is the hash bucket of graph node i (the trainer's dense
    index ↔ host keyspace map).
    """
    import jax.numpy as jnp

    emb = np.asarray(
        model.apply(
            {"params": params},
            jnp.asarray(node_feats, jnp.float32),
            table,
            jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), jnp.int32),
            return_embeddings=True,
        )
    )
    # Head layers: the top-level Dense stack consuming [s, d, s*d].  The
    # GATRanker carries one leading non-head Dense (the embedding
    # projection); the HopRanker's encoder Denses live in a submodule so
    # its head starts at Dense_0 — detect the head start by input width
    # instead of hard-coding the model family.
    dense_names = sorted(
        (k for k in params if k.startswith("Dense_")),
        key=lambda k: int(k.split("_")[1]),
    )
    expected_in = 3 * emb.shape[1]

    def _head_from(start: int):
        """Validate the trailing Dense chain [start:]: widths must chain
        and the final layer must be the scalar score head."""
        ws = [
            (np.asarray(params[k]["kernel"], np.float32),
             np.asarray(params[k]["bias"], np.float32))
            for k in dense_names[start:]
        ]
        if not ws or ws[0][0].shape[0] != expected_in or ws[-1][0].shape[1] != 1:
            return None
        for (w1, _), (w2, _) in zip(ws, ws[1:]):
            if w1.shape[1] != w2.shape[0]:
                return None
        return ws

    # LAST matching start wins: a leading non-head Dense (the GAT's
    # embedding projection) can coincidentally share the input width, but
    # it cannot chain through to the scalar output — the validation above
    # rejects it.
    head = next(
        (
            h
            for i in range(len(dense_names) - 1, -1, -1)
            if np.asarray(params[dense_names[i]]["kernel"]).shape[0] == expected_in
            and (h := _head_from(i)) is not None
        ),
        None,
    )
    if head is None:
        raise ValueError(
            f"no trailing Dense chain consumes [s,d,s*d] width {expected_in} "
            "and ends in a scalar head: models trained with query_edge_feats "
            "are not exportable as a GNNScorer"
        )
    order = np.argsort(buckets)
    return GNNScorer(
        buckets=np.asarray(buckets, np.int64)[order],
        embeddings=emb[order].astype(np.float32),
        head_weights=head,
    )


def gnn_scorer_to_bytes(scorer: GNNScorer) -> bytes:
    arrays: Dict[str, np.ndarray] = {
        "buckets": scorer.buckets,
        "embeddings": scorer.embeddings,
    }
    for i, (w, b) in enumerate(scorer.head_weights):
        arrays[f"w{i}"] = w
        arrays[f"b{i}"] = b
    meta = json.dumps(
        {
            "model_type": "gnn",
            "version": scorer.version,
            "n_layers": len(scorer.head_weights),
        }
    )
    arrays["meta"] = np.frombuffer(meta.encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()
