"""Train loops: MLP regressor + GraphSAGE/GAT, data-parallel over a mesh.

Fills the reference's stub (trainer/training/training.go:60-99): ``Train``
ran trainGNN ∥ trainMLP with TODO bodies; here both are real JAX loops.

Sharding recipe (scaling-book style): one (data, model) mesh; batches
sharded on ``data``; params replicated; the loss all-reduce and gradient
psum are inserted by XLA from the shardings — no hand-written collectives
in the DP path.  The train step is a single jitted function; donated state
keeps HBM flat.

Evaluation matches the manager registry's schema: MLP → MSE/MAE
(manager/rpcserver/manager_server_v1.go CreateModel mlp evaluation),
GNN → additionally precision/recall/F1 of "good parent" classification
(top-half bandwidth), mirroring model.go's GNN evaluation fields.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh

from ..models.gnn import GATRanker, GNNConfig, GraphSAGE, NeighborTable
from ..models.mlp import MLPConfig, MLPRegressor
from ..parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    create_mesh,
    replicated,
)
from .ingest import EdgeBatches


@dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 1e-4
    epochs: int = 5
    warmup_steps: int = 100
    log_every: int = 50
    seed: int = 0


@dataclass
class EvalMetrics:
    """What gets recorded in the model registry (manager model evaluation)."""

    mse: float = 0.0
    mae: float = 0.0                  # log-space MAE
    bandwidth_mae_mbps: float = 0.0   # unlogged, MB/s — BASELINE's headline metric
    precision: float = 0.0
    recall: float = 0.0
    f1: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return {
            "mse": self.mse,
            "mae": self.mae,
            "bandwidth_mae_mbps": self.bandwidth_mae_mbps,
            "precision": self.precision,
            "recall": self.recall,
            "f1": self.f1,
        }


class TrainState(train_state.TrainState):
    dropout_rng: jax.Array = None
    # Feature standardization constants (computed from the training split,
    # applied at train/eval/serve time; exported into the scorer artifact).
    # Raw features mix log-scales (~20) with [0,1] ratios — unnormalized,
    # the regressor conditions poorly and validation MAE roughly doubles.
    feat_mean: jax.Array = None
    feat_std: jax.Array = None


def _huber(pred: jax.Array, target: jax.Array, delta: float = 1.0) -> jax.Array:
    err = pred - target
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    return jnp.mean(0.5 * quad**2 + delta * (abs_err - quad))


def _make_optimizer(cfg: TrainConfig, steps_per_epoch: int) -> optax.GradientTransformation:
    total = max(cfg.epochs * steps_per_epoch, cfg.warmup_steps + 1)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=total,
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=cfg.weight_decay),
    )


# ---------------------------------------------------------------------------
# MLP (BASELINE configs[0]: correctness + MAE parity on 10k records)
# ---------------------------------------------------------------------------


def _mlp_train_step(state: TrainState, feats, target):
    rng = jax.random.fold_in(state.dropout_rng, state.step)
    feats = (feats - state.feat_mean) / state.feat_std

    def loss_fn(params):
        pred = state.apply_fn(
            {"params": params}, feats, train=True, rngs={"dropout": rng}
        )
        return _huber(pred, target)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads=grads), loss


def train_mlp(
    train_data: EdgeBatches,
    val_data: EdgeBatches,
    *,
    model_config: Optional[MLPConfig] = None,
    config: Optional[TrainConfig] = None,
    mesh: Optional[Mesh] = None,
) -> Tuple[TrainState, EvalMetrics, List[Dict[str, float]]]:
    cfg = config or TrainConfig()
    mcfg = model_config or MLPConfig()
    mesh = mesh or create_mesh()
    model = MLPRegressor(mcfg)

    # Batch dim shards over the data axis — round the batch to a multiple.
    data_n = mesh.shape[DATA_AXIS]
    if train_data.batch_size % data_n:
        rounded = max((train_data.batch_size // data_n) * data_n, data_n)
        train_data = EdgeBatches(
            train_data.rows,
            batch_size=rounded,
            shuffle=train_data.shuffle,
            seed=train_data.seed,
            drop_remainder=train_data.drop_remainder,
        )
    if len(train_data) == 0:
        # Silently running zero steps would export an untrained (random)
        # model — fail loudly instead.
        raise ValueError(
            f"no full batches: {train_data.rows.shape[0]} rows < batch "
            f"{train_data.batch_size} (data axis {data_n})"
        )

    rng = jax.random.PRNGKey(cfg.seed)
    init_rng, dropout_rng = jax.random.split(rng)
    sample = jnp.zeros((2, mcfg.in_dim), jnp.float32)
    params = model.init(init_rng, sample)["params"]
    from ..models.mlp import warm_start_output_bias

    params = warm_start_output_bias(
        params, float(train_data.rows[:, -1].mean())
    )
    train_feats = train_data.rows[:, 2 : 2 + mcfg.in_dim]
    feat_mean = jnp.asarray(train_feats.mean(axis=0), jnp.float32)
    raw_std = train_feats.std(axis=0)
    # Columns (near-)constant in training carry no signal — scale them by 1,
    # not by a tiny std that would amplify any serve-time deviation into a
    # distribution explosion (e.g. a single-content-length training corpus
    # meeting a different length at scheduling time).
    feat_std = jnp.asarray(np.where(raw_std < 1e-3, 1.0, raw_std), jnp.float32)
    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=_make_optimizer(cfg, max(len(train_data), 1)),
        dropout_rng=dropout_rng,
        feat_mean=feat_mean,
        feat_std=feat_std,
    )

    data_shard = batch_sharding(mesh)
    repl = replicated(mesh)
    state = jax.device_put(state, repl)
    step = jax.jit(
        _mlp_train_step,
        in_shardings=(repl, data_shard, data_shard),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )

    history: List[Dict[str, float]] = []
    t0 = time.perf_counter()
    seen = 0
    for epoch in range(cfg.epochs):
        for feats, target, _, _ in train_data.epoch(epoch):
            state, loss = step(state, jnp.asarray(feats), jnp.asarray(target))
            seen += feats.shape[0]
            if int(state.step) % cfg.log_every == 0:
                history.append(
                    {
                        "step": int(state.step),
                        "epoch": epoch,
                        "loss": float(loss),
                        "records_per_sec": seen / (time.perf_counter() - t0),
                    }
                )
    metrics = evaluate_mlp(state, val_data)
    return state, metrics, history


def evaluate_mlp(state: TrainState, val_data: EdgeBatches) -> EvalMetrics:
    apply = jax.jit(
        lambda p, x: state.apply_fn(
            {"params": p}, (x - state.feat_mean) / state.feat_std
        )
    )
    preds, targets = [], []
    for feats, target, _, _ in val_data.epoch(0):
        preds.append(np.asarray(apply(state.params, jnp.asarray(feats))))
        targets.append(target)
    return _regression_metrics(np.concatenate(preds), np.concatenate(targets))


def _regression_metrics(pred: np.ndarray, target: np.ndarray) -> EvalMetrics:
    err = pred - target
    mse = float(np.mean(err**2))
    mae = float(np.mean(np.abs(err)))
    bw_mae = float(np.mean(np.abs(np.expm1(pred) - np.expm1(target)))) / 1e6
    # "Good parent" = top-half bandwidth; measures ranking usefulness the way
    # the registry's gnn evaluation wants precision/recall/f1.
    thresh = np.median(target)
    pos_pred, pos_true = pred >= thresh, target >= thresh
    tp = float(np.sum(pos_pred & pos_true))
    precision = tp / max(float(np.sum(pos_pred)), 1.0)
    recall = tp / max(float(np.sum(pos_true)), 1.0)
    f1 = 2 * precision * recall / max(precision + recall, 1e-9)
    return EvalMetrics(
        mse=mse,
        mae=mae,
        bandwidth_mae_mbps=bw_mae,
        precision=precision,
        recall=recall,
        f1=f1,
    )


# ---------------------------------------------------------------------------
# GraphSAGE (configs[1]): self-supervised RTT regression over the probe graph
# ---------------------------------------------------------------------------


def train_graphsage(
    node_feats: np.ndarray,
    table: NeighborTable,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_target: np.ndarray,       # e.g. normalized RTT per probe edge
    *,
    model_config: Optional[GNNConfig] = None,
    config: Optional[TrainConfig] = None,
    mesh: Optional[Mesh] = None,
    batch_size: int = 4096,
) -> Tuple[TrainState, EvalMetrics, List[Dict[str, float]]]:
    """Encoder pretraining: predict per-edge RTT from endpoint embeddings.

    The probe graph's signal (EMA RTT per edge) supervises the encoder; the
    learned embeddings are the node representation the GAT ranker and the
    evaluator-facing scorer build on.
    """
    cfg = config or TrainConfig()
    mcfg = model_config or GNNConfig()
    mesh = mesh or create_mesh()

    # Edge head on top of the encoder, defined inline to keep GraphSAGE reusable.
    import flax.linen as nn

    class _SAGEEdgeModel(nn.Module):
        cfg: GNNConfig

        @nn.compact
        def __call__(self, node_feats, table, src, dst, *, train: bool = False):
            emb = GraphSAGE(self.cfg)(node_feats, table, train=train)
            s = jnp.take(emb, src, axis=0)
            d = jnp.take(emb, dst, axis=0)
            x = jnp.concatenate([s, d, s * d], axis=-1).astype(self.cfg.dtype)
            x = nn.gelu(nn.Dense(self.cfg.hidden, dtype=self.cfg.dtype, param_dtype=jnp.float32)(x))
            return nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(x)[..., 0]

    model = _SAGEEdgeModel(mcfg)
    return _train_graph_model(
        model, node_feats, table, edge_src, edge_dst, edge_target, None,
        cfg, mesh, batch_size,
    )


# ---------------------------------------------------------------------------
# GAT ranker (configs[2]): beats the rule-based evaluator on bandwidth MAE
# ---------------------------------------------------------------------------


def train_gat_ranker(
    node_feats: np.ndarray,
    table: NeighborTable,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_target: np.ndarray,          # log1p bandwidth per download edge
    query_edge_feats: Optional[np.ndarray] = None,
    *,
    model_config: Optional[GNNConfig] = None,
    config: Optional[TrainConfig] = None,
    mesh: Optional[Mesh] = None,
    batch_size: int = 4096,
) -> Tuple[TrainState, EvalMetrics, List[Dict[str, float]]]:
    cfg = config or TrainConfig()
    mcfg = model_config or GNNConfig()
    mesh = mesh or create_mesh()
    model = GATRanker(mcfg)
    return _train_graph_model(
        model, node_feats, table, edge_src, edge_dst, edge_target,
        query_edge_feats, cfg, mesh, batch_size,
    )


def train_hop_ranker(
    node_feats: np.ndarray,
    table: NeighborTable,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_target: np.ndarray,          # log1p bandwidth per download edge
    query_edge_feats: Optional[np.ndarray] = None,
    *,
    model_config=None,
    config: Optional[TrainConfig] = None,
    mesh: Optional[Mesh] = None,
    batch_size: int = 65_536,
    hop_feats: Optional[np.ndarray] = None,
    node_sharding: str = "replicated",
) -> Tuple[TrainState, EvalMetrics, List[Dict[str, float]]]:
    """Scatter-free flagship ranker (models/hop.py): aggregation is
    precomputed once per snapshot, the train step is pure dense MXU work
    on edge batches — measured ~9× faster per step than the GAT at the
    north-star shape with equal-or-better validation quality
    (BENCHMARKS.md).  Pass ``hop_feats`` when the caller already
    precomputed them (the scorer export needs the same array — compute
    once, use twice).  ``node_sharding="model"`` partitions the hop
    features and embedding table by node over the mesh's model axis —
    the config[4] scale mode where node tables exceed one chip's HBM."""
    from ..models.hop import HopConfig, HopRanker, precompute_hop_features_jit

    cfg = config or TrainConfig()
    mcfg = model_config or HopConfig()
    mesh = mesh or create_mesh()
    if hop_feats is None:
        if node_sharding == "model":
            # config[4] scale mode: the [N, F] hop table is the memory
            # wall, so the PRECOMPUTE itself runs node-sharded — per hop
            # one halo all-to-all of boundary rows replaces the full-
            # table gather, and the output lands already sharded
            # P(model) for the train step (no host round-trip).
            from ..parallel.graph_sharding import (
                build_halo_plan,
                precompute_hop_features_sharded,
            )
            from ..parallel.mesh import MODEL_AXIS

            plan = build_halo_plan(table, mesh, axis=MODEL_AXIS)
            hop_feats = precompute_hop_features_sharded(
                mesh,
                jnp.asarray(node_feats, jnp.float32),
                table,
                plan,
                hops=mcfg.hops,
                axis=MODEL_AXIS,
            )
        else:
            # The module-level cached jit (models/hop.py): a per-call
            # jax.jit(partial(...)) here compiled a throwaway program per
            # train_hop_ranker invocation (DF010).
            hop_feats = np.asarray(
                precompute_hop_features_jit(
                    jnp.asarray(node_feats, jnp.float32), table,
                    hops=mcfg.hops,
                )
            )
    model = HopRanker(mcfg)
    return _train_graph_model(
        model, hop_feats, table, edge_src, edge_dst, edge_target,
        query_edge_feats, cfg, mesh, batch_size,
        node_sharding=node_sharding,
    )


def _graph_train_step(state: TrainState, node_feats, table, src, dst, target, qef):
    rng = jax.random.fold_in(state.dropout_rng, state.step)

    def loss_fn(params):
        args = (node_feats, table, src, dst) if qef is None else (node_feats, table, src, dst, qef)
        pred = state.apply_fn(
            {"params": params}, *args, train=True, rngs={"dropout": rng}
        )
        return _huber(pred, target)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads=grads), loss


def _node_table_sharding(mesh: Mesh):
    """THE node-table partition spec: rows sharded over the model axis.
    Single definition — hop features and the embedding/optimizer leaves
    must always shard identically."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MODEL_AXIS

    return NamedSharding(mesh, P(MODEL_AXIS, None))


def _is_node_table_path(path) -> bool:
    """True for leaves that live in per-node tables — the learnable
    embedding and its optimizer moments (they share the 'embedding' key
    path).  THE single definition: the model-parallel sharding spec and
    the online trainer's id-recycling row reset must agree on which
    leaves are node tables, or a recycled id's state silently survives
    in one of them."""
    return any(getattr(p, "key", None) == "embedding" for p in path)


def _node_sharded_state_spec(mesh: Mesh, tree):
    """Sharding tree for model-parallel node tables: the learnable
    embedding table (and its optimizer moments — they share the leaf
    path) partitions by NODE over the model axis; everything else
    replicates.  The config[4] memory story: at 1B-edge scale the node
    tables are the floor, so they shard instead of replicating."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    node_tables = _node_table_sharding(mesh)

    def leaf_spec(path, leaf):
        if _is_node_table_path(path):
            return node_tables
        return repl

    return jax.tree_util.tree_map_with_path(leaf_spec, tree)


def _train_graph_model(
    model,
    node_feats: np.ndarray,
    table: NeighborTable,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_target: np.ndarray,
    query_edge_feats: Optional[np.ndarray],
    cfg: TrainConfig,
    mesh: Mesh,
    batch_size: int,
    node_sharding: str = "replicated",
) -> Tuple[TrainState, EvalMetrics, List[Dict[str, float]]]:
    n_edges = len(edge_src)
    rng = np.random.default_rng(cfg.seed)
    order = rng.permutation(n_edges)
    n_val = max(int(n_edges * 0.1), 1)
    val_idx, train_idx = order[:n_val], order[n_val:]

    jrng = jax.random.PRNGKey(cfg.seed)
    init_rng, dropout_rng = jax.random.split(jrng)
    nf = jnp.asarray(node_feats, jnp.float32)
    b0 = min(batch_size, max(len(train_idx), 2))
    # The batch dim shards over the data axis — round down to a multiple.
    data_n = mesh.shape[DATA_AXIS]
    b0 = max((b0 // data_n) * data_n, data_n)
    if len(train_idx) < b0:
        raise ValueError(
            f"no full batches: {len(train_idx)} train edges < batch {b0} "
            f"(data axis {data_n})"
        )
    sample_args = (
        nf,
        table,
        jnp.zeros((b0,), jnp.int32),
        jnp.zeros((b0,), jnp.int32),
    )
    if query_edge_feats is not None:
        sample_args = sample_args + (jnp.zeros((b0, query_edge_feats.shape[1]), jnp.float32),)
    params = model.init(init_rng, *sample_args)["params"]
    # Output-bias warm start at the training-split target mean (shared fix:
    # models.mlp.warm_start_output_bias — Huber's linear tail otherwise
    # spends the whole run closing the constant offset on short schedules).
    from ..models.mlp import warm_start_output_bias

    params = warm_start_output_bias(params, float(edge_target[train_idx].mean()))

    steps_per_epoch = max(len(train_idx) // b0, 1)
    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=_make_optimizer(cfg, steps_per_epoch),
        dropout_rng=dropout_rng,
    )

    repl = replicated(mesh)
    data_shard = batch_sharding(mesh)
    if node_sharding == "model":
        # Tensor-parallel node tables (VERDICT r2 weak-#7 made a product
        # option): hop features + the embedding table (and its moments)
        # partition by node over the model axis; the endpoint gathers
        # cross shards and XLA inserts the collectives.  Loss parity with
        # the replicated mode is asserted in tests.
        nf_shard = _node_table_sharding(mesh)
        state_shard = _node_sharded_state_spec(mesh, state)
    elif node_sharding == "replicated":
        nf_shard = repl
        state_shard = repl
    else:
        raise ValueError(f"unknown node_sharding {node_sharding!r}")
    state = jax.device_put(state, state_shard)
    nf = jax.device_put(nf, nf_shard)
    dev_table = jax.device_put(table, repl)

    has_qef = query_edge_feats is not None
    in_shardings = (state_shard, nf_shard, repl, data_shard, data_shard, data_shard)
    if has_qef:
        in_shardings = in_shardings + (data_shard,)
        step_fn = jax.jit(
            _graph_train_step,
            in_shardings=in_shardings,
            out_shardings=(state_shard, repl),
            donate_argnums=(0,),
        )
    else:
        step_fn = jax.jit(
            lambda s, n, t, a, b, y: _graph_train_step(s, n, t, a, b, y, None),
            in_shardings=in_shardings,
            out_shardings=(state_shard, repl),
            donate_argnums=(0,),
        )

    history: List[Dict[str, float]] = []
    t0 = time.perf_counter()
    seen = 0
    for epoch in range(cfg.epochs):
        ep_order = np.random.default_rng(cfg.seed + epoch).permutation(train_idx)
        for start in range(0, len(ep_order) - b0 + 1, b0):
            idx = ep_order[start : start + b0]
            args = [
                state,
                nf,
                dev_table,
                jnp.asarray(edge_src[idx], jnp.int32),
                jnp.asarray(edge_dst[idx], jnp.int32),
                jnp.asarray(edge_target[idx], jnp.float32),
            ]
            if has_qef:
                args.append(jnp.asarray(query_edge_feats[idx], jnp.float32))
            state, loss = step_fn(*args)
            seen += b0
            if int(state.step) % cfg.log_every == 0:
                history.append(
                    {
                        "step": int(state.step),
                        "epoch": epoch,
                        "loss": float(loss),
                        "records_per_sec": seen / (time.perf_counter() - t0),
                    }
                )

    # Validation on the held-out edges.
    def predict(idx: np.ndarray) -> np.ndarray:
        args = [
            nf,
            dev_table,
            jnp.asarray(edge_src[idx], jnp.int32),
            jnp.asarray(edge_dst[idx], jnp.int32),
        ]
        if has_qef:
            args.append(jnp.asarray(query_edge_feats[idx], jnp.float32))
        return np.asarray(state.apply_fn({"params": state.params}, *args))

    pred = predict(val_idx)
    metrics = _regression_metrics(pred, edge_target[val_idx])
    return state, metrics, history


# ---------------------------------------------------------------------------
# Checkpointing (orbax) — the reference had nothing to checkpoint; the 10-min
# 1B-record runs need save/restore (SURVEY.md §5.4).
# ---------------------------------------------------------------------------


def save_checkpoint(path: str, state: TrainState) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, {"params": state.params, "step": int(state.step)}, force=True)
    ckptr.wait_until_finished()


def restore_params(path: str) -> Any:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path)["params"]
