"""Online streaming trainer: continuous ingest + checkpoint/resume.

BASELINE configs[4]/[5]: the trainer keeps consuming scheduler record
uploads while training (the reference's design point was batch retraining
every 7 days — announcer.go's Trainer.Interval; here the model tracks the
swarm continuously).  SURVEY §5.4: the reference has no training
checkpointing ("nothing to checkpoint yet"); the 10-minute 1B-record runs
need orbax save/restore, implemented here.

Design:
- a bounded host-side queue of row batches (the ingest boundary — the
  Train stream handler or the columnar tailer feeds it);
- the train loop pulls, normalizes with RUNNING statistics (Welford
  update; a stream has no fixed training split to standardize against),
  and steps the jitted update — one compilation, static batch shape;
- every ``checkpoint_every`` steps the full state (params, opt state,
  step, normalizer moments) checkpoints via orbax; ``resume()`` restores
  and continues byte-identically.
"""

from __future__ import annotations

import os
import queue
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.mlp import MLPConfig, MLPRegressor, warm_start_output_bias
from ..records.features import DOWNLOAD_FEATURE_DIM, mask_post_hoc
from .train import _huber


@dataclass
class StreamingConfig:
    batch_size: int = 4096
    checkpoint_every: int = 200       # steps
    queue_capacity: int = 64          # batches of backpressure
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    decay_steps: int = 100_000
    seed: int = 0
    # Drift-baseline window: the most recent masked feature rows kept for
    # stamping train_bin_edges/train_bin_fracs into exported scorers
    # (trainer/export.feature_snapshot_stats).  A stream has no fixed
    # training split, so the baseline IS the trailing window the weights
    # were last fitted against.  0 disables stamping.
    snapshot_rows: int = 4096


class RunningMoments:
    """Welford running mean/variance over feature columns (stream-safe)."""

    def __init__(self, dim: int) -> None:
        self.count = 0.0
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)

    def update(self, batch: np.ndarray) -> None:
        n_b = batch.shape[0]
        if n_b == 0:
            return
        b_mean = batch.mean(axis=0)
        b_var = batch.var(axis=0)
        n_a = self.count
        n = n_a + n_b
        delta = b_mean - self.mean
        self.mean += delta * (n_b / n)
        self.m2 += b_var * n_b + (delta**2) * (n_a * n_b / n)
        self.count = n

    @property
    def std(self) -> np.ndarray:
        if self.count < 2:
            return np.ones_like(self.mean)
        s = np.sqrt(self.m2 / self.count)
        return np.where(s < 1e-3, 1.0, s)

    def to_arrays(self) -> Dict[str, np.ndarray]:
        return {
            "count": np.asarray([self.count]),
            "mean": self.mean.copy(),
            "m2": self.m2.copy(),
        }

    @classmethod
    def from_arrays(cls, data: Dict[str, np.ndarray]) -> "RunningMoments":
        rm = cls(len(data["mean"]))
        rm.count = float(np.asarray(data["count"]).reshape(-1)[0])
        rm.mean = np.asarray(data["mean"], np.float64).copy()
        rm.m2 = np.asarray(data["m2"], np.float64).copy()
        return rm


class StreamingTrainer:
    """MLP streaming trainer (the GNN streaming path builds on the same
    queue/checkpoint skeleton in a later round)."""

    def __init__(
        self,
        config: Optional[StreamingConfig] = None,
        model_config: Optional[MLPConfig] = None,
        *,
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        self.config = config or StreamingConfig()
        self.model_config = model_config or MLPConfig()
        self.checkpoint_dir = checkpoint_dir
        self.model = MLPRegressor(self.model_config)
        self._queue: "queue.Queue[Optional[np.ndarray]]" = queue.Queue(
            maxsize=self.config.queue_capacity
        )
        self.moments = RunningMoments(self.model_config.in_dim)
        self.records_seen = 0
        self._leftover: Optional[np.ndarray] = None
        self._bias_initialized = False
        # Trailing-window feature ring for the exported drift baseline.
        self._snapshot: Optional[np.ndarray] = None
        self._snapshot_pos = 0
        self._snapshot_count = 0
        self._init_state()
        self._step_fn = jax.jit(self._train_step, donate_argnums=(0, 1))

    # -- state ---------------------------------------------------------------

    def _make_tx(self):
        cfg = self.config
        import optax

        schedule = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, cfg.warmup_steps, cfg.decay_steps
        )
        return optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(schedule, weight_decay=cfg.weight_decay),
        )

    def _init_state(self) -> None:
        rng = jax.random.PRNGKey(self.config.seed)
        sample = jnp.zeros((2, self.model_config.in_dim), jnp.float32)
        self.params = self.model.init(rng, sample)["params"]
        self.tx = self._make_tx()
        self.opt_state = self.tx.init(self.params)
        self.step = 0

    def _train_step(self, params, opt_state, feats, target, mean, std):
        feats = (feats - mean) / std

        def loss_fn(p):
            pred = self.model.apply({"params": p}, feats)
            return _huber(pred, target)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # -- ingest --------------------------------------------------------------

    def feed(self, rows: np.ndarray, *, block: bool = True) -> bool:
        """Offer a [n, DOWNLOAD_COLUMNS] row batch; False if full (non-block)."""
        try:
            self._queue.put(np.asarray(rows, np.float32), block=block)
            return True
        except queue.Full:
            return False

    def end_of_stream(self) -> None:
        self._queue.put(None)

    # -- train loop ----------------------------------------------------------

    def _next_batch(self, timeout: Optional[float]) -> Optional[np.ndarray]:
        """Accumulate queued rows into one fixed-size batch (static shapes)."""
        bs = self.config.batch_size
        parts: List[np.ndarray] = []
        have = 0
        if self._leftover is not None:
            parts.append(self._leftover)
            have = len(self._leftover)
            self._leftover = None
        while have < bs:
            try:
                rows = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            if rows is None:  # end of stream sentinel
                self._queue.put(None)  # re-post for other waiters
                break
            parts.append(rows)
            have += len(rows)
        if not parts:
            return None
        all_rows = np.concatenate(parts, axis=0)
        if len(all_rows) < bs:
            self._leftover = all_rows
            return None
        batch, self._leftover = all_rows[:bs], all_rows[bs:]
        if len(self._leftover) == 0:
            self._leftover = None
        return batch

    def run(self, *, max_steps: Optional[int] = None, idle_timeout: float = 1.0) -> int:
        """Consume the stream until end_of_stream (or idle) — returns steps run."""
        steps_run = 0
        while max_steps is None or steps_run < max_steps:
            batch = self._next_batch(timeout=idle_timeout)
            if batch is None:
                break
            feats = mask_post_hoc(batch[:, 2 : 2 + DOWNLOAD_FEATURE_DIM])
            target = batch[:, -1].astype(np.float32)
            if not self._bias_initialized:
                # First batch's target mean warm-starts the output bias
                # (models.mlp.warm_start_output_bias — shared with the
                # federated trainer).
                self.params = warm_start_output_bias(
                    self.params, float(target.mean())
                )
                self._bias_initialized = True
            self.moments.update(feats)
            self._note_features(feats)
            self.records_seen += len(batch)
            self.params, self.opt_state, loss = self._step_fn(
                self.params,
                self.opt_state,
                jnp.asarray(feats),
                jnp.asarray(target),
                jnp.asarray(self.moments.mean, jnp.float32),
                jnp.asarray(self.moments.std, jnp.float32),
            )
            self.step += 1
            steps_run += 1
            if (
                self.checkpoint_dir
                and self.step % self.config.checkpoint_every == 0
            ):
                self.checkpoint()
        return steps_run

    # -- drift-baseline window ------------------------------------------------

    def _note_features(self, feats: np.ndarray) -> None:
        """Ring-append trained (masked) feature rows for the drift
        baseline.  Order inside the ring is irrelevant: the baseline is
        quantile histograms, a pure function of the row multiset."""
        cap = self.config.snapshot_rows
        if cap <= 0 or feats.shape[0] == 0:
            return
        if self._snapshot is None:
            self._snapshot = np.zeros((cap, feats.shape[1]), np.float32)
        n = len(feats)
        if n >= cap:
            self._snapshot[:] = feats[-cap:]
            self._snapshot_pos = 0
            self._snapshot_count = cap
            return
        pos = self._snapshot_pos
        end = pos + n
        if end <= cap:
            self._snapshot[pos:end] = feats
        else:
            k = cap - pos
            self._snapshot[pos:] = feats[:k]
            self._snapshot[: end - cap] = feats[k:]
        self._snapshot_pos = end % cap
        self._snapshot_count = min(cap, self._snapshot_count + n)

    def snapshot_feature_rows(self) -> Optional[np.ndarray]:
        """The trailing feature window (None before any training step)."""
        if self._snapshot is None or self._snapshot_count == 0:
            return None
        return self._snapshot[: self._snapshot_count]

    # -- checkpoint / resume (orbax) -----------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(os.path.abspath(self.checkpoint_dir), "stream")

    def checkpoint(self) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        payload = {
            "params": self.params,
            "opt_state": self.opt_state,
            "step": self.step,
            "records_seen": self.records_seen,
            "bias_initialized": int(self._bias_initialized),
            "moments": self.moments.to_arrays(),
            # Drift window travels with the weights: a resumed trainer
            # exports the SAME baseline it would have exported pre-crash.
            "snapshot": (
                self._snapshot
                if self._snapshot is not None
                else np.zeros(
                    (max(self.config.snapshot_rows, 1), self.model_config.in_dim),
                    np.float32,
                )
            ),
            "snapshot_pos": self._snapshot_pos,
            "snapshot_count": self._snapshot_count,
        }
        ckptr.save(self._ckpt_path(), payload, force=True)
        ckptr.wait_until_finished()

    def resume(self) -> bool:
        """Restore the latest checkpoint; False if none exists."""
        import orbax.checkpoint as ocp

        path = self._ckpt_path()
        if not os.path.exists(path):
            return False
        ckptr = ocp.StandardCheckpointer()
        abstract = {
            "params": self.params,
            "opt_state": self.opt_state,
            "step": 0,
            "records_seen": 0,
            "bias_initialized": 0,
            "moments": self.moments.to_arrays(),
            "snapshot": np.zeros(
                (max(self.config.snapshot_rows, 1), self.model_config.in_dim),
                np.float32,
            ),
            "snapshot_pos": 0,
            "snapshot_count": 0,
        }
        try:
            restored = ckptr.restore(path, abstract)
            self._bias_initialized = bool(restored["bias_initialized"])
        except Exception:  # noqa: BLE001 — legacy checkpoint (pre-snapshot)
            for key in ("snapshot", "snapshot_pos", "snapshot_count"):
                del abstract[key]
            try:
                restored = ckptr.restore(path, abstract)
                self._bias_initialized = bool(restored["bias_initialized"])
            except Exception:  # noqa: BLE001 — legacy checkpoint (pre-flag)
                del abstract["bias_initialized"]
                restored = ckptr.restore(path, abstract)
                # A legacy checkpoint has trained params: the bias offset is
                # already baked in — re-applying it would corrupt the model.
                self._bias_initialized = True
        if "snapshot" in restored:
            self._snapshot_count = int(restored["snapshot_count"])
            self._snapshot_pos = int(restored["snapshot_pos"])
            self._snapshot = (
                np.asarray(restored["snapshot"], np.float32).copy()
                if self._snapshot_count
                else None
            )
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.step = int(restored["step"])
        self.records_seen = int(restored["records_seen"])
        self.moments = RunningMoments.from_arrays(restored["moments"])
        return True

    # -- export --------------------------------------------------------------

    def export_scorer(self):
        from .export import export_mlp_scorer, feature_snapshot_stats

        scorer = export_mlp_scorer(
            self.params,
            feat_mean=self.moments.mean.astype(np.float32),
            feat_std=self.moments.std.astype(np.float32),
            post_hoc_masked=True,
        )
        # Stamp the drift baseline exactly like trainer/export's batch
        # path (export_from_state): without it a streaming-trained
        # candidate would sail past the rollout plane's PSI gate blind.
        rows = self.snapshot_feature_rows()
        if rows is not None and len(rows):
            edges, fracs = feature_snapshot_stats(rows)
            scorer.train_bin_edges = edges
            scorer.train_bin_fracs = fracs
        return scorer
