"""Stress: concurrent download load generator with latency statistics.

Reference: test/tools/stress/main.go — fires concurrent downloads and
reports throughput + latency percentiles.  Drives any conductor-shaped
downloader (embedded daemon, wire node) against a task catalog.

Library + CLI:  ``python -m dragonfly2_tpu.tools.stress --help``.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class StressReport:
    total: int = 0
    succeeded: int = 0
    failed: int = 0
    bytes: int = 0
    wall_s: float = 0.0
    latencies_s: List[float] = field(default_factory=list)

    @property
    def throughput_mbps(self) -> float:
        return self.bytes / max(self.wall_s, 1e-9) / 1e6

    @property
    def rps(self) -> float:
        return self.succeeded / max(self.wall_s, 1e-9)

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile: ceil(p/100 * n) - 1 (p99 of 100 samples
        is the 99th value, not the max)."""
        if not self.latencies_s:
            return 0.0
        data = sorted(self.latencies_s)
        import math

        idx = max(math.ceil(p / 100.0 * len(data)) - 1, 0)
        return data[min(idx, len(data) - 1)]

    def summary(self) -> Dict:
        return {
            "total": self.total,
            "succeeded": self.succeeded,
            "failed": self.failed,
            "throughput_MBps": round(self.throughput_mbps, 2),
            "downloads_per_sec": round(self.rps, 2),
            "latency_p50_ms": round(self.percentile(50) * 1e3, 2),
            "latency_p95_ms": round(self.percentile(95) * 1e3, 2),
            "latency_p99_ms": round(self.percentile(99) * 1e3, 2),
        }


def run_stress(
    download: Callable[[str], "object"],
    urls: List[str],
    *,
    concurrency: int = 8,
    total: int = 100,
) -> StressReport:
    """Fire ``total`` downloads over ``urls`` with ``concurrency`` workers.

    ``download(url)`` must return an object with ``ok`` and ``bytes``
    attributes (DownloadResult-shaped).
    """
    if not urls:
        raise ValueError("run_stress needs at least one url")
    report = StressReport(total=total)
    lock = threading.Lock()
    counter = {"i": 0}

    def worker() -> None:
        while True:
            with lock:
                if counter["i"] >= total:
                    return
                i = counter["i"]
                counter["i"] += 1
            url = urls[i % len(urls)]
            t0 = time.perf_counter()
            try:
                result = download(url)
                ok = bool(getattr(result, "ok", False))
                nbytes = int(getattr(result, "bytes", 0))
            except Exception as exc:  # noqa: BLE001 — load-gen counts failures
                logging.getLogger(__name__).debug("download %s failed: %s", url, exc)
                ok, nbytes = False, 0
            dt = time.perf_counter() - t0
            with lock:
                if ok:
                    report.succeeded += 1
                    report.bytes += nbytes
                    report.latencies_s.append(dt)
                else:
                    report.failed += 1

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, daemon=True) for _ in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        # Bounded join loop (DF008 timeout sweep): a hung worker shows up
        # in watchdog stack dumps rather than freezing the run silently.
        while t.is_alive():
            t.join(5.0)
    report.wall_s = time.perf_counter() - t0
    return report


def main(argv: Optional[list] = None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser("stress", description="P2P download load generator")
    p.add_argument("--scheduler", required=True, help="scheduler RPC URL")
    p.add_argument("--url", action="append", required=True, help="source URL (repeatable)")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--total", type=int, default=100)
    p.add_argument("--piece-size", type=int, default=4 << 20)
    p.add_argument("--work-dir", default=None)
    args = p.parse_args(argv)

    import tempfile

    from ..daemon import DaemonStorage, UploadManager
    from ..daemon.conductor import Conductor
    from ..rpc import HTTPPieceFetcher, PieceHTTPServer, RemoteScheduler
    from ..scheduler.resource import Host
    from ..source import PieceSourceFetcher
    from ..utils import idgen

    work = args.work_dir or tempfile.mkdtemp(prefix="stress-")
    storage = DaemonStorage(work)
    upload = UploadManager(storage)
    piece_server = PieceHTTPServer(upload)
    piece_server.serve()
    host = Host(
        id=idgen.host_id_v2("127.0.0.1", f"stress-{piece_server.port}"),
        hostname="stress",
        ip="127.0.0.1",
        download_port=piece_server.port,
    )
    client = RemoteScheduler(args.scheduler)
    source = PieceSourceFetcher()
    conductor = Conductor(
        host, storage, client,
        piece_fetcher=HTTPPieceFetcher(client.resolve_host),
        source_fetcher=source,
    )

    def download(url: str):
        content_length = source.content_length(url)
        if content_length < 0:
            # -1 would yield a fake 0-piece "success" — fail the sample.
            raise IOError(f"cannot size {url}")
        return conductor.download(
            url, piece_size=args.piece_size, content_length=content_length
        )

    report = run_stress(
        download, args.url, concurrency=args.concurrency, total=args.total
    )
    print(json.dumps(report.summary()))
    piece_server.stop()
    return 0 if report.failed == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
