"""Operational tools (reference: test/tools/ — stress load generator,
fixture servers)."""
