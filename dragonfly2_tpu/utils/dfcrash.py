"""Dynamic crash witness: runtime validation of the persistence inventory.

``tools/dflint/staterules.py`` (DF014) statically inventories every
KVTable write site — (namespace, callsite, method) — and declares which
sites are multi-row transactions that must stay ONE ``put_many``.
Static analysis can rot silently: a binding the resolver misses, or a
``put_many`` quietly split into sequential ``put``s, changes nothing in
the lint until the wrong crash tears an invariant.  This module closes
that loop, in the mould of the lock witness (``utils/dflock.py``) and
the compile witness (``utils/dftrace.py``):

in witness mode (installed by ``tests/conftest.py``) every write method
on the concrete ``KVTable`` implementations (``_MemTable`` /
``_SQLiteTable``) records, for writes issued **from project code**, the
triple ``(namespace, caller site, method, row count)`` keyed by the
caller's ``(relpath, lineno)`` — exactly the identity the static
persistence inventory indexes.

``tests/test_zz_crashwitness.py`` then asserts that every observed
write site maps into :meth:`StateAnalysis.persistence_site_index` with
the same namespace (a stale inventory is a test failure, not silent
rot), that the declared multi-row sites are only ever observed as
``put_many``, and — driving the existing ``state.put.*`` fault seams —
that a crash injected at each declared multi-row site leaves the
namespace's declared invariant intact after reload.

Design constraints, mirroring dflock/dftrace:

- **foreign writes are untouched** — a table driven directly from test
  code records nothing (only project-code callers are inventoried);
- **recording is re-entrant-safe** — ``_SQLiteTable.put`` delegates to
  ``put_many``; a thread-local depth guard attributes the write to the
  OUTERMOST call, with the method name the caller actually issued;
- **recording failure never breaks persistence** — bookkeeping is
  wrapped defensively; the underlying write always runs.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

Site = Tuple[str, int]          # (repo-relative path, lineno) of the caller


def _raw_lock():
    """The witness's own bookkeeping lock, built from the REAL lock
    factory: diagnostics must not instrument diagnostics.  A proxied
    lock here would put consumer-lock → witness-lock edges into the
    lock witness's graph that no static analysis can explain (the
    table-method wrapping only exists at runtime)."""
    try:
        from .dflock import _REAL_LOCK

        return _REAL_LOCK()
    except ImportError:  # pragma: no cover — dflock always ships
        return threading.Lock()


class WriteStats:
    __slots__ = ("namespace", "method", "writes", "max_rows")

    def __init__(self, namespace: str, method: str) -> None:
        self.namespace = namespace
        self.method = method
        self.writes = 0
        self.max_rows = 0

    def as_dict(self) -> dict:
        return {
            "namespace": self.namespace,
            "method": self.method,
            "writes": self.writes,
            "max_rows": self.max_rows,
        }


class CrashWitness:
    """Global per-site write statistics."""

    def __init__(self, package_dir: str) -> None:
        self.package_dir = os.path.abspath(package_dir)
        self.repo_root = os.path.dirname(self.package_dir)
        self._mu = _raw_lock()
        self._local = threading.local()
        # site -> {(namespace, method): WriteStats}
        self.records: Dict[Site, Dict[Tuple[str, str], WriteStats]] = {}

    # -- caller-site capture ------------------------------------------------

    def _site_of_stack(self) -> Optional[Site]:
        """The project frame that issued the table write: walk up past
        this module and the KVTable implementations themselves."""
        frame = sys._getframe(2)
        own = os.path.abspath(__file__)
        while frame is not None:
            filename = os.path.abspath(frame.f_code.co_filename)
            if filename == own:
                frame = frame.f_back
                continue
            if filename.endswith(os.path.join("manager", "state.py")) and \
                    frame.f_code.co_name in ("put", "put_many", "delete"):
                # The _SQLiteTable.put → put_many internal hop.
                frame = frame.f_back
                continue
            if not filename.startswith(self.package_dir + os.sep):
                return None   # foreign caller (test driving the table raw)
            rel = os.path.relpath(filename, self.repo_root).replace(os.sep, "/")
            return (rel, frame.f_lineno)
        return None

    # -- recording ----------------------------------------------------------

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _enter(self) -> int:
        d = self._depth()
        self._local.depth = d + 1
        return d

    def _exit(self) -> None:
        self._local.depth = max(self._depth() - 1, 0)

    def note_write(self, namespace: str, method: str, rows: int) -> None:
        site = self._site_of_stack()
        if site is None:
            return
        key = (namespace, method)
        with self._mu:
            per_site = self.records.setdefault(site, {})
            st = per_site.get(key)
            if st is None:
                st = per_site[key] = WriteStats(namespace, method)
            st.writes += 1
            if rows > st.max_rows:
                st.max_rows = rows

    def snapshot(self) -> Dict[Site, List[dict]]:
        with self._mu:
            return {
                site: [st.as_dict() for st in sorted(
                    per_site.values(), key=lambda s: (s.namespace, s.method)
                )]
                for site, per_site in self.records.items()
            }

    def reset(self) -> None:
        with self._mu:
            self.records.clear()


_installed: Optional[CrashWitness] = None


def witness() -> Optional[CrashWitness]:
    return _installed


class isolated:
    """``with isolated() as w: ...`` — scoped empty record table, the
    session's observations restored on exit.  The mutation-sensitivity
    test drives a deliberately-torn registry through the live witness;
    its records must not poison the session-wide inventory check."""

    def __enter__(self) -> Optional[CrashWitness]:
        w = _installed
        self._w = w
        if w is not None:
            with w._mu:
                self._saved, w.records = w.records, {}
        return w

    def __exit__(self, *exc) -> None:
        w = self._w
        if w is not None:
            with w._mu:
                w.records = self._saved
        return None


def _default_package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wrap(cls, name: str, w: CrashWitness) -> None:
    orig = cls.__dict__[name]

    if name == "put_many":
        def wrapped(self, items):                      # noqa: ANN001
            depth = w._enter()
            try:
                out = orig(self, items)
            finally:
                w._exit()
            # Committed writes only: an injected pre-transaction fault
            # must not surface as an observed write.
            if depth == 0:
                try:
                    w.note_write(getattr(self, "_ns", "?"), name, len(items))
                except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the write itself already committed
                    pass
            return out
    else:
        def wrapped(self, key, *args):                 # noqa: ANN001
            depth = w._enter()
            try:
                out = orig(self, key, *args)
            finally:
                w._exit()
            if depth == 0:
                try:
                    w.note_write(getattr(self, "_ns", "?"), name, 1)
                except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the write itself already committed
                    pass
            return out

    wrapped.__name__ = name
    wrapped.__qualname__ = f"{cls.__name__}.{name}"
    wrapped.__wrapped_by_dfcrash__ = orig
    setattr(cls, name, wrapped)


def install(package_dir: Optional[str] = None) -> CrashWitness:
    """Wrap the concrete KVTable write methods with recording shims.
    Idempotent; returns the active witness.  Importing the state module
    here is the point — conftest installs dflock/dftrace first, so the
    import itself is fully witnessed."""
    global _installed
    if _installed is not None:
        return _installed
    from ..manager import state

    w = CrashWitness(package_dir or _default_package_dir())
    for cls in (state._MemTable, state._SQLiteTable):
        for name in ("put", "put_many", "delete"):
            if not hasattr(cls.__dict__.get(name), "__wrapped_by_dfcrash__"):
                _wrap(cls, name, w)
    _installed = w
    return w


def uninstall() -> None:
    """Restore the stock write methods."""
    global _installed
    from ..manager import state

    for cls in (state._MemTable, state._SQLiteTable):
        for name in ("put", "put_many", "delete"):
            fn = cls.__dict__.get(name)
            orig = getattr(fn, "__wrapped_by_dfcrash__", None)
            if orig is not None:
                setattr(cls, name, orig)
    _installed = None
