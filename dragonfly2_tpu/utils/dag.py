"""Generic concurrent DAG with cycle detection (reference: pkg/graph/dag/dag.go).

Backs the scheduler's per-task peer graph (scheduler/resource/task.go:155):
vertices are peers, an edge parent→child means the child downloads pieces
from the parent.  Adding an edge that would close a cycle is rejected
(dag.go:277 CanAddEdge / :374-388 DFS), which is what keeps the swarm an
acyclic piece-flow graph.

Thread-safe via a single RLock — the scheduler mutates the graph from many
peer streams concurrently.
"""

from __future__ import annotations

import threading
from typing import Dict, Generic, Iterator, Set, TypeVar

V = TypeVar("V")


class DAGError(Exception):
    pass


class VertexNotFound(DAGError):
    pass


class VertexExists(DAGError):
    pass


class CycleError(DAGError):
    pass


class Vertex(Generic[V]):
    __slots__ = ("id", "value", "parents", "children")

    def __init__(self, vid: str, value: V):
        self.id = vid
        self.value: V = value
        self.parents: Set["Vertex[V]"] = set()
        self.children: Set["Vertex[V]"] = set()

    def in_degree(self) -> int:
        return len(self.parents)

    def out_degree(self) -> int:
        return len(self.children)


class DAG(Generic[V]):
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._vertices: Dict[str, Vertex[V]] = {}

    def __len__(self) -> int:
        with self._mu:
            return len(self._vertices)

    def __contains__(self, vid: str) -> bool:
        with self._mu:
            return vid in self._vertices

    def add_vertex(self, vid: str, value: V) -> Vertex[V]:
        with self._mu:
            if vid in self._vertices:
                raise VertexExists(vid)
            v = Vertex(vid, value)
            self._vertices[vid] = v
            return v

    def get_vertex(self, vid: str) -> Vertex[V]:
        with self._mu:
            try:
                return self._vertices[vid]
            except KeyError:
                raise VertexNotFound(vid) from None

    def delete_vertex(self, vid: str) -> None:
        with self._mu:
            v = self._vertices.pop(vid, None)
            if v is None:
                return
            for p in v.parents:
                p.children.discard(v)
            for c in v.children:
                c.parents.discard(v)
            v.parents.clear()
            v.children.clear()

    def vertex_ids(self) -> list[str]:
        with self._mu:
            return list(self._vertices)

    def vertices(self) -> list[Vertex[V]]:
        with self._mu:
            return list(self._vertices.values())

    def _reachable(self, start: Vertex[V], target: Vertex[V]) -> bool:
        # Iterative DFS down the children links.
        stack = [start]
        seen: Set[str] = set()
        while stack:
            cur = stack.pop()
            if cur is target:
                return True
            if cur.id in seen:
                continue
            seen.add(cur.id)
            stack.extend(cur.children)
        return False

    def can_add_edge(self, from_id: str, to_id: str) -> bool:
        with self._mu:
            if from_id == to_id:
                return False
            f = self._vertices.get(from_id)
            t = self._vertices.get(to_id)
            if f is None or t is None:
                return False
            if t in f.children:
                return False
            return not self._reachable(t, f)

    def add_edge(self, from_id: str, to_id: str) -> None:
        with self._mu:
            if from_id == to_id:
                raise CycleError(f"self edge {from_id}")
            f = self.get_vertex(from_id)
            t = self.get_vertex(to_id)
            if t in f.children:
                return
            if self._reachable(t, f):
                raise CycleError(f"{from_id}->{to_id} would close a cycle")
            f.children.add(t)
            t.parents.add(f)

    def delete_edge(self, from_id: str, to_id: str) -> None:
        with self._mu:
            f = self.get_vertex(from_id)
            t = self.get_vertex(to_id)
            f.children.discard(t)
            t.parents.discard(f)

    def delete_vertex_in_edges(self, vid: str) -> None:
        """Detach vertex from all its parents (reference: DeleteVertexInEdges)."""
        with self._mu:
            v = self.get_vertex(vid)
            for p in list(v.parents):
                p.children.discard(v)
            v.parents.clear()

    def delete_vertex_out_edges(self, vid: str) -> None:
        with self._mu:
            v = self.get_vertex(vid)
            for c in list(v.children):
                c.parents.discard(v)
            v.children.clear()

    def source_vertices(self) -> list[Vertex[V]]:
        """Vertices with no parents (swarm roots: seed peers / back-to-source)."""
        with self._mu:
            return [v for v in self._vertices.values() if not v.parents]

    def sink_vertices(self) -> list[Vertex[V]]:
        with self._mu:
            return [v for v in self._vertices.values() if not v.children]

    def topo_order(self) -> Iterator[Vertex[V]]:
        """Kahn's algorithm; raises CycleError if the graph is not acyclic."""
        with self._mu:
            in_deg = {vid: v.in_degree() for vid, v in self._vertices.items()}
            ready = [v for v in self._vertices.values() if in_deg[v.id] == 0]
            order: list[Vertex[V]] = []
            while ready:
                v = ready.pop()
                order.append(v)
                for c in v.children:
                    in_deg[c.id] -= 1
                    if in_deg[c.id] == 0:
                        ready.append(c)
            if len(order) != len(self._vertices):
                raise CycleError("graph contains a cycle")
        return iter(order)
