"""Content digests (reference: pkg/digest/digest.go).

Digest strings are ``<algorithm>:<hex>`` (e.g. ``sha256:ab12...``); helpers
hash strings, bytes, and file-like readers.
"""

from __future__ import annotations

import hashlib
from typing import BinaryIO, Iterable

ALGORITHM_SHA256 = "sha256"
ALGORITHM_SHA512 = "sha512"
ALGORITHM_MD5 = "md5"

_ALGOS = {
    ALGORITHM_SHA256: hashlib.sha256,
    ALGORITHM_SHA512: hashlib.sha512,
    ALGORITHM_MD5: hashlib.md5,
}


def sha256_from_strings(*parts: str) -> str:
    """Hex sha256 over newline-joined parts (reference: pkg/digest SHA256FromStrings)."""
    h = hashlib.sha256()
    for i, p in enumerate(parts):
        if i:
            h.update(b"\n")
        h.update(p.encode("utf-8"))
    return h.hexdigest()


def sha256_from_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def new(algorithm: str, encoded: str) -> str:
    if algorithm not in _ALGOS:
        raise ValueError(f"unknown digest algorithm {algorithm!r}")
    return f"{algorithm}:{encoded}"


def parse(value: str) -> tuple[str, str]:
    """Split ``algo:hex`` and validate the algorithm and hex length."""
    algorithm, sep, encoded = value.partition(":")
    if not sep or algorithm not in _ALGOS:
        raise ValueError(f"invalid digest {value!r}")
    want = _ALGOS[algorithm]().digest_size * 2
    if len(encoded) != want:
        raise ValueError(f"invalid {algorithm} digest length {len(encoded)} != {want}")
    return algorithm, encoded


def hash_reader(algorithm: str, reader: BinaryIO, chunk_size: int = 1 << 20) -> str:
    h = _ALGOS[algorithm]()
    while True:
        chunk = reader.read(chunk_size)
        if not chunk:
            break
        h.update(chunk)
    return new(algorithm, h.hexdigest())


def hash_chunks(algorithm: str, chunks: Iterable[bytes]) -> str:
    h = _ALGOS[algorithm]()
    for chunk in chunks:
        h.update(chunk)
    return new(algorithm, h.hexdigest())
