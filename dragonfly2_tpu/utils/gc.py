"""Interval-task garbage-collection runner (reference: pkg/gc/gc.go:28-137).

Services register named tasks with an interval and a timeout; a single
background scheduler ticks each task on its own cadence.  Used by the
scheduler to reap expired hosts/peers/tasks and by the daemon's storage
quota reclaimer.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict

logger = logging.getLogger(__name__)


@dataclass
class Task:
    id: str
    interval: float
    timeout: float
    runner: Callable[[], None]

    def __post_init__(self) -> None:
        if self.timeout > self.interval:
            raise ValueError(f"gc task {self.id}: timeout exceeds interval")
        if self.interval <= 0:
            raise ValueError(f"gc task {self.id}: non-positive interval")


class GC:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tasks: Dict[str, Task] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._started = False

    def add(self, task: Task) -> None:
        with self._mu:
            respawn = self._started and task.id not in self._threads
            self._tasks[task.id] = task
            # Re-adding an id only swaps the task object; the existing loop
            # thread reads the task from the registry each tick, so cadence
            # changes take effect without spawning a duplicate runner.
            if respawn:
                self._spawn(task.id)

    def run(self, task_id: str) -> None:
        """Run one task immediately (reference: gc.Run)."""
        with self._mu:
            task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(task_id)
        self._run_once(task)

    def run_all(self) -> None:
        with self._mu:
            tasks = list(self._tasks.values())
        for t in tasks:
            self._run_once(t)

    def _run_once(self, task: Task) -> None:
        done = threading.Event()

        def call() -> None:
            try:
                task.runner()
            except Exception:  # noqa: BLE001 — GC must never kill the service
                logger.exception("gc task %s failed", task.id)
            finally:
                done.set()

        t = threading.Thread(target=call, name=f"gc-run-{task.id}", daemon=True)
        t.start()
        if not done.wait(task.timeout):
            logger.warning("gc task %s timed out after %.1fs", task.id, task.timeout)

    def _spawn(self, task_id: str) -> None:
        def loop() -> None:
            while True:
                with self._mu:
                    task = self._tasks.get(task_id)
                if task is None:
                    return
                if self._stop.wait(task.interval):
                    return
                with self._mu:
                    task = self._tasks.get(task_id)
                if task is not None:
                    self._run_once(task)

        th = threading.Thread(target=loop, name=f"gc-{task_id}", daemon=True)
        th.start()
        self._threads[task_id] = th

    def start(self) -> None:
        with self._mu:
            if self._started:
                return
            self._started = True
            for task_id in self._tasks:
                self._spawn(task_id)

    def stop(self) -> None:
        self._stop.set()
