"""Dynamic determinism witness: runtime validation of the DF018 taint report.

``tools/dflint/detrules.py`` statically taints every function reachable
from a declared replay root (records/determinism_contracts.py) and
fails ambient nondeterminism inside the closure.  Static analysis can
rot silently: a call edge the resolver misses puts a ``time.time()``
back on a replay path with no finding.  This module closes that loop,
in the mould of the lock witness (``utils/dflock.py``), the compile
witness (``utils/dftrace.py``) and the crash witness
(``utils/dfcrash.py``):

in witness mode (installed by ``tests/conftest.py``, off-switch
``DF_DET_WITNESS=0``) the patchable ambient sources — ``time.time`` /
``monotonic`` / ``perf_counter`` (+ ``_ns`` twins), ``os.urandom``,
``uuid.uuid1``/``uuid4``, the ambient ``random`` module draws — are
wrapped with call-site recorders, and every declared replay root is
wrapped to ARM the recorder (thread-local) while it is on the stack.
Each ambient read observed while armed records ``(source, relpath,
lineno, root)`` — exactly the identity the static ambient-site index
uses.

``tests/test_zz_detwitness.py`` then asserts, via
:func:`tools.dflint.detrules.det_witness_gaps`, that every observation
maps to a statically-known ambient site or a declared observability
sink span (a resolver blind spot is a tier-1 failure, and a root the
contracts no longer declare fails the other direction), and re-runs
every root twice over identical journal bytes in subprocesses with
different PYTHONHASHSEED values — decision output must be
byte-identical.

Design constraints, mirroring the sibling witnesses:

- **disarmed reads are near-free** — one thread-local attribute probe,
  then straight into the original function; other threads (journal
  cadence, exporter flush) stay disarmed while a root runs;
- **recording failure never breaks the plane** — bookkeeping is wrapped
  defensively; the underlying clock/RNG call always runs;
- **``datetime.datetime.now`` is NOT patchable** (attribute of a C
  type) — the static rule alone covers it, documented here so nobody
  mistakes its absence for coverage.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


def _raw_lock():
    """Bookkeeping lock from the REAL factory: diagnostics must not
    instrument diagnostics (the dfcrash/dftrace precedent)."""
    try:
        from .dflock import _REAL_LOCK

        return _REAL_LOCK()
    except ImportError:  # pragma: no cover — dflock always ships
        return threading.Lock()


# Ambient sources patched at module-attribute level.  Project code never
# does ``from time import time`` (dflint idiom), so attribute patches
# are visible everywhere.
_PATCHED_SOURCES: Tuple[Tuple[str, str, str], ...] = (
    # (module, attr, canonical source name — matches detrules' tables)
    ("time", "time", "time.time"),
    ("time", "time_ns", "time.time_ns"),
    ("time", "monotonic", "time.monotonic"),
    ("time", "monotonic_ns", "time.monotonic_ns"),
    ("time", "perf_counter", "time.perf_counter"),
    ("time", "perf_counter_ns", "time.perf_counter_ns"),
    ("os", "urandom", "os.urandom"),
    ("uuid", "uuid1", "uuid.uuid1"),
    ("uuid", "uuid4", "uuid.uuid4"),
    ("random", "random", "random.random"),
    ("random", "randint", "random.randint"),
    ("random", "randrange", "random.randrange"),
    ("random", "uniform", "random.uniform"),
    ("random", "choice", "random.choice"),
    ("random", "shuffle", "random.shuffle"),
    ("random", "getrandbits", "random.getrandbits"),
)


class DetWitness:
    """Armed-while-a-replay-root-runs ambient-read recorder."""

    def __init__(self, package_dir: str) -> None:
        self.package_dir = os.path.abspath(package_dir)
        self.repo_root = os.path.dirname(self.package_dir)
        self._mu = _raw_lock()
        self._local = threading.local()
        # (relpath, lineno, source, root) -> observation count
        self.records: Dict[Tuple[str, int, str, str], int] = {}

    # -- arming (thread-local root stack) -----------------------------------

    def _roots(self) -> List[str]:
        roots = getattr(self._local, "roots", None)
        if roots is None:
            roots = self._local.roots = []
        return roots

    def push_root(self, name: str) -> None:
        self._roots().append(name)

    def pop_root(self) -> None:
        roots = self._roots()
        if roots:
            roots.pop()

    def armed_root(self) -> Optional[str]:
        """The OUTERMOST armed root on this thread (build_report →
        replay_fleet → evaluate attributes to build_report), or None
        when disarmed."""
        roots = getattr(self._local, "roots", None)
        return roots[0] if roots else None

    def armed_depth(self) -> int:
        roots = getattr(self._local, "roots", None)
        return len(roots) if roots else 0

    # -- recording ----------------------------------------------------------

    def _site_of_stack(self) -> Optional[Tuple[str, int]]:
        """The nearest repo frame below the patched source: walk up
        past this module (and stdlib internals like ``uuid`` calling
        ``os.urandom``) to the project line that triggered the read."""
        frame = sys._getframe(2)
        own = os.path.abspath(__file__)
        while frame is not None:
            filename = os.path.abspath(frame.f_code.co_filename)
            if filename != own and filename.startswith(
                self.repo_root + os.sep
            ):
                rel = os.path.relpath(filename, self.repo_root)
                return (rel.replace(os.sep, "/"), frame.f_lineno)
            frame = frame.f_back
        return None

    def note_read(self, source: str) -> None:
        root = self.armed_root()
        if root is None:
            return
        site = self._site_of_stack()
        if site is None:
            return
        key = (site[0], site[1], source, root)
        with self._mu:
            self.records[key] = self.records.get(key, 0) + 1

    def snapshot(self) -> List[dict]:
        """Observations in det_witness_gaps' input shape."""
        with self._mu:
            return [
                {
                    "relpath": relpath,
                    "lineno": lineno,
                    "source": source,
                    "root": root,
                    "count": count,
                }
                for (relpath, lineno, source, root), count in sorted(
                    self.records.items()
                )
            ]

    def reset(self) -> None:
        with self._mu:
            self.records.clear()


_installed: Optional[DetWitness] = None


def witness() -> Optional[DetWitness]:
    return _installed


class isolated:
    """``with isolated() as w: ...`` — scoped empty record table, the
    session's observations restored on exit (the mutation-sensitivity
    drill must not poison the session-wide cross-validation)."""

    def __enter__(self) -> Optional[DetWitness]:
        w = _installed
        self._w = w
        if w is not None:
            with w._mu:
                self._saved, w.records = w.records, {}
        return w

    def __exit__(self, *exc) -> None:
        w = self._w
        if w is not None:
            with w._mu:
                w.records = self._saved
        return None


class armed:
    """``with armed("slo.evaluate"): ...`` — arm the recorder on this
    thread as if the named replay root were on the stack.  Test-only:
    the mutation drill compiles a deliberately-broken copy of a root's
    module and drives it under the root's name."""

    def __init__(self, root: str) -> None:
        self.root = root

    def __enter__(self) -> Optional[DetWitness]:
        w = _installed
        self._w = w
        if w is not None:
            w.push_root(self.root)
        return w

    def __exit__(self, *exc) -> None:
        if self._w is not None:
            self._w.pop_root()
        return None


def _default_package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- source + root wrapping --------------------------------------------------


def _wrap_source(orig: Callable, source: str, w: DetWitness) -> Callable:
    def wrapped(*args: Any, **kwargs: Any):
        # Disarmed fast path first: one thread-local probe, no locks.
        if w.armed_depth():
            try:
                w.note_read(source)
            except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the read itself must run
                pass
        return orig(*args, **kwargs)

    wrapped.__name__ = getattr(orig, "__name__", source.rsplit(".", 1)[-1])
    wrapped.__qualname__ = wrapped.__name__
    wrapped.__wrapped_by_dfdet__ = orig
    return wrapped


def _wrap_root(name: str, fn: Callable, w: DetWitness) -> Callable:
    def wrapped(*args: Any, **kwargs: Any):
        w.push_root(name)
        try:
            return fn(*args, **kwargs)
        finally:
            w.pop_root()

    wrapped.__name__ = getattr(fn, "__name__", name)
    wrapped.__qualname__ = getattr(fn, "__qualname__", name)
    wrapped.__doc__ = getattr(fn, "__doc__", None)
    wrapped.__wrapped_by_dfdet__ = fn
    return wrapped


def _module_name_of(relpath: str) -> str:
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _wrap_declared_roots(w: DetWitness) -> List[str]:
    """Wrap every declared replay root in place (module import is the
    resolution step — tools.* and dragonfly2_tpu.* are both packages).
    Returns the root names actually wrapped; an unresolvable root is
    skipped here because the static side already fails it by name."""
    import importlib

    from ..records.determinism_contracts import DETERMINISM_CONTRACTS

    wrapped_names: List[str] = []
    for name, spec in sorted(DETERMINISM_CONTRACTS["replay_roots"].items()):
        try:
            mod = importlib.import_module(_module_name_of(spec["file"]))
        except ImportError:
            continue
        qual = spec["qual"].split(".")
        if len(qual) == 1:
            holder: Any = mod
            attr = qual[0]
        else:
            holder = getattr(mod, qual[0], None)
            attr = qual[1]
            if holder is None:
                continue
        raw = holder.__dict__.get(attr) if isinstance(holder, type) else getattr(holder, attr, None)
        if raw is None:
            continue
        probe = raw.__func__ if isinstance(raw, (classmethod, staticmethod)) else raw
        if getattr(probe, "__wrapped_by_dfdet__", None) is not None:
            wrapped_names.append(name)
            continue
        # classmethod/staticmethod descriptors wrap their __func__ and
        # re-wrap in the same descriptor (SLOAutopilot.replay).
        if isinstance(raw, classmethod):
            shim: Any = classmethod(_wrap_root(name, raw.__func__, w))
            shim.__func__.__wrapped_by_dfdet__ = raw
        elif isinstance(raw, staticmethod):
            shim = staticmethod(_wrap_root(name, raw.__func__, w))
            shim.__func__.__wrapped_by_dfdet__ = raw
        else:
            shim = _wrap_root(name, raw, w)
        setattr(holder, attr, shim)
        wrapped_names.append(name)
    return wrapped_names


def install(package_dir: Optional[str] = None) -> DetWitness:
    """Patch the ambient sources and wrap the declared replay roots.
    Idempotent; returns the active witness."""
    global _installed
    if _installed is not None:
        return _installed
    import importlib

    w = DetWitness(package_dir or _default_package_dir())
    for mod_name, attr, source in _PATCHED_SOURCES:
        mod = importlib.import_module(mod_name)
        orig = getattr(mod, attr, None)
        if orig is None or getattr(orig, "__wrapped_by_dfdet__", None) is not None:
            continue
        setattr(mod, attr, _wrap_source(orig, source, w))
    w.wrapped_roots = _wrap_declared_roots(w)
    _installed = w
    return w


def uninstall() -> None:
    """Restore the stock sources and root functions."""
    global _installed
    import importlib

    for mod_name, attr, _source in _PATCHED_SOURCES:
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr, None)
        orig = getattr(fn, "__wrapped_by_dfdet__", None)
        if orig is not None:
            setattr(mod, attr, orig)
    if _installed is not None:
        from ..records.determinism_contracts import DETERMINISM_CONTRACTS

        for _name, spec in DETERMINISM_CONTRACTS["replay_roots"].items():
            try:
                mod = importlib.import_module(_module_name_of(spec["file"]))
            except ImportError:
                continue
            qual = spec["qual"].split(".")
            holder: Any = mod if len(qual) == 1 else getattr(mod, qual[0], None)
            if holder is None:
                continue
            attr = qual[-1]
            raw = holder.__dict__.get(attr) if isinstance(holder, type) else getattr(holder, attr, None)
            if isinstance(raw, (classmethod, staticmethod)):
                orig = getattr(raw.__func__, "__wrapped_by_dfdet__", None)
            else:
                orig = getattr(raw, "__wrapped_by_dfdet__", None)
            if orig is not None:
                setattr(holder, attr, orig)
    _installed = None
