"""Uniform diagnostics endpoint: ``/metrics`` + ``/debug/spans`` on
every plane (DESIGN.md §21).

The manager serves these routes on its REST surface; the scheduler and
daemon — whose primary listeners speak the RPC/piece wire — get the same
surface from this loopback sidecar (reference: every binary runs a
metrics listener, scheduler/metrics/metrics.go:44-180 + the
grpc_prometheus handler):

  GET /metrics          — Prometheus text exposition (default registry)
  GET /debug/spans      — recent-span ring as ONE OTLP/JSON
                          ExportTraceServiceRequest (the same shape the
                          durable trace log frames carry, so operator
                          tooling parses both identically)
  GET /debug/exemplars  — histogram exemplars: last trace id per bucket,
                          joining a slow-bucket latency to its trace in
                          the flight recorder
  GET /debug/slo        — the SLO engine's last evaluation (burn rates,
                          breach verdicts per declared SLO; DESIGN.md
                          §23) — the machine-readable overload signal
                          the SLO autopilot consumes

Gated behind config (``metrics.enable``); binds loopback by default —
the exposition includes label values operators may consider internal.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from typing import Tuple

from ..rpc._server import ThreadedHTTPService


class DiagnosticsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _body(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from .metrics import default_registry

                if self.path == "/metrics":
                    self._body(
                        200,
                        default_registry.expose_text().encode(),
                        "text/plain; version=0.0.4",
                    )
                elif self.path == "/debug/spans":
                    from .tracing import recent_spans_otlp

                    self._body(
                        200,
                        json.dumps(recent_spans_otlp()).encode(),
                        "application/json",
                    )
                elif self.path == "/debug/exemplars":
                    self._body(
                        200,
                        json.dumps(default_registry.exemplars()).encode(),
                        "application/json",
                    )
                elif self.path == "/debug/slo":
                    from .slo import debug_state

                    self._body(
                        200,
                        json.dumps(debug_state()).encode(),
                        "application/json",
                    )
                else:
                    self._body(404, b"not found\n", "text/plain")

        self._svc = ThreadedHTTPService(Handler, host, port, "diagnostics")
        self.address: Tuple[str, int] = self._svc.address

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
