"""Dynamic compile witness: runtime validation of jit trace discipline.

``tools/dflint/tracerules.py`` (DF010) statically indexes every
``jax.jit``/``pjit`` construction site and ``tools/dflint/
compile_budget.toml`` bounds how many XLA compiles one creation at each
site may trigger.  Static analysis can rot silently — a construction the
resolver misses, or a cached callable that quietly starts retracing per
call (shape churn, a lost ``static_argnums``), changes nothing in the
lint.  This module closes that loop, in the mould of the lock witness
(``utils/dflock.py``):

in witness mode (installed by ``tests/conftest.py`` before any project
import) ``jax.jit`` is replaced by a factory that, for constructions
issued **from project code**, wraps the returned jitted callable in a
counting proxy.  Per creation site ``(relpath, lineno)`` — exactly the
identity the static index records — it tracks creations, calls, and the
maximum number of XLA compiles any single creation triggered (read from
the jitted function's own ``_cache_size()``; a signature-set fallback
covers jax builds without it).

``tests/test_zz_compilewitness.py`` then asserts that every observed
creation site maps into the static index (an unknown site is a per-call
construction or a resolver blind spot — fix tracerules, never the test)
and that every per-creation compile count fits the checked-in budget (a
steady-state path that recompiles per call fails BY FUNCTION NAME).

Design constraints, mirroring dflock:

- **foreign creations are untouched** — jit calls issued from jax, flax,
  optax or test code get the raw jitted function back, zero overhead;
- **the proxy is transparent** — ``lower``/``clear_cache``/attributes
  delegate to the real jitted callable; only ``__call__`` adds a counter
  read, and counting failures never break the call;
- **recording is thread-safe** — the training threads that drive jitted
  steps share one lock-guarded stats table.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable, Dict, Optional, Tuple

Site = Tuple[str, int]          # (repo-relative path, lineno) of the creation


class SiteStats:
    __slots__ = ("creations", "calls", "max_compiles")

    def __init__(self) -> None:
        self.creations = 0
        self.calls = 0
        self.max_compiles = 0

    def as_dict(self) -> Dict[str, int]:
        return {
            "creations": self.creations,
            "calls": self.calls,
            "max_compiles": self.max_compiles,
        }


class CompileWitness:
    """Global per-creation-site compile statistics."""

    def __init__(self, package_dir: str) -> None:
        self.package_dir = os.path.abspath(package_dir)
        self.repo_root = os.path.dirname(self.package_dir)
        self._mu = threading.Lock()
        self.stats: Dict[Site, SiteStats] = {}

    def site_of_frame(self, frame) -> Optional[Site]:
        filename = os.path.abspath(frame.f_code.co_filename)
        if not filename.startswith(self.package_dir + os.sep):
            return None
        rel = os.path.relpath(filename, self.repo_root).replace(os.sep, "/")
        return (rel, frame.f_lineno)

    def note_creation(self, site: Site) -> SiteStats:
        with self._mu:
            st = self.stats.get(site)
            if st is None:
                st = self.stats[site] = SiteStats()
            st.creations += 1
            return st

    def note_call(self, site: Site, compiles: int) -> None:
        with self._mu:
            st = self.stats.get(site)
            if st is None:  # pragma: no cover — creation always precedes
                st = self.stats[site] = SiteStats()
            st.calls += 1
            if compiles > st.max_compiles:
                st.max_compiles = compiles

    def snapshot(self) -> Dict[Site, Dict[str, int]]:
        with self._mu:
            return {site: st.as_dict() for site, st in self.stats.items()}

    def total_compiles(self) -> int:
        """Sum of max-compiles over sites — a cheap monotone proxy for
        'any steady-state recompile happened since the last snapshot'
        (tools/bench_sched.py brackets measured rounds with it)."""
        with self._mu:
            return sum(st.max_compiles for st in self.stats.values())

    def reset(self) -> None:
        with self._mu:
            self.stats.clear()


class _JitProxy:
    """Counts compiles around a real jitted callable; delegates the rest."""

    __slots__ = ("_jitted", "_site", "_w", "_sigs", "_compiles")

    def __init__(self, jitted, site: Site, witness: CompileWitness) -> None:
        object.__setattr__(self, "_jitted", jitted)
        object.__setattr__(self, "_site", site)
        object.__setattr__(self, "_w", witness)
        object.__setattr__(self, "_sigs", set())
        object.__setattr__(self, "_compiles", 0)

    def _count_compiles(self, args, kwargs) -> int:
        jitted = self._jitted
        cache_size = getattr(jitted, "_cache_size", None)
        if cache_size is not None:
            try:
                return int(cache_size())
            except Exception:  # dflint: disable=DF001 — diagnostics only; fall through to the signature fallback
                pass
        # Fallback: count distinct abstract signatures ourselves.
        try:
            import jax

            leaves = jax.tree_util.tree_leaves((args, kwargs))
            sig = tuple(
                (getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
                for x in leaves
            )
            self._sigs.add(sig)
            return len(self._sigs)
        except Exception:  # dflint: disable=DF001 — diagnostics only; never perturb the jitted call
            return self._compiles

    def __call__(self, *args, **kwargs):
        out = self._jitted(*args, **kwargs)
        try:
            compiles = self._count_compiles(args, kwargs)
            object.__setattr__(self, "_compiles", compiles)
            self._w.note_call(self._site, compiles)
        except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the jitted result is already computed
            pass
        return out

    def __getattr__(self, name):
        return getattr(self._jitted, name)

    def __repr__(self):  # pragma: no cover — debugging aid
        return f"<dftrace proxy {self._site[0]}:{self._site[1]} of {self._jitted!r}>"


_installed: Optional[CompileWitness] = None
_real_jit: Optional[Callable[..., Any]] = None


def witness() -> Optional[CompileWitness]:
    return _installed


def _default_package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def install(package_dir: Optional[str] = None) -> CompileWitness:
    """Patch ``jax.jit`` with the site-aware counting factory.
    Idempotent; returns the active witness.  Importing jax here is the
    point — the caller (conftest) controls platform env beforehand."""
    global _installed, _real_jit
    if _installed is not None:
        return _installed
    import jax

    w = CompileWitness(package_dir or _default_package_dir())
    real_jit = jax.jit
    _real_jit = real_jit

    def counting_jit(fun=None, **kwargs):
        if fun is None:
            # jax.jit(static_argnames=...) factory form: defer until the
            # function arrives, then re-enter with the original frame
            # already gone — attribute the creation to the deferred call.
            def deferred(f):
                return counting_jit(f, **kwargs)

            return deferred
        jitted = real_jit(fun, **kwargs)
        site = w.site_of_frame(sys._getframe(1))
        if site is None:
            return jitted
        w.note_creation(site)
        return _JitProxy(jitted, site, w)

    jax.jit = counting_jit
    _installed = w
    return w


def uninstall() -> None:
    """Restore the stock ``jax.jit`` (existing proxies keep working)."""
    global _installed
    if _real_jit is not None:
        import jax

        jax.jit = _real_jit
    _installed = None
