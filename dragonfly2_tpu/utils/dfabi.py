"""Runtime ABI witness: the compiled library vs the declared contracts.

DF020 (tools/dflint/checkers/df020_abi.py) proves the three TEXTS agree
— registry, native.cpp, ctypes bindings.  Text agreement can still lie
about what the compiler did: a padding surprise, an ABI-breaking flag,
or a stale committed ``.so`` whose symbols predate the sources.  This
module closes that loop in the mould of the sibling witnesses (dflock /
dftrace / dfcrash / dfspan / dfdet):

- ``native.cpp`` carries a ``DF_ABI_EXPORTS`` X-macro table expanded
  into per-symbol ``static_assert``s AND a ``df_abi_manifest()`` export
  that emits canonical JSON — prototype table, compiler-computed
  ``sizeof``/``offsetof`` for every packed record, compiled constant
  values — byte-compatible with Python's ``json.dumps(...,
  sort_keys=True, separators=(",", ":"))``.
- this module renders the SAME canonical JSON from
  ``records/abi_contracts.py`` and diffs the two;
  ``tests/test_zz_abiwitness.py`` requires byte equality and
  round-trips a sentinel FetchDone record through
  ``df_abi_probe_fetchdone()`` (a memcpy of the compiled struct, every
  field distinguishable) plus the stats field order through a real
  serve.

Installed by ``tests/conftest.py`` (section 2f); ``DF_ABI_WITNESS=0``
disables.  Install is bookkeeping-only — the native library is NOT
built or loaded at conftest time (plenty of tier-1 tests never touch
native); the witness test triggers the lazy load itself.  When the
library is unavailable the witness reports exactly that instead of
failing: the skip-clean discipline of the sanitizer gate.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional

_ARMED = False
_ROOT: Optional[str] = None

# The sentinel df_abi_probe_fetchdone() fills: every field carries a
# value distinguishable by position AND width, so a swapped or widened
# field cannot round-trip clean.  status deliberately reuses a registry
# status constant so one real enum value crosses the boundary too.
PROBE_SENTINEL = {
    "number": 0xA1B2C3D4,
    "status": -2,  # kFetchStatusProto
    "length": 0x00C0FFEE,
    "slot": -7,
    "cost_ns": 0x0102030405060708,
}


def install(root: str) -> None:
    global _ARMED, _ROOT
    _ARMED = True
    _ROOT = root


def armed() -> bool:
    return _ARMED


def expected_manifest() -> dict:
    """The manifest the compiled library must emit, from the registry."""
    from ..records import abi_contracts

    return abi_contracts.expected_manifest()


def expected_manifest_bytes() -> bytes:
    from ..records import abi_contracts

    return abi_contracts.manifest_json().encode()


def live_manifest_bytes() -> Optional[bytes]:
    """``df_abi_manifest()`` from the loaded library; None when the
    native library is unavailable or predates the witness export."""
    from .. import native

    lib = native.load()
    if lib is None:
        return None
    raw = lib.df_abi_manifest()
    return None if raw is None else bytes(raw)


def diff_manifests(expected: dict, live: dict) -> List[str]:
    """Human-readable gaps between two manifest objects, keyed the way
    DF020 keys its findings (symbol/field/constant names) so a witness
    failure and a static failure for the same drift read the same."""
    gaps: List[str] = []
    for section in ("constants", "exports", "records"):
        want = expected.get(section, {})
        got = live.get(section, {})
        for name in sorted(set(want) | set(got)):
            if name not in got:
                gaps.append(f"{section}: {name} missing from the compiled "
                            f"manifest (stale .so?)")
            elif name not in want:
                gaps.append(f"{section}: {name} in the compiled manifest but "
                            f"not declared in records/abi_contracts.py")
            elif want[name] != got[name]:
                gaps.append(f"{section}: {name} declared {want[name]!r} but "
                            f"compiled {got[name]!r}")
    if expected.get("version") != live.get("version"):
        gaps.append(f"version: declared {expected.get('version')!r} vs "
                    f"compiled {live.get('version')!r}")
    return gaps


def compare(
    expected_bytes: Optional[bytes] = None,
    live_bytes: Optional[bytes] = None,
) -> List[str]:
    """Gap descriptions between registry and compiled manifest.  Empty
    list == witness green.  Both sides overridable so the gap fixtures
    (doctored manifest, stale registry) exercise the real comparator."""
    if expected_bytes is None:
        expected_bytes = expected_manifest_bytes()
    if live_bytes is None:
        live_bytes = live_manifest_bytes()
    if live_bytes is None:
        return ["native library unavailable (or df_abi_manifest missing) — "
                "witness cannot run"]
    try:
        live = json.loads(live_bytes.decode())
    except (ValueError, UnicodeDecodeError) as exc:
        return [f"compiled manifest is not valid JSON: {exc}"]
    gaps = diff_manifests(json.loads(expected_bytes.decode()), live)
    if not gaps and expected_bytes != live_bytes:
        # same object, different bytes: the C++ emitter broke canonical
        # form (key order / separators) — the byte contract is the spec
        gaps.append("manifest objects match but bytes differ — the C++ "
                    "emitter no longer produces canonical JSON")
    return gaps


def probe_fetchdone() -> Optional[Dict[str, int]]:
    """Round-trip the sentinel FetchDone: fields unpacked with the
    registry's struct format.  None when the library is unavailable."""
    import ctypes

    from .. import native
    from ..records import abi_contracts

    lib = native.load()
    if lib is None:
        return None
    size = abi_contracts.record_size("FetchDone")
    buf = (ctypes.c_uint8 * (size * 2))()  # slack: a size drift still lands
    n = lib.df_abi_probe_fetchdone(buf, len(buf))
    if n < 0:
        return None
    values = struct.unpack_from(
        abi_contracts.record_format("FetchDone"), bytes(buf), 0
    )
    fields = [f for f, _t in
              abi_contracts.ABI_CONTRACTS["records"]["FetchDone"]["fields"]]
    out = dict(zip(fields, values))
    out["__returned_size__"] = int(n)
    return out
