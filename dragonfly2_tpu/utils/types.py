"""Shared enum types (reference: pkg/types/*.go and api common protos)."""

from __future__ import annotations

import enum


class HostType(enum.IntEnum):
    """Peer host roles (reference: pkg/types — Normal < Super < Strong < Weak seeds).

    The evaluator scores seed types above normal peers
    (scheduler/scheduling/evaluator/evaluator_base.go host-type feature).
    """

    NORMAL = 0
    SUPER_SEED = 1
    STRONG_SEED = 2
    WEAK_SEED = 3

    @property
    def is_seed(self) -> bool:
        return self is not HostType.NORMAL

    @property
    def name_str(self) -> str:
        return _HOST_TYPE_NAMES[self]


_HOST_TYPE_NAMES = {
    HostType.NORMAL: "normal",
    HostType.SUPER_SEED: "super",
    HostType.STRONG_SEED: "strong",
    HostType.WEAK_SEED: "weak",
}


class SizeScope(enum.IntEnum):
    """Task content-size buckets that pick the scheduling shortcut
    (reference: scheduler/resource/task.go:444-470).

    EMPTY → zero-byte response inline; TINY (≤128 B) → bytes inline in the
    scheduler response; SMALL (single piece) → single parent, no DAG;
    NORMAL → full piece-level swarm scheduling; UNKNOWN → length not known yet.
    """

    NORMAL = 0
    SMALL = 1
    TINY = 2
    EMPTY = 3
    UNKNOWN = 4


EMPTY_FILE_SIZE = 0
TINY_FILE_SIZE = 128


class Priority(enum.IntEnum):
    """Download priority levels (reference: common v2 Priority proto).

    LEVEL0 is highest; the scheduler maps priority to seed-peer trigger
    behavior (service_v2.go:1370 downloadTaskBySeedPeer).
    """

    LEVEL0 = 0
    LEVEL1 = 1
    LEVEL2 = 2
    LEVEL3 = 3
    LEVEL4 = 4
    LEVEL5 = 5
    LEVEL6 = 6


class TrainingModelType(enum.Enum):
    """Model families the trainer produces (reference: manager/models/model.go gnn|mlp)."""

    GNN = "gnn"
    MLP = "mlp"
