"""RTT probing (reference: pkg/net/ping — the ICMP prober behind the
daemon's probe agent).

ICMP needs raw sockets (CAP_NET_RAW); the deployable default here is a
TCP-connect prober: RTT of a SYN/accept round to the target's announced
port — measurable as an unprivileged process and monotone with network
distance, which is all the EMA/topology pipeline needs.  An ICMP
implementation can register behind the same callable shape.
"""

from __future__ import annotations

import socket
import time
from typing import Optional


def tcp_ping(ip: str, port: int, *, timeout: float = 1.0) -> Optional[int]:
    """RTT in nanoseconds of a TCP connect, or None on timeout/refusal.

    1s default timeout matches the reference's ping timeout (the evaluator
    normalizes RTT against it, evaluator_network_topology.go:53-56).
    """
    t0 = time.monotonic_ns()
    try:
        with socket.create_connection((ip, port), timeout=timeout):
            return time.monotonic_ns() - t0
    except OSError:
        return None


def make_host_pinger(*, timeout: float = 1.0):
    """ProbeAgent-shaped pinger: Host → rtt_ns | None (ping the announced
    download port; it is the port peers actually fetch from)."""

    def ping(host) -> Optional[int]:
        port = host.download_port or host.port
        if not host.ip or not port:
            return None
        return tcp_ping(host.ip, port, timeout=timeout)

    return ping
