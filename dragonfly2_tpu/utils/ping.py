"""RTT probing (reference: pkg/net/ping — the ICMP prober behind the
daemon's probe agent).

``icmp_ping`` is real ICMP echo (pkg/net/ping semantics): it tries the
unprivileged datagram-ICMP socket first (Linux ``ping_group_range``),
then a raw socket (CAP_NET_RAW), building/parsing echo packets directly.

``tcp_ping`` is the deliberate fallback divergence: where ICMP is
unavailable (no capability, containers with ping groups closed), the RTT
of a SYN/accept round against the target's announced download port
stands in.  Note the measured quantity differs from ICMP — a loaded
accept queue inflates "RTT" with server load — which is arguably useful
for parent selection (a busy server IS slower to serve) but is not the
reference's network-distance semantics; deployments wanting pure ICMP
grant the capability and get it automatically.

``make_host_pinger`` composes both behind the ProbeAgent's pluggable
callable: ICMP when the socket is obtainable, TCP otherwise.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional


def _icmp_checksum(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    total = sum(struct.unpack(f"!{len(data) // 2}H", data))
    total = (total >> 16) + (total & 0xFFFF)
    total += total >> 16
    return ~total & 0xFFFF


def _open_icmp_socket() -> Optional[socket.socket]:
    """Unprivileged datagram ICMP first, raw second; None when neither is
    permitted."""
    for sock_type in (socket.SOCK_DGRAM, socket.SOCK_RAW):
        try:
            return socket.socket(socket.AF_INET, sock_type, socket.IPPROTO_ICMP)
        except (PermissionError, OSError):
            continue
    return None


def icmp_available() -> bool:
    s = _open_icmp_socket()
    if s is None:
        return False
    s.close()
    return True


def icmp_ping(ip: str, *, timeout: float = 1.0, seq: int = 0) -> Optional[int]:
    """RTT in nanoseconds of one ICMP echo, or None on timeout/denial.

    Echo request: type 8, code 0, identifier from the pid, 16-byte
    payload carrying the send timestamp.  The reply is matched on the
    payload (datagram-ICMP sockets rewrite the identifier; raw sockets
    deliver the IP header too — both shapes handled).
    """
    s = _open_icmp_socket()
    if s is None:
        return None
    try:
        s.settimeout(timeout)
        ident = os.getpid() & 0xFFFF
        payload = struct.pack("!Qq", time.monotonic_ns(), seq)
        header = struct.pack("!BBHHH", 8, 0, 0, ident, seq & 0xFFFF)
        checksum = _icmp_checksum(header + payload)
        packet = struct.pack("!BBHHH", 8, 0, checksum, ident, seq & 0xFFFF) + payload
        t0 = time.monotonic_ns()
        s.sendto(packet, (ip, 0))
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            s.settimeout(remaining)
            try:
                data, _addr = s.recvfrom(1024)
            except socket.timeout:
                return None
            t1 = time.monotonic_ns()
            # Raw sockets prepend the IP header; its IHL field gives the
            # offset.  Datagram sockets hand the ICMP message directly.
            if len(data) >= 20 and (data[0] >> 4) == 4 and s.type == socket.SOCK_RAW:
                data = data[(data[0] & 0x0F) * 4:]
            if len(data) < 8 or data[0] != 0:  # echo reply only
                continue
            if data[8:] == payload:
                return t1 - t0
    except OSError:
        return None
    finally:
        s.close()


def tcp_ping(ip: str, port: int, *, timeout: float = 1.0) -> Optional[int]:
    """RTT in nanoseconds of a TCP connect, or None on timeout/refusal.

    1s default timeout matches the reference's ping timeout (the evaluator
    normalizes RTT against it, evaluator_network_topology.go:53-56).
    """
    t0 = time.monotonic_ns()
    try:
        with socket.create_connection((ip, port), timeout=timeout):
            return time.monotonic_ns() - t0
    except OSError:
        return None


def make_host_pinger(*, timeout: float = 1.0, prefer_icmp: bool = True):
    """ProbeAgent-shaped pinger: Host → rtt_ns | None.

    ICMP when the process can open an ICMP socket (checked once),
    else the TCP-connect fallback against the announced download port
    (it is the port peers actually fetch from)."""
    use_icmp = prefer_icmp and icmp_available()
    # Hosts that silently drop ICMP (firewall policy) would otherwise pay
    # the full ICMP timeout before EVERY TCP fallback, forever — memo the
    # first failure per ip and go straight to TCP afterwards.
    icmp_dead: set = set()

    def ping(host) -> Optional[int]:
        if not host.ip:
            return None
        if use_icmp and host.ip not in icmp_dead:
            rtt = icmp_ping(host.ip, timeout=timeout)
            if rtt is not None:
                return rtt
            icmp_dead.add(host.ip)
            # Unreachable by ICMP (filtered) — fall through to TCP.
        port = host.download_port or host.port
        if not port:
            return None
        return tcp_ping(host.ip, port, timeout=timeout)

    return ping
