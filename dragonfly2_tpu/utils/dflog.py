"""Leveled, per-concern rotating loggers (reference: internal/dflog).

The reference writes separate rotating files per concern (core, grpc, gc,
job, storage — logcore.go) with an optional ``--console`` override
(cmd/dependency).  ``setup()`` configures the same shape on the stdlib
logging tree: concern loggers are children of ``dragonfly.<concern>`` with
their own rotating file handlers.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
from typing import Dict, Optional

CONCERNS = ("core", "grpc", "gc", "job", "storage", "training")

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_configured: Dict[str, bool] = {}


def setup(
    *,
    level: str = "info",
    log_dir: Optional[str] = None,
    console: bool = False,
    max_bytes: int = 50 << 20,
    backups: int = 5,
    service: str = "dragonfly",
) -> None:
    """Configure the package logger tree. Idempotent per service.

    Handlers attach to the ``dragonfly2_tpu`` package tree — that is
    where every module logger (``logging.getLogger(__name__)``) actually
    lives.  Attaching to a logger named after the service ("trainer")
    captured NOTHING from the modules doing the work; ``service`` now
    only names the log files."""
    if _configured.get(service):
        return
    _configured[service] = True
    root = logging.getLogger("dragonfly2_tpu")
    root.setLevel(_LEVELS.get(level, logging.INFO))
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
    )
    if console or not log_dir:
        h = logging.StreamHandler()
        h.setFormatter(fmt)
        root.addHandler(h)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        core = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, f"{service}-core.log"),
            maxBytes=max_bytes,
            backupCount=backups,
        )
        core.setFormatter(fmt)
        root.addHandler(core)
        for concern in CONCERNS[1:]:
            lg = logging.getLogger(f"{service}.{concern}")
            fh = logging.handlers.RotatingFileHandler(
                os.path.join(log_dir, f"{service}-{concern}.log"),
                maxBytes=max_bytes,
                backupCount=backups,
            )
            fh.setFormatter(fmt)
            lg.addHandler(fh)


def get(concern: str = "core", service: str = "dragonfly") -> logging.Logger:
    if concern == "core":
        return logging.getLogger(service)
    return logging.getLogger(f"{service}.{concern}")
