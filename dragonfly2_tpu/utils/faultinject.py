"""Deterministic fault injection at the P2P control/data-plane seams.

The reference proves failure handling with e2e drills (test/e2e/), not
policy text.  This module is the layer those drills stand on: every
network-ish seam in the stack — the RPC transports
(rpc/scheduler_client, rpc/grpc_transport, rpc/_server), the piece
plane (rpc/piece_transport, daemon/upload), the manager StateBackend
(manager/state), the source clients (source/client) and the trainer's
dispatch loop — calls ``fire(site)`` on its hot path.  With no injector
installed that is one global read and a ``None`` compare; with one
installed, the scenario decides per call site and call index whether to
inject a fault.

Fault kinds:

- ``drop``      raise ``FaultInjected`` (a ``ConnectionError`` — the
                transports' retry class — so drops exercise the real
                retry/breaker/fallback machinery);
- ``delay``     sleep ``delay_s`` (stall, not failure: surfaces timeout
                and deadline bugs);
- ``dferror``   raise the typed ``utils.dferrors`` error for ``code``
                (the wire's retryable/terminal taxonomy);
- ``truncate``  cut a bytes payload to ``keep_bytes`` (torn body — the
                silent-corruption probe; seams that move bodies pass
                them through ``fire(site, payload=...)``);
- ``crash``     SIGKILL the CURRENT process (the drills' kill switch:
                a child process installs a scenario from the
                ``DF_FAULTINJECT`` env var and dies at a deterministic
                call index, no racy external kill timing).

Determinism contract: NO wall-clock randomness.  A spec triggers on
explicit per-site call indices (``at``), a modulus (``every``), or a
probability — and the probability coin is ``sha256(seed:spec:site:index)``,
so the same scenario seed replays the exact same fault sequence, call
for call.  ``FaultInjector.history`` records every injection for replay
assertions (tests/test_chaos.py proves same-seed ⇒ same-history).
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import os
import signal
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Tuple

ENV_VAR = "DF_FAULTINJECT"

KINDS = ("drop", "delay", "dferror", "truncate", "crash")


class FaultInjected(ConnectionError):
    """An injected 'drop': the call never reached the other side."""


@dataclass(frozen=True)
class FaultSpec:
    """One rule of a scenario: WHERE (site glob), WHAT (kind) and WHEN
    (explicit indices / modulus / deterministic probability)."""

    site: str                     # fnmatch glob over dotted site names
    kind: str                     # drop | delay | dferror | truncate | crash
    at: Tuple[int, ...] = ()      # explicit 0-based per-site call indices
    every: int = 0                # fire when site index % every == 0
    probability: float = 0.0      # seeded per-(site, index) coin
    delay_s: float = 0.0          # delay kind
    code: int = 14                # dferror kind (dferrors.Code; 14=UNAVAILABLE)
    keep_bytes: int = 0           # truncate kind: bytes kept
    max_fires: int = 0            # 0 = unlimited

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    def to_dict(self) -> dict:
        d = asdict(self)
        d["at"] = list(self.at)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        d = dict(d)
        d["at"] = tuple(d.get("at", ()))
        return cls(**d)


@dataclass
class Injection:
    """One fired fault — the replay-comparable history record."""

    site: str
    index: int    # per-site call index
    kind: str
    spec: int     # which rule fired

    def key(self) -> Tuple[str, int, str, int]:
        return (self.site, self.index, self.kind, self.spec)


class FaultInjector:
    """Scenario executor: per-site call counters + seeded decisions.

    Thread-safe; the decision for call N of a site depends only on
    (seed, rule order, site name, N), never on timing or interleaving —
    concurrent workers each see the deterministic fault for the index
    they drew.
    """

    def __init__(
        self,
        specs: List[FaultSpec],
        *,
        seed: int = 0,
        sleep=time.sleep,
        kill=None,
    ) -> None:
        self.specs = list(specs)
        self.seed = int(seed)
        self._sleep = sleep
        # Injectable for tests that assert crash scheduling without dying.
        self._kill = kill or (lambda: os.kill(os.getpid(), signal.SIGKILL))
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}
        self.history: List[Injection] = []

    # -- deterministic coin --------------------------------------------------

    def _coin(self, spec_idx: int, site: str, index: int) -> float:
        h = hashlib.sha256(
            f"{self.seed}:{spec_idx}:{site}:{index}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def _triggers(
        self, spec: FaultSpec, spec_idx: int, site: str, index: int
    ) -> bool:
        if not fnmatch.fnmatchcase(site, spec.site):
            return False
        if spec.at:
            return index in spec.at
        if spec.every:
            return index % spec.every == 0
        if spec.probability > 0.0:
            return self._coin(spec_idx, site, index) < spec.probability
        return False

    # -- the seam API --------------------------------------------------------

    def fire(self, site: str, payload=None):
        """Evaluate every rule for this call of ``site``.  Returns the
        (possibly truncated) payload; raises for drop/dferror; sleeps
        for delay; SIGKILLs for crash.  Multiple rules may stack on one
        call (e.g. delay THEN drop) — raising kinds end evaluation."""
        with self._mu:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
        for spec_idx, spec in enumerate(self.specs):
            if not self._triggers(spec, spec_idx, site, index):
                continue
            with self._mu:
                fired = self._fires.get(spec_idx, 0)
                if spec.max_fires and fired >= spec.max_fires:
                    continue
                self._fires[spec_idx] = fired + 1
                self.history.append(Injection(site, index, spec.kind, spec_idx))
            if spec.kind == "delay":
                self._sleep(spec.delay_s)
            elif spec.kind == "drop":
                raise FaultInjected(f"injected drop at {site}#{index}")
            elif spec.kind == "dferror":
                from .dferrors import Code, DfError, UnavailableError

                code = Code(spec.code)
                if code is Code.UNAVAILABLE:
                    raise UnavailableError(f"injected at {site}#{index}")
                raise DfError(f"injected at {site}#{index}", code=code)
            elif spec.kind == "truncate":
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    payload = bytes(payload)[: spec.keep_bytes]
            elif spec.kind == "crash":
                self._kill()
        return payload

    def call_count(self, site: str) -> int:
        with self._mu:
            return self._counts.get(site, 0)

    def history_keys(self) -> List[Tuple[str, int, str, int]]:
        with self._mu:
            return [inj.key() for inj in self.history]


# ---------------------------------------------------------------------------
# Process-global installation (the seams' fast path)
# ---------------------------------------------------------------------------

_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _active
    _active = injector
    return injector


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[FaultInjector]:
    return _active


def fire(site: str, payload=None):
    """The seam hook: a no-op passthrough unless an injector is installed."""
    inj = _active
    if inj is None:
        return payload
    return inj.fire(site, payload)


def truncates(site: str) -> bool:
    """True when the installed scenario carries a TRUNCATE rule that
    could match ``site``.  Zero-copy serve paths consult this: a torn-body
    fault needs a byte payload to cut, so its presence forces the
    buffered path (drop/delay/dferror/crash faults work on either)."""
    inj = _active
    if inj is None:
        return False
    return any(
        spec.kind == "truncate" and fnmatch.fnmatchcase(site, spec.site)
        for spec in inj.specs
    )


def targets(*sites: str) -> bool:
    """True when the installed scenario carries ANY rule that could match
    one of ``sites``.  The native fetch dispatch consults this: the
    in-engine loop cannot fire Python seams per piece, so a scenario
    aimed at the piece plane (``piece.fetch``, ``piece.fetch.body``,
    ``daemon.stream.tee``, ...) forces the byte-identical Python arm,
    keeping every chaos drill's faults biting (DESIGN.md §28)."""
    inj = _active
    if inj is None:
        return False
    return any(
        fnmatch.fnmatchcase(site, spec.site)
        for spec in inj.specs
        for site in sites
    )


class installed:
    """``with installed(injector): ...`` — scoped installation for tests."""

    def __init__(self, injector: FaultInjector) -> None:
        self.injector = injector

    def __enter__(self) -> FaultInjector:
        return install(self.injector)

    def __exit__(self, *exc) -> None:
        uninstall()


def install_from_env(env=None) -> Optional[FaultInjector]:
    """Install the scenario carried in ``DF_FAULTINJECT`` (JSON:
    ``{"seed": N, "faults": [FaultSpec dicts]}``).  Called by every CLI
    binary at boot so subprocess drills inject — and SIGKILL — at
    deterministic call indices with no external kill timing."""
    spec = (env if env is not None else os.environ).get(ENV_VAR)
    if not spec:
        return None
    data = json.loads(spec)
    return install(
        FaultInjector(
            [FaultSpec.from_dict(d) for d in data.get("faults", [])],
            seed=int(data.get("seed", 0)),
        )
    )
