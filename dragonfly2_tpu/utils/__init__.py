"""Shared kernel utilities (mirrors the reference's pkg/ + internal/ layer)."""
