"""Typed service errors (reference: internal/dferrors — gRPC-coded errors
the services use to signal retryable vs terminal conditions)."""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    """Wire-stable error codes (subset of the reference's dfcodes)."""

    OK = 0
    UNKNOWN = 1
    INVALID_ARGUMENT = 3
    NOT_FOUND = 5
    RESOURCE_EXHAUSTED = 8
    FAILED_PRECONDITION = 9
    UNAVAILABLE = 14
    SCHEDULE_FAILED = 1000
    NEED_BACK_TO_SOURCE = 1001
    PEER_GONE = 1002
    TASK_GONE = 1003


class DfError(Exception):
    code: Code = Code.UNKNOWN
    retryable: bool = False

    def __init__(self, message: str = "", *, code: Code | None = None):
        super().__init__(message or self.__class__.__name__)
        if code is not None:
            self.code = code


class NotFoundError(DfError):
    code = Code.NOT_FOUND


class InvalidArgumentError(DfError):
    code = Code.INVALID_ARGUMENT


class UnavailableError(DfError):
    code = Code.UNAVAILABLE
    retryable = True


class ResourceExhaustedError(DfError):
    code = Code.RESOURCE_EXHAUSTED
    retryable = True


class ScheduleFailedError(DfError):
    code = Code.SCHEDULE_FAILED


class NeedBackToSourceError(DfError):
    code = Code.NEED_BACK_TO_SOURCE


def is_retryable(exc: BaseException) -> bool:
    return isinstance(exc, DfError) and exc.retryable
