"""Dynamic lock witness: runtime validation of the static lock graph.

``tools/dflint/program.py`` derives the project's lock-ordering graph by
static analysis.  Static resolution can rot silently — a call-graph edge
the resolver misses removes lock edges from the graph without failing
anything.  This module closes that loop: in witness mode (installed by
``tests/conftest.py`` for the tier-1 run) every ``threading.Lock`` /
``RLock`` / ``Condition`` **created from project code** is wrapped in a
recording proxy.  Each thread keeps a stack of held locks; acquiring
lock B while holding lock A records the acquisition-order edge A→B,
keyed by the locks' *creation sites* ``(relpath, lineno)`` — exactly the
identity the static analyzer records for every ``threading.X()`` call,
so dynamic edges map 1:1 onto static lock classes.

The tier-1 cross-check (``tests/test_zz_lockwitness.py``) then asserts
that every dynamically-observed edge exists in the statically-derived
graph: a dynamic edge with no static counterpart means the resolver has
a blind spot (test failure, not silent rot).

Design constraints:

- **foreign locks are untouched** — the factory wraps only when the
  creating frame's file lives under the package root; jax, logging,
  queue, Event internals keep raw primitives and zero overhead;
- **Condition waits are modeled exactly** — a no-arg ``Condition`` gets
  a proxied RLock as its backing lock, and the proxy hides
  ``_release_save``/``_acquire_restore`` so ``Condition.wait`` releases
  and re-acquires through the recording ``release()``/``acquire()``
  path (the held-stack correctly drops the lock while waiting);
- **recording failure never breaks locking** — the proxy's bookkeeping
  is wrapped defensively; the underlying primitive's semantics are
  delegated untouched.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

Site = Tuple[str, int]          # (repo-relative path, lineno) of the creation call
EdgeKey = Tuple[Site, Site]


class LockWitness:
    """Global edge recorder shared by every proxy."""

    def __init__(self, package_dir: str) -> None:
        self.package_dir = os.path.abspath(package_dir)
        self.repo_root = os.path.dirname(self.package_dir)
        self._mu = _REAL_LOCK()
        self._local = threading.local()
        # edge -> description of the first observation (thread + location)
        self.edges: Dict[EdgeKey, str] = {}
        self.sites: Set[Site] = set()

    # -- creation-site capture ----------------------------------------------

    def site_of_frame(self, frame) -> Optional[Site]:
        filename = os.path.abspath(frame.f_code.co_filename)
        if not filename.startswith(self.package_dir + os.sep):
            return None
        rel = os.path.relpath(filename, self.repo_root).replace(os.sep, "/")
        return (rel, frame.f_lineno)

    # -- held-stack bookkeeping ---------------------------------------------

    def _stack(self) -> List[Site]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def note_acquire(self, site: Site) -> None:
        st = self._stack()
        if st:
            new = [
                (held, site) for held in dict.fromkeys(st)
                if (held, site) not in self.edges
            ]
            if new:
                frame = sys._getframe(2)
                where = (
                    f"{threading.current_thread().name} at "
                    f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
                )
                with self._mu:
                    for key in new:
                        self.edges.setdefault(key, where)
        st.append(site)

    def note_release(self, site: Site) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == site:
                del st[i]
                return

    def snapshot_edges(self) -> Dict[EdgeKey, str]:
        with self._mu:
            return dict(self.edges)

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()


class _WitnessProxy:
    """Records acquire/release around a real Lock/RLock; everything else
    (``locked``, ``_is_owned``, …) delegates to the primitive.
    ``_release_save``/``_acquire_restore`` are deliberately HIDDEN so a
    ``Condition`` backed by this proxy falls back to plain
    ``release()``/``acquire()`` during ``wait()`` — keeping the recorded
    held-stack exact across waits."""

    __slots__ = ("_inner", "_site", "_w")

    def __init__(self, inner, site: Site, witness: LockWitness) -> None:
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_site", site)
        object.__setattr__(self, "_w", witness)

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            try:
                self._w.note_acquire(self._site)
            except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the lock itself IS acquired and a raise here would corrupt callers' locking
                pass
        return got

    def release(self):
        self._inner.release()
        try:
            self._w.note_release(self._site)
        except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; the lock is already released and a raise here would corrupt callers' locking
            pass

    def __enter__(self):
        return self.acquire()

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        if name in ("_release_save", "_acquire_restore"):
            # Force threading.Condition onto the recording fallback path.
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __repr__(self):  # pragma: no cover — debugging aid
        return f"<dflock proxy {self._site[0]}:{self._site[1]} of {self._inner!r}>"


_installed: Optional[LockWitness] = None


def witness() -> Optional[LockWitness]:
    return _installed


def _default_package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def install(package_dir: Optional[str] = None) -> LockWitness:
    """Patch the ``threading`` factories with site-aware wrappers.
    Idempotent; returns the active witness."""
    global _installed
    if _installed is not None:
        return _installed
    w = LockWitness(package_dir or _default_package_dir())

    def make_lock():
        site = w.site_of_frame(sys._getframe(1))
        inner = _REAL_LOCK()
        if site is None:
            return inner
        w.sites.add(site)
        return _WitnessProxy(inner, site, w)

    def make_rlock():
        site = w.site_of_frame(sys._getframe(1))
        inner = _REAL_RLOCK()
        if site is None:
            return inner
        w.sites.add(site)
        return _WitnessProxy(inner, site, w)

    def make_condition(lock=None):
        site = w.site_of_frame(sys._getframe(1))
        if site is None:
            return _REAL_CONDITION(lock)
        if lock is None:
            # Same default as stock Condition (an RLock), but proxied so
            # enter/exit/wait record against THIS creation site.
            w.sites.add(site)
            lock = _WitnessProxy(_REAL_RLOCK(), site, w)
        # An explicit lock is (usually) already a proxy recording against
        # its own creation site — Condition acquisitions alias it, which
        # matches the static analyzer's Condition(wrapped-lock) model.
        return _REAL_CONDITION(lock)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition
    _installed = w
    return w


def uninstall() -> None:
    """Restore the stock factories (existing proxies keep working)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = None
