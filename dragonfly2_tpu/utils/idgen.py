"""Deterministic ID generation (reference: pkg/idgen/*.go).

IDs are stable hashes so every service derives the same identity for the
same entity without coordination:

- host ID v1:  ``<hostname>-<port>``          (pkg/idgen/host_id.go:26-28)
- host ID v2:  sha256(ip, hostname)           (pkg/idgen/host_id.go:31-33)
- task ID:     sha256 over filtered URL + digest + range + tag + application
               (pkg/idgen/task_id.go:60-95)
- peer ID:     ``<ip>-<hostname>-<random>-<suffix>``
- model ID:    sha256(ip, hostname, model name) (pkg/idgen/model_id.go:31-39)
"""

from __future__ import annotations

import urllib.parse
import uuid
from dataclasses import dataclass, field
from typing import Sequence

from .digest import sha256_from_strings


@dataclass(frozen=True)
class URLMeta:
    """Subset of the wire URL metadata that keys a task (common.UrlMeta)."""

    digest: str = ""
    tag: str = ""
    range: str = ""
    filtered_query_params: Sequence[str] = field(default_factory=tuple)
    application: str = ""
    priority: int = 0


def host_id_v1(hostname: str, port: int) -> str:
    return f"{hostname}-{port}"


def host_id_v2(ip: str, hostname: str, seed_peer: bool = False) -> str:
    if seed_peer:
        return sha256_from_strings(ip, hostname, "seed")
    return sha256_from_strings(ip, hostname)


def _filter_query_params(url: str, filtered: Sequence[str]) -> str:
    """Drop the named query params and sort the rest for a canonical URL.

    With no params to filter the raw URL is returned unchanged, so
    ``task_id(url)`` and ``task_id(url, URLMeta())`` agree (the reference's
    FilterQueryParams is likewise a no-op on an empty filter list,
    pkg/net/url/url.go:24-27 — canonicalization only kicks in when
    filtering already rewrites the query).
    """
    if not any(f.strip() for f in filtered):
        return url
    try:
        parts = urllib.parse.urlsplit(url)
        query = urllib.parse.parse_qsl(parts.query, keep_blank_values=True)
        drop = {f.strip() for f in filtered if f.strip()}
        kept = sorted((k, v) for k, v in query if k not in drop)
        return urllib.parse.urlunsplit(
            parts._replace(query=urllib.parse.urlencode(kept))
        )
    except ValueError:
        return ""


def task_id(url: str, meta: URLMeta | None = None, *, ignore_range: bool = False) -> str:
    """Task identity: same content fetched the same way ⇒ same swarm."""
    if meta is None:
        return sha256_from_strings(url)
    data = [_filter_query_params(url, meta.filtered_query_params)]
    if meta.digest:
        data.append(meta.digest)
    if not ignore_range and meta.range:
        data.append(meta.range)
    if meta.tag:
        data.append(meta.tag)
    if meta.application:
        data.append(meta.application)
    return sha256_from_strings(*data)


def parent_task_id(url: str, meta: URLMeta | None = None) -> str:
    """Task ID ignoring byte range — keys the whole-file parent of a ranged task."""
    return task_id(url, meta, ignore_range=True)


def cache_task_id(path: str, tag: str = "", application: str = "") -> str:
    data = [path]
    if tag:
        data.append(tag)
    if application:
        data.append(application)
    return sha256_from_strings(*data)


def peer_id(ip: str, hostname: str, *, seed: bool = False) -> str:
    suffix = "seed" if seed else "normal"
    return f"{ip}-{hostname}-{uuid.uuid4().hex}-{suffix}"


def model_id(ip: str, hostname: str, name: str) -> str:
    return sha256_from_strings(ip, hostname, name)


def model_version_id(data: bytes) -> str:
    import hashlib

    return hashlib.sha256(data).hexdigest()[:16]
