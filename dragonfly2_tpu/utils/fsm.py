"""Minimal finite-state machine (reference dependency: looplab/fsm).

The scheduler's Peer/Task/Host resources gate every lifecycle transition
through an FSM (scheduler/resource/peer.go:52-110, task.go:57-85) so that
races between streams can't produce illegal states.  This is the same
event/transition model: named events, each with a set of legal source
states and one destination state, plus optional callbacks.

Thread-safe: transitions take a lock; an illegal event raises
InvalidEventError rather than silently corrupting state.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple


class FSMError(Exception):
    pass


class InvalidEventError(FSMError):
    def __init__(self, event: str, state: str):
        super().__init__(f"event {event!r} inappropriate in current state {state!r}")
        self.event = event
        self.state = state


@dataclass(frozen=True)
class EventDesc:
    name: str
    src: Sequence[str]
    dst: str


class FSM:
    def __init__(
        self,
        initial: str,
        events: Iterable[EventDesc],
        callbacks: Optional[Dict[str, Callable[["FSM", str, str, str], None]]] = None,
    ) -> None:
        """callbacks keys: ``enter_<state>``, ``after_<event>``, or ``enter_state``."""
        self._mu = threading.RLock()
        self._state = initial
        self._transitions: Dict[Tuple[str, str], str] = {}
        for e in events:
            for src in e.src:
                self._transitions[(e.name, src)] = e.dst
        self._callbacks = dict(callbacks or {})

    @property
    def current(self) -> str:
        with self._mu:
            return self._state

    def is_(self, state: str) -> bool:
        return self.current == state

    def can(self, event: str) -> bool:
        with self._mu:
            return (event, self._state) in self._transitions

    def event(self, name: str) -> None:
        with self._mu:
            key = (name, self._state)
            dst = self._transitions.get(key)
            if dst is None:
                raise InvalidEventError(name, self._state)
            src = self._state
            self._state = dst
            cbs = []
            for cb_key in (f"enter_{dst}", f"after_{name}", "enter_state"):
                cb = self._callbacks.get(cb_key)
                if cb is not None:
                    cbs.append(cb)
        for cb in cbs:
            cb(self, name, src, dst)

    def set_state(self, state: str) -> None:
        with self._mu:
            self._state = state
