"""Prometheus-style metrics registry (reference: scheduler/metrics/,
trainer/metrics/, grpc_prometheus interceptors).

Counters/gauges/histograms with label support and text exposition
(Prometheus format), dependency-free.  Services define their metric sets
at module scope the way the reference does (metrics.go:44-180).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (exposition-format spec).  Without this a hostile
    label value (a URL with a quote, a multi-line error string) splits
    the sample line and corrupts every series after it in the scrape."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """# HELP text escaping: backslash and newline only (quotes are legal
    in help text per the exposition format)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _current_trace_id() -> Optional[str]:
    """Active trace id on this thread (exemplar hook): one thread-local
    read through the tracer — cheap enough for per-observe use."""
    from .tracing import current_trace_id

    return current_trace_id()


class _Metric:
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._mu = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        # Hot path (per-observe): equal length + every name present is
        # equivalent to set equality without building two sets per call.
        names = self.label_names
        if len(labels) == len(names):
            try:
                return tuple([labels[n] for n in names])
            except KeyError:
                pass
        raise ValueError(
            f"{self.name}: labels {sorted(labels)} != {sorted(self.label_names)}"
        )

    def _fmt_labels(self, key: Tuple[str, ...]) -> str:
        if not key:
            return ""
        inner = ",".join(
            f'{n}="{_escape_label_value(v)}"'
            for n, v in zip(self.label_names, key)
        )
        return "{" + inner + "}"


class _CounterChild:
    """Label-bound counter handle: the per-call kwargs-dict build and
    label validation are paid ONCE at bind time — serving hot paths
    (scheduler featcache/evaluator) observe through these."""

    __slots__ = ("_metric", "_key_t")

    def __init__(self, metric: "Counter", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key_t = key

    def inc(self, amount: float = 1.0) -> None:
        m = self._metric
        with m._mu:
            m._values[self._key_t] = m._values.get(self._key_t, 0.0) + amount


class Counter(_Metric):
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def labels(self, **labels: str) -> _CounterChild:
        return _CounterChild(self, self._key(labels))

    def value(self, **labels: str) -> float:
        with self._mu:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} counter"]
        with self._mu:
            for key, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._mu:
            self._values[self._key(labels)] = value

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._mu:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} gauge"]
        with self._mu:
            for key, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(key)} {v}")
        return out


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class _HistogramChild:
    """Label-bound histogram handle (see _CounterChild).  Caches the
    per-key bucket-count list so a hot-path observe is one bisect + one
    locked region of three list/dict ops."""

    __slots__ = ("_metric", "_key_t", "_counts")

    def __init__(self, metric: "Histogram", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key_t = key
        self._counts = None

    def observe(self, value: float) -> None:
        m = self._metric
        idx = bisect.bisect_left(m.buckets, value)
        key = self._key_t
        tid = _current_trace_id()
        with m._mu:
            counts = self._counts
            if counts is None:
                counts = m._counts.get(key)
                if counts is None:
                    counts = m._counts[key] = [0] * len(m.buckets)
                self._counts = counts
            if idx < len(counts):
                counts[idx] += 1
            m._sums[key] = m._sums.get(key, 0.0) + value
            m._totals[key] = m._totals.get(key, 0) + 1
            if tid is not None:
                m._exemplars.setdefault(key, {})[idx] = tid


class Histogram(_Metric):
    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}
        # Exemplars: last trace id observed per (key, bucket) — recorded
        # under the existing metric lock (one dict store when a span is
        # active, nothing otherwise), exposed as /debug/exemplars JSON so
        # a slow-bucket latency joins to its flight-recorder trace.
        self._exemplars: Dict[Tuple[str, ...], Dict[int, str]] = {}

    def observe(self, value: float, **labels: str) -> None:
        # Counts are stored PER-BUCKET (one increment per observe) and
        # cumulated at expose time — the cumulative-update loop over the
        # bucket ladder showed up on the scheduler's per-announce path.
        self._observe_key(self._key(labels), value)

    def _observe_key(self, key: Tuple[str, ...], value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        tid = _current_trace_id()
        with self._mu:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * len(self.buckets)
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if tid is not None:
                self._exemplars.setdefault(key, {})[idx] = tid

    def labels(self, **labels: str) -> "_HistogramChild":
        return _HistogramChild(self, self._key(labels))

    def exemplars(self) -> Dict[str, Dict[str, str]]:
        """``{label-set: {le: trace_id}}`` — the last trace id observed
        per bucket (``le`` is the bucket's upper bound, ``+Inf`` for the
        overflow bucket)."""
        with self._mu:
            snap = {k: dict(v) for k, v in self._exemplars.items()}
        out: Dict[str, Dict[str, str]] = {}
        for key, per_bucket in snap.items():
            label_str = self._fmt_labels(key) or "{}"
            out[label_str] = {
                (str(self.buckets[i]) if i < len(self.buckets) else "+Inf"): tid
                for i, tid in sorted(per_bucket.items())
            }
        return out

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} histogram"]
        with self._mu:
            for key, counts in sorted(self._counts.items()):
                base = self._fmt_labels(key)[1:-1] if key else ""
                running = 0
                for le, c in zip(self.buckets, counts):
                    running += c
                    sep = "," if base else ""
                    out.append(f'{self.name}_bucket{{{base}{sep}le="{le}"}} {running}')
                sep = "," if base else ""
                out.append(f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {self._totals[key]}')
                lbl = "{" + base + "}" if base else ""
                out.append(f"{self.name}_sum{lbl} {self._sums[key]}")
                out.append(f"{self.name}_count{lbl} {self._totals[key]}")
        return out


class Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def counter(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help, label_names))

    def gauge(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, label_names))

    def histogram(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram(name, help, label_names, buckets))

    def _register(self, metric):
        with self._mu:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(f"metric {metric.name} re-registered as different type")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def expose_text(self) -> str:
        with self._mu:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def exemplars(self) -> Dict[str, Dict[str, Dict[str, str]]]:
        """Every histogram's per-bucket exemplars (``/debug/exemplars``):
        {metric: {label-set: {le: trace_id}}}, empty sets omitted."""
        with self._mu:
            metrics = list(self._metrics.values())
        out: Dict[str, Dict[str, Dict[str, str]]] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                ex = m.exemplars()
                if ex:
                    out[m.name] = ex
        return out


# Process-default registry (services may create their own for isolation).
default_registry = Registry()
